"""DataStore: the top seam — schema CRUD, writes, queries (GeoTools role).

Reference: ``GeoMesaDataStore`` (``geomesa-index-api/.../geotools/
GeoMesaDataStore.scala:49``) + ``QueryPlanner.runQuery`` (SURVEY.md §3.3).
Host-side orchestration: schemas and the canonical columnar tables live here;
each write rebuilds index permutations and backend device state (bulk-load
semantics v1 — the streaming LSM delta tier is the lambda-pattern follow-up,
SURVEY.md §2.11).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.analysis.contracts import (
    cache_surface,
    choreography_boundary,
    dispatch_budget,
    feedback_sink,
    mutation,
)
from geomesa_tpu.filter import ast
from geomesa_tpu.index.api import FeatureIndex
from geomesa_tpu.planning.planner import Query, QueryPlanner, build_indices
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType, parse_spec
from geomesa_tpu.store.backends import ExecutionBackend, OracleBackend, TpuBackend

if TYPE_CHECKING:
    from geomesa_tpu.store.bufferpool import BufferPool

_BACKENDS = {"oracle": OracleBackend, "tpu": TpuBackend}


def _ttl_cutoff_ms(ttl_ms: int, now_ms: int | None = None) -> int:
    """THE age-off cutoff: rows with dtg >= cutoff are live. One definition
    shared by the query-time mask, the mesh aggregation mask, and physical
    age_off(), so the three can never drift."""
    import time as _time

    return (int(_time.time() * 1000) if now_ms is None else now_ms) - ttl_ms


# hints that are pure execution METADATA (attribution, never semantics):
# they change neither results nor the execution contract, so the batched
# and cached paths must not decline on them — the serving coalescer
# (serving/coalesce.py) stamps "tenant" on every query it batches so a
# shared dispatch meters each member query against ITS tenant
_METADATA_HINTS = frozenset({"tenant"})


def _semantic_hints(q) -> bool:
    """True when the query carries hints that can alter results or the
    execution contract (everything except the metadata set above)."""
    return any(k not in _METADATA_HINTS for k in q.hints)


def _pure_bbox_time(f: ast.Filter, sft: FeatureType) -> bool:
    """True when the filter is a conjunction of spatial-box/temporal
    primaries on the schema's DEFAULT geometry/date fields — fully
    expressible as int-domain (boxes, windows) with no residual, so the
    batched loose count covers it. Predicates on other attributes (which
    ``bounds.extract`` would silently treat as unconstrained) disqualify."""
    if isinstance(f, ast.Include):
        return True
    if isinstance(f, ast.BBox):
        return f.prop == sft.geom_field
    if isinstance(f, (ast.During, ast.TempOp)):
        return f.prop == sft.dtg_field
    if isinstance(f, ast.And):
        return all(_pure_bbox_time(c, sft) for c in f.children)
    return False


@dataclass
class QueryResult:
    """Materialized query result + plan trace + optional aggregates.

    Aggregates mirror the reference's push-down scan flavors (SURVEY.md §2.3):
    ``density`` (DensityScan grid), ``stats`` (StatsScan sketches), and
    ``bin_data`` (BinAggregatingScan byte stream).
    """

    table: FeatureTable
    row_ids: np.ndarray
    plan_info: Any = None
    density: np.ndarray | None = None  # (height, width) f64 weighted counts
    stats: dict | None = None  # label -> sketch
    bin_data: bytes | None = None
    # federation partial-results marker (MergedDataStoreView in `partial`
    # mode): True when one or more members were skipped; member_errors
    # carries (member_index, exception_type, message) per skipped member
    degraded: bool = False
    member_errors: list | None = None

    @property
    def count(self) -> int:
        return len(self.table)

    def records(self) -> list[dict]:
        return [self.table.record(i) for i in range(len(self.table))]


@dataclass
class ExplainAnalyze:
    """``EXPLAIN ANALYZE`` output: the static plan text plus the measured
    per-stage timeline (:class:`geomesa_tpu.obs.StageTimeline`) of one real
    execution. ``stages`` durations sum to ``wall_ms`` by construction (an
    ``other`` residual stage absorbs untraced time). ``device`` is the
    devprof attribution of the analyzed run (compile / dispatch /
    device-compute / h2d / d2h, ms + bytes); ``cost`` is the cost table's
    predicted-vs-actual for this plan shape (predicted is the table's p50
    BEFORE this run observed into it)."""

    plan: str
    timeline: Any
    hits: int
    device: "dict | None" = None
    cost: "dict | None" = None
    # buffer-pool / query-cache gauges at analyze time (hit/miss/eviction
    # counters + pyramid bytes — DataStore.cache_report)
    cache: "dict | None" = None
    # correctness-audit verdict for the analyzed execution (obs/audit.py:
    # pass / diverged / abstained), present when auditing is enabled —
    # the analyzed query runs with the "audit" hint and the auditor
    # drains synchronously so the verdict is available here
    audit: "dict | None" = None

    @property
    def stages(self) -> list:
        return self.timeline.stages

    @property
    def wall_ms(self) -> float:
        return self.timeline.wall_ms

    def __str__(self) -> str:
        out = f"{self.plan}\n{self.timeline.render()}"
        if self.device:
            out += "\n  Device time:"
            for k in ("compile", "dispatch", "device_compute", "h2d", "d2h"):
                out += f"\n    {k:<15s} {self.device.get(k, 0.0):10.3f} ms"
            out += (f"\n    transfers       h2d {self.device.get('h2d_bytes', 0)} B"
                    f" / d2h {self.device.get('d2h_bytes', 0)} B"
                    f" ({self.device.get('dispatches', 0)} dispatches)")
        if self.cost:
            pred = self.cost.get("predicted")
            pred_ms = pred.get("wall_ms_p50") if pred else None
            out += (
                f"\n  Cost profile [{self.cost.get('signature')}]: "
                + (f"predicted {pred_ms} ms p50 "
                   f"(n={pred.get('observations')})" if pred
                   else "no prior observations")
                + f", actual {self.cost.get('actual_ms')} ms"
            )
            cal = self.cost.get("calibration_error")
            if cal is not None:
                out += f" (calibration error {cal:.1%})"
            src = self.cost.get("strategy_source")
            if src:
                out += f"\n  Strategy source: {src}"
            for alt in self.cost.get("alternatives") or []:
                obs_txt = (
                    f"observed {alt['observed_ms_p50']} ms p50"
                    f" (n={alt['observations']})"
                    if alt.get("observed_ms_p50") is not None
                    else "no observations"
                )
                out += (
                    f"\n  Rejected: {alt['name']} ≈ "
                    f"{alt['est_rows']:.0f} rows, {obs_txt}"
                )
        if self.cache:
            ac = self.cache.get("agg_cache") or {}
            pool = self.cache.get("pool") or {}
            out += (
                f"\n  Cache: agg hits {ac.get('hits', 0)} / misses "
                f"{ac.get('misses', 0)} / evictions "
                f"{ac.get('evictions', 0)}; pool hits "
                f"{pool.get('hits', 0)} / misses {pool.get('misses', 0)}"
                f" / evictions {pool.get('evictions', 0)}"
            )
            pb = self.cache.get("pyramid_bytes") or {}
            if pb:
                out += "; pyramid bytes " + ", ".join(
                    f"{t}={b}" for t, b in sorted(pb.items()))
        if self.audit:
            out += (f"\n  Audit: {self.audit.get('verdict')} "
                    f"({self.audit.get('kind')}"
                    + (f": {self.audit['detail']}"
                       if self.audit.get("detail") else "")
                    + ")")
        return out + f"\n  Hits: {self.hits}"


# both derived-data caches ride this state object: the plan cache is
# valid only for the current `indices` object (identity-checked on every
# lookup/insert), the pyramids only for the current data epoch — and the
# epoch IS monotonic within one _TypeState lifetime (both caches die
# with the object, so the delete+recreate restart cannot serve them)
@cache_surface(name="plan-cache", keyed_by="indices-identity",
               purge=("purge_derived",))
@cache_surface(name="agg-pyramids", keyed_by="epoch", epoch="monotonic",
               purge=("purge_derived",))
@dataclass
class _TypeState:
    sft: FeatureType
    table: FeatureTable | None = None  # main (sorted, device-resident) tier
    indices: dict[str, FeatureIndex] = field(default_factory=dict)
    backend_state: Any = None
    stats: Any = None  # StoreStats
    delta: Any = None  # DeltaTier (hot append buffer)
    fid_seq: int = 0  # monotonic sequential-fid allocator (under `lock`)
    # main-tier rebuild epoch: bumps on every state swap (compact, delete,
    # age-off, evolution). With the delta tier's mutation version it forms
    # the DATA EPOCH stamped on cached aggregates (ops/geoblocks.py) and
    # the buffer pool's donation fingerprint — delta-only writes bump the
    # version but not this, so donated main-tier buffers stay reusable
    epoch: int = 0
    # durability plane (store/wal.py): seq of the last WAL record whose
    # effect is in this in-memory state (updated under `lock` with the
    # apply; checkpoint stamps read it under `wal_lock`), and a per-state
    # identity so an incremental checkpoint can never reuse a manifest
    # entry across a delete+recreate of the same type name (the epoch
    # tuple restarts at the same values there)
    wal_seq: int = 0
    ident: str = ""

    def __post_init__(self):
        if self.delta is None:
            from geomesa_tpu.store.delta import DeltaTier

            self.delta = DeltaTier()
        # plan cache (the reference's SoftThreadLocal plan caches,
        # QueryPlanner.scala:160): (filter text, forced index) → planned
        # (IndexPlan, residual AST, info). Entries are valid for the
        # CURRENT `indices` object only — every state swap clears it, and
        # both lookup and insert verify `st.indices is <snapshot indices>`
        # under `lock`, so a stale plan can never pair with fresh indices
        from collections import OrderedDict

        self.plan_cache: OrderedDict = OrderedDict()
        # GeoBlocks pre-aggregation pyramids, one per (group_by tuple,
        # value_cols tuple): immutable, stamped with the data epoch at
        # build time, dropped wholesale on every rebuild (under `lock`)
        self.pyramids: dict = {}
        import threading

        # `lock` guards the coherent (table, indices, backend_state, stats,
        # delta) swap vs concurrent readers — a background persister (lambda
        # role) compacts while queries run, and a reader must never pair a
        # new table with old index permutations. `mutate_lock` serializes the
        # MUTATION pipelines end-to-end (compact / delete / age-off / schema
        # evolution / recover): last-writer-wins swaps between concurrent
        # mutators would otherwise lose updates.
        self.lock = threading.RLock()
        self.mutate_lock = threading.RLock()
        # WAL ordering guard — held across (apply + WAL append) so the
        # per-type journal's seq order always equals the apply order, and
        # by the checkpointer while stamping this type's applied seq.
        # Hierarchy: wal_lock > mutate_lock > lock (docs/concurrency.md).
        self.wal_lock = threading.RLock()
        if not self.ident:
            import uuid

            self.ident = uuid.uuid4().hex

    def purge_derived(self) -> None:
        """Drop BOTH derived-data caches (plan cache + GeoBlocks
        pyramids) — the one invalidation point every state swap calls
        under ``lock``. The declared purge target of the ``plan-cache``
        and ``agg-pyramids`` cache surfaces above: keeping the two
        ``clear()`` calls in one place is what lets the ``--flow`` F001
        pass prove every mutation path reaches them."""
        # every caller swaps state under `lock`; the helper exists so the
        # two clears cannot drift apart, not to introduce a lock scope
        # tpurace: disable-next-line=R001
        self.plan_cache.clear()
        # tpurace: disable-next-line=R001
        self.pyramids.clear()

    def snapshot(self):
        """Coherent read of the query-relevant state (one lock hold)."""
        with self.lock:
            return (
                self.table,
                self.indices,
                self.backend_state,
                self.stats,
                self.delta.merged(),
            )

    def data_epoch(self) -> tuple:
        """The (rebuild epoch, delta version) pair every mutation advances
        monotonically. Cache users MUST read this BEFORE taking the data
        snapshot they compute from: a mutation racing the computation then
        stamps the entry with a pair that never recurs — a guaranteed
        future MISS, never a stale hit."""
        with self.lock:
            return (self.epoch, self.delta.version)

    def consume_snapshot(self):
        """Mutator-side snapshot: state + the number of delta tables the
        mutation will consume (call ONLY with ``mutate_lock`` held)."""
        with self.lock:
            return (
                self.table,
                self.indices,
                self.delta.merged(),
                len(self.delta.tables),
            )

    @property
    def main_rows(self) -> int:
        return 0 if self.table is None else len(self.table)

    @property
    def total_rows(self) -> int:
        return self.main_rows + self.delta.rows


@choreography_boundary
class DataStore:
    """An in-process spatio-temporal datastore over a pluggable backend.

    ``audit_writer`` (an :class:`~geomesa_tpu.utils.audit.AuditWriter`) records
    a ``QueryEvent`` per query; ``metrics`` (a
    :class:`~geomesa_tpu.utils.metrics.MetricsRegistry`) accumulates
    query/write counters and timings; ``user`` tags audit records.

    The facade is the sanctioned stage-orchestration layer
    (``@choreography_boundary``, tpusync): per-query routing and
    fallback loops in here are host choreography BY DESIGN, and callers
    are charged zero static dispatch cost for calling in. The batched
    entry points below carry their own ``@dispatch_budget`` contracts,
    which opt them back into the S001 worst-case check — those bounds
    (and the runtime ledger's measured rates, via ``--sync
    --reconcile``) are where the fusion guarantees live.
    """

    def __init__(
        self,
        backend: str | ExecutionBackend = "tpu",
        audit_writer=None,
        metrics=None,
        user: str = "unknown",
        wal_dir: str | None = None,
    ):
        if isinstance(backend, str):
            backend = _BACKENDS[backend]()
        self.backend = backend
        self._types: dict[str, _TypeState] = {}
        self.audit_writer = audit_writer
        self.user = user
        if metrics is None:
            from geomesa_tpu.utils.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        # SLO engine (docs/observability.md § SLOs): one availability/
        # latency observation per completed or timed-out query, exposed
        # as burn-rate gauges on GET /api/metrics?format=prometheus
        from geomesa_tpu.obs.slo import SloEngine

        self.slo = SloEngine()
        self.slo.objective("store.query", target=0.999)
        # GeoBlocks query cache (ops/geoblocks.py): exact-repeat grouped
        # aggregations served straight from cache, epoch-validated so a
        # write can never leave a stale answer servable
        from geomesa_tpu.ops.geoblocks import QueryCache

        self.agg_cache = QueryCache()
        from geomesa_tpu.utils import timeouts as _timeouts
        from geomesa_tpu.utils.timeouts import Watchdog

        self.watchdog = Watchdog()
        # thread-exhaustion signal, sampled live at metrics snapshot time
        self.metrics.gauge("store.query.abandoned_running").fn = (
            _timeouts.abandoned_running
        )
        import threading

        # atomic schema-catalog mutation (create/delete/rename): a threaded
        # REST server creates schemas concurrently
        self._schema_lock = threading.Lock()
        # (scope type-name | None, fn(sft, query) -> query) pairs
        self._interceptors: list[tuple[str | None, Any]] = []
        # device-failure circuit breaker (failure detection/recovery, SURVEY
        # §5: the reference delegates to the backing store's failover; here
        # the host columnar table IS the replica, so a dead device degrades
        # to exact host scans instead of failing queries)
        self._device_down_until: float = 0.0
        # durability plane (store/wal.py; docs/operations.md § Durability
        # & recovery): with GEOMESA_TPU_WAL (or wal_dir=) every mutating
        # op journals before it acks; DataStore.open(recover=True) replays
        # the tail over the last checkpoint
        self._wal = None
        self._wal_replay = False  # recovery/load applies without journaling
        self._wal_schema_seq = 0  # last APPLIED schema-op seq (schema_lock)
        self._wal_catalog: str | None = None
        self._wal_ckpt = None
        self._wal_unreplayed = False
        if wal_dir is None:
            wal_dir = os.environ.get("GEOMESA_TPU_WAL") or None
        if wal_dir:
            from geomesa_tpu.store.wal import WriteAheadLog

            self._wal = WriteAheadLog(wal_dir)
            # attaching over a journal with RETAINED records without
            # replaying them (DataStore.open does; a plain construct —
            # e.g. GEOMESA_TPU_WAL ambient on a CLI load — does not)
            # must not mutate or checkpoint: a save would trim, and new
            # stamps would shadow, acked history that was never applied.
            # open() clears the flag once the tail is accounted for.
            self._wal_unreplayed = self._wal.has_records()

    # -- failure detection / recovery -----------------------------------------
    DEVICE_BACKOFF_S = 30.0  # circuit stays open this long after a failure

    @staticmethod
    def _is_device_error(e: BaseException) -> bool:
        """Errors that mean 'the accelerator path died', not 'bad query'.

        jax/jaxlib-raised errors and connection failures qualify outright;
        bare RuntimeError/OSError only with a device-flavored message, so a
        host-side logic bug can't masquerade as an outage and hide behind
        the (correct but slow) brute-force fallback.
        """
        mod = type(e).__module__ or ""
        if mod.startswith(("jax", "jaxlib")):
            return True
        if isinstance(e, (ConnectionError, TimeoutError)):
            return True
        if isinstance(e, (RuntimeError, OSError)):
            msg = str(e).lower()
            return any(
                s in msg
                for s in (
                    "unavailable", "deadline", "backend", "device", "tunnel",
                    "axon", "tpu", "transfer", "connection", "socket",
                )
            )
        return False

    def _device_available(self) -> bool:
        import time as _time

        return _time.monotonic() >= self._device_down_until

    def _trip_device_circuit(self, e: BaseException) -> None:
        import time as _time

        self._device_down_until = _time.monotonic() + self.DEVICE_BACKOFF_S
        self.metrics.gauge("store.device.circuit_open").set(1.0)

    def _note_device_ok(self) -> None:
        """A device path just succeeded: close a half-open circuit."""
        if self._device_down_until:
            self._device_down_until = 0.0
            self.metrics.gauge("store.device.circuit_open").set(0.0)

    def recover(self, type_name: str | None = None) -> bool:
        """Close the device circuit and rebuild device-resident state.

        Call after an accelerator outage (or let the circuit's backoff probe
        recover lazily). Returns True when device state reloaded cleanly.
        """
        self._device_down_until = 0.0
        self.metrics.gauge("store.device.circuit_open").set(0.0)
        names = [type_name] if type_name else list(self._types)
        ok = True
        for name in names:
            st = self._types[name]
            # mutate_lock: a compaction swapping state mid-load would leave
            # residency for a table that is no longer current
            with st.mutate_lock:
                with st.lock:
                    table, indices, epoch = st.table, st.indices, st.epoch
                if table is None:
                    continue
                try:
                    # same main tier → same fingerprint: buffers a pool-
                    # pressure eviction donated re-admit without staging
                    loaded = self.backend.load(
                        st.sft, table, indices, fingerprint=epoch)
                    with st.lock:
                        st.backend_state = loaded
                except Exception as e:  # noqa: BLE001 — degrade, don't fail
                    if not self._is_device_error(e):
                        raise
                    self._trip_device_circuit(e)
                    ok = False
        return ok

    # -- schema CRUD (MetadataBackedDataStore role) --------------------------
    def create_schema(self, sft: FeatureType | str, spec: str | None = None) -> FeatureType:
        if isinstance(sft, str):
            if spec is None:
                raise ValueError("create_schema('name', 'spec string') requires a spec")
            sft = parse_spec(sft, spec)
        vis_field = sft.user_data.get("geomesa.vis.field")
        if vis_field and vis_field not in {a.name for a in sft.attributes}:
            raise ValueError(
                f"geomesa.vis.field names unknown attribute {vis_field!r}"
            )
        state = _TypeState(sft=sft, indices=build_indices(sft))
        ticket = None
        if self._wal_active():
            from geomesa_tpu.store import wal as _walmod

            with self._wal.schema_lock:
                with self._schema_lock:  # atomic exists-check + insert
                    if sft.name in self._types:
                        raise ValueError(f"schema already exists: {sft.name}")
                    self._types[sft.name] = state
                # schema-topic appends order under wal.schema_lock (held
                # here), not the per-type wal_lock the data ops use
                # tpurace: disable-next-line=R001
                ticket = self._wal.append(
                    _walmod.SCHEMA_TOPIC,
                    {"op": "create_schema", "name": sft.name,
                     "spec": sft.to_spec(),
                     "index_layout": sft.index_layout})
                self._wal_schema_seq = ticket.seq
        else:
            with self._schema_lock:  # atomic exists-check + insert
                if sft.name in self._types:
                    raise ValueError(f"schema already exists: {sft.name}")
                self._types[sft.name] = state
        if ticket is not None:
            self._wal.commit(ticket)
        return sft

    @mutation(kind="evolve", invalidates=("plan-cache", "agg-pyramids"))
    @mutation(kind="rename", invalidates=(
        "geoblocks-query-cache", "buffer-pool", "device-cost-table",
        "spill-ledger", "planner-calibration-table",
        "persisted-cost-sidecar", "track-state-cache",
        "query-lens", "roundtrip-ledger", "stream-lens"))
    def update_schema(
        self,
        type_name: str,
        add: str | list[str] | None = None,
        keywords: list[str] | None = None,
        rename_to: str | None = None,
    ) -> FeatureType:
        """Schema evolution (``GeoMesaDataStore.updateSchema`` role,
        ``MetadataBackedDataStore.scala``): append attributes (all-null for
        existing rows), set keyword user-data, rename the type. Reference
        semantics are preserved: existing attributes cannot be removed or
        retyped, and the default geometry cannot change.

        ``add``: attribute spec string(s) in the SFT DSL, e.g.
        ``"severity:Integer:index=true"``.
        """
        if self._wal_active():
            from geomesa_tpu.store import wal as _walmod

            st = self._state(type_name)
            with self._wal.schema_lock, st.wal_lock:
                new_sft = self._apply_update_schema(
                    type_name, add, keywords, rename_to)
                ticket = self._wal.append(
                    _walmod.SCHEMA_TOPIC,
                    {"op": "update_schema", "type": type_name,
                     "add": ([add] if isinstance(add, str) else
                             list(add) if add else None),
                     "keywords": keywords, "rename_to": rename_to})
                self._wal_schema_seq = ticket.seq
            self._wal.commit(ticket)
            return new_sft
        return self._apply_update_schema(type_name, add, keywords, rename_to)

    def _apply_update_schema(
        self,
        type_name: str,
        add: str | list[str] | None = None,
        keywords: list[str] | None = None,
        rename_to: str | None = None,
    ) -> FeatureType:
        st = self._state(type_name)
        sft = st.sft
        new_attrs = list(sft.attributes)
        have = {a.name for a in new_attrs}
        appended = []
        if add:
            specs = [add] if isinstance(add, str) else list(add)
            for spec in specs:
                tmp = parse_spec("_tmp", spec)
                for a in tmp.attributes:
                    if a.type.is_geometry:
                        raise ValueError(
                            "cannot add geometry attributes (reference "
                            "updateSchema restriction)"
                        )
                    if a.name in have:
                        raise ValueError(f"attribute already exists: {a.name!r}")
                    new_attrs.append(a)
                    appended.append(a)
                    have.add(a.name)
        user_data = dict(sft.user_data)
        if keywords is not None:
            # comma-joined so the value survives the to_spec round-trip
            user_data["geomesa.keywords"] = ",".join(keywords)
        from geomesa_tpu.schema.sft import AttributeType as _AT

        if (
            any(a.type == _AT.DATE for a in appended)
            and "geomesa.index.dtg" not in user_data
        ):
            # pin the pre-evolution dtg ("" = none): an appended all-null
            # Date column must not become the store's temporal axis
            user_data["geomesa.index.dtg"] = sft.dtg_field or ""
        new_name = rename_to or sft.name
        if rename_to and rename_to != type_name:
            if rename_to in self._types:
                raise ValueError(f"schema already exists: {rename_to!r}")
        new_sft = FeatureType(
            name=new_name,
            attributes=new_attrs,
            default_geom=sft.geom_field,
            user_data=user_data,
        )

        # build the evolved table OUTSIDE the swap: main + delta merged in
        # host code, appended attributes backfilled as null columns — one
        # rebuild, and any failure leaves the old state fully intact
        from geomesa_tpu.schema.columnar import null_column

        with st.mutate_lock:
            main, _, delta_table, n_tables = st.consume_snapshot()
            parts = [t for t in (main, delta_table) if t is not None and len(t)]
            base = FeatureTable.concat(parts) if len(parts) > 1 else (
                parts[0] if parts else None
            )
            if base is not None:
                cols = dict(base.columns)
                for a in appended:
                    cols[a.name] = null_column(a.type, len(base))
                # sft swaps atomically WITH the rebuilt state: a concurrent
                # query never pairs the evolved schema with old indices
                self._rebuild(
                    st, FeatureTable(new_sft, base.fids, cols),
                    consumed_tables=n_tables, new_sft=new_sft,
                )
            else:
                with st.lock:
                    st.sft = new_sft
                    st.table = None
                    st.indices = build_indices(new_sft)
                    st.backend_state = None
                    st.delta.drop_first(n_tables)
                    st.purge_derived()
                    st.epoch += 1
        if rename_to and rename_to != type_name:
            with self._schema_lock:
                self._types[rename_to] = self._types.pop(type_name)
                # interceptors scoped to the old name follow the rename
                self._interceptors = [
                    (rename_to if scope == type_name else scope, fn)
                    for scope, fn in self._interceptors
                ]
            # device residency, cached aggregates, cost rows are all keyed
            # by type NAME: a rebuild above registered them under the OLD
            # name, where they would leak forever (and poison a future
            # schema reusing that name). Drop the device state so the next
            # query rebuilds under the new name, then purge the old key.
            st = self._types[rename_to]
            with st.mutate_lock:
                with st.lock:
                    st.backend_state = None
                    st.pyramids.clear()
            self._purge_type_name(type_name)
        return new_sft

    def get_schema(self, name: str) -> FeatureType:
        return self._state(name).sft

    def list_schemas(self) -> list[str]:
        return sorted(self._types)

    @mutation(kind="delete_schema", invalidates=(
        "geoblocks-query-cache", "buffer-pool", "device-cost-table",
        "spill-ledger", "planner-calibration-table",
        "persisted-cost-sidecar", "track-state-cache",
        "query-lens", "roundtrip-ledger", "stream-lens"))
    def delete_schema(self, name: str) -> None:
        if self._wal_active():
            from geomesa_tpu.store import wal as _walmod

            with self._wal.schema_lock:
                self._apply_delete_schema(name)
                # schema-topic appends order under wal.schema_lock (held
                # here), not the per-type wal_lock the data ops use
                # tpurace: disable-next-line=R001
                ticket = self._wal.append(
                    _walmod.SCHEMA_TOPIC,
                    {"op": "delete_schema", "name": name})
                self._wal_schema_seq = ticket.seq
            self._wal.commit(ticket)
            return
        self._apply_delete_schema(name)

    def _apply_delete_schema(self, name: str) -> None:
        with self._schema_lock:
            del self._types[name]
        # a recreated same-name type RESTARTS its rebuild epoch and delta
        # version at the same values, so everything keyed by type name
        # must die with the schema: cached aggregates (the epoch tuple
        # recurs — the successor would read the dead table's answers as
        # current), pool entries/donations (a fingerprint collision would
        # re-admit the dead table's device columns as the new state), the
        # spill report, and the observed cost profile + probe phase
        self._purge_type_name(name)

    def _purge_type_name(self, name: str) -> None:
        """Drop every store/pool/telemetry artifact keyed by a type NAME
        whose schema no longer answers for it (delete, rename)."""
        self.agg_cache.invalidate(name)
        pool: "BufferPool | None" = getattr(self.backend, "pool", None)
        if pool is not None:
            pool.purge(name)
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.planning import costmodel

        devmon.ledger().clear_spills(name)
        devmon.costs().forget(name)
        costmodel.model().forget(name)
        # the retained profiling plane (obs.lens) and the roundtrip rollup
        # (obs.ledger) key series by type name too: a recreated same-name
        # type must not inherit its predecessor's latency history or
        # fusion ranking (and the sentinel must not compare across them)
        from geomesa_tpu.obs import lens as _lensmod
        from geomesa_tpu.obs import ledger as _rtledger

        _lensmod.get().forget(name)
        _rtledger.table().forget(name)
        # the stream lens keys delivery history by TOPIC, and the topic
        # convention is type-name-derived — a recreated same-name type's
        # standing subscriptions must not inherit the dead type's
        # delivery histograms, lateness counters, or capacity history
        from geomesa_tpu.obs import streamlens as _streamlens

        _streamlens.get().forget(f"geomesa-{name}")
        # the PERSISTED cost sidecar too: a restart must not resurrect a
        # deleted/renamed type's profile for an unrelated successor
        devmon.purge_persisted_costs(name)
        # cached trajectory track states are epoch-fingerprinted by the
        # SAME restarting (rebuild epoch, delta version) tuple — a
        # recreated same-name type could collide and serve the dead
        # table's per-entity aggregates as current
        from geomesa_tpu.trajectory import state as _traj_state

        _traj_state.invalidate(self, name)

    def _state(self, name: str) -> _TypeState:
        if name not in self._types:
            raise KeyError(f"no such schema: {name!r}")
        return self._types[name]

    # -- writes (GeoMesaFeatureWriter + lambda hot-tier roles) ---------------
    @mutation(kind="write", invalidates=("plan-cache", "agg-pyramids"))
    def write(self, type_name: str, data, fids=None) -> int:
        """Append features (FeatureTable or list of record dicts).

        Writes land in the hot delta tier (immediately queryable, scanned
        brute-force) and are merged into the sorted main tier when the delta
        passes the compaction threshold — the lambda-architecture pattern
        (SURVEY.md §2.11). Validation before commit (the reference's
        all-indices-validate-before-write pattern, ``IndexAdapter.scala:
        139-149``): rows with a null default geometry or null dtg are
        rejected, and main-tier state only swaps in after every index builds,
        so a failed write never leaves the store half-applied.

        With the durability plane attached (``GEOMESA_TPU_WAL`` /
        ``wal_dir=``) the write is journaled under the type's WAL order
        lock and the return — the ACK — waits for the record's
        group-commit durability: a SIGKILL after return can never lose it
        (docs/operations.md § Durability & recovery).
        """
        st = self._state(type_name)
        with obs.span("write", type_name=type_name):
            if isinstance(data, list):
                if fids is None:
                    fids = self._generate_fids(st, len(data), data)
                data = FeatureTable.from_records(st.sft, data, fids)
            self._validate(st.sft, data)
            ticket = None
            if self._wal_active():
                from geomesa_tpu.io.arrow import to_ipc_bytes
                from geomesa_tpu.store import wal as _walmod

                payload = to_ipc_bytes(data)
                with st.wal_lock:
                    compact_now = self._apply_write(st, data)
                    ticket = self._wal.append(
                        _walmod.topic_for(type_name), {"op": "write"}, payload)
                    with st.lock:
                        st.wal_seq = ticket.seq
            else:
                compact_now = self._apply_write(st, data)
            if ticket is not None:
                self._wal.commit(ticket)  # durability before the ack
            if compact_now:
                self.compact(type_name)
            return len(data)

    def _apply_write(self, st: _TypeState, data) -> bool:
        self.metrics.counter("store.writes").inc(len(data))
        with st.lock:
            st.delta.append(data)
            return st.delta.should_compact(st.main_rows)

    def _wal_active(self) -> bool:
        """Journal this mutation? False on the WAL-off path (one attribute
        check — the <2% write-overhead bound) and during recovery replay /
        checkpoint load (the records being applied ARE the journal).
        Raises if the attached journal still holds an unreplayed tail —
        mutating over un-recovered acked history must fail loudly, not
        shadow it (open with ``DataStore.open(catalog, recover=True)``)."""
        if self._wal is None or self._wal_replay:
            return False
        if self._wal_unreplayed:
            from geomesa_tpu.store.wal import WalTailError

            raise WalTailError(
                f"WAL {self._wal.path!r} holds un-replayed acked records; "
                f"this store was attached without recovery — open the "
                f"catalog with DataStore.open(..., recover=True)")
        return True

    def _generate_fids(self, st, n: int, records: list) -> list:
        """Default feature ids. Schemas opting in via user-data
        ``geomesa.fid.uuid='z3'`` get z3-prefixed ids (the reference writer's
        Z3 time-UUID default, ``GeoMesaFeatureWriter.scala:81``); otherwise
        sequential ``<type>.<n>`` ids."""
        sft = st.sft
        if (
            str(sft.user_data.get("geomesa.fid.uuid", "")).lower() == "z3"
            and sft.geom_field is not None
            and sft.dtg_field is not None
        ):
            from geomesa_tpu.schema.columnar import _to_millis
            from geomesa_tpu.utils.fid import z3_fids

            lons = np.empty(n)
            lats = np.empty(n)
            ts = np.empty(n, dtype=np.int64)
            ok = True
            for i, r in enumerate(records):
                g = r.get(sft.geom_field)
                t = r.get(sft.dtg_field)
                if g is None or t is None or not hasattr(g, "bbox"):
                    ok = False
                    break
                x1, y1, x2, y2 = g.bbox
                lons[i] = (x1 + x2) / 2
                lats[i] = (y1 + y2) / 2
                ts[i] = _to_millis(t)
            if ok:
                return list(z3_fids(lons, lats, ts, sft.z3_interval))
        with st.lock:
            # monotonic per-type sequence: concurrent writers must never
            # mint the same id (total_rows alone is a check-then-act race)
            st.fid_seq = max(st.fid_seq, st.total_rows)
            base = st.fid_seq
            st.fid_seq += n
        return [f"{st.sft.name}.{base + i}" for i in range(n)]

    # -- query interceptors (QueryInterceptor.scala:27 role) ------------------
    def register_interceptor(self, type_name: str | None, fn) -> None:
        """Register ``fn(sft, query) -> query`` rewriting queries before
        planning; ``type_name`` None applies to every schema."""
        # under the schema lock: the rename path REPLACES the list wholesale
        # while holding it, and an append racing that swap would land on the
        # discarded list (registration silently lost)
        with self._schema_lock:
            self._interceptors.append((type_name, fn))

    def _intercept(self, type_name: str, sft, q: Query) -> Query:
        for scope, fn in self._interceptors:
            if scope is None or scope == type_name:
                out = fn(sft, q)
                if out is not None:
                    q = out
        return q

    @mutation(kind="delete", invalidates=("plan-cache", "agg-pyramids"))
    def delete_features(self, type_name: str, fids, visible_to=None) -> int:
        """Remove features by id (the ``GeoMesaFeatureWriter`` remove role).

        Rebuilds the main tier without the targeted rows (columnar stores
        delete by rewrite, like the reference's LSM deletes compact away);
        returns the number of rows removed. ``visible_to`` (a list of
        authorizations) enforces record visibility UNDER the mutation lock:
        targeting any row the auths cannot see raises ``PermissionError`` —
        the race-proof backstop for the serving layer's pre-check.
        """
        st = self._state(type_name)
        want = {str(f) for f in fids}
        if self._wal_active():
            from geomesa_tpu.store import wal as _walmod

            ticket = None
            with st.wal_lock:
                removed = self._apply_delete(st, want, visible_to)
                if removed:  # no state change → nothing to journal
                    ticket = self._wal.append(
                        _walmod.topic_for(type_name),
                        {"op": "delete", "fids": sorted(want),
                         "visible_to": (None if visible_to is None
                                        else list(visible_to))})
                    with st.lock:
                        st.wal_seq = ticket.seq
            if ticket is not None:
                self._wal.commit(ticket)
            return removed
        return self._apply_delete(st, want, visible_to)

    def _apply_delete(self, st: _TypeState, want: set, visible_to) -> int:
        with st.mutate_lock:
            main, _, delta, n_tables = st.consume_snapshot()
            tables = [t for t in (main, delta) if t is not None and len(t)]
            if not tables:
                return 0
            combined = (
                tables[0] if len(tables) == 1 else FeatureTable.concat(tables)
            )
            keep = np.array(
                [str(f) not in want for f in combined.fids], dtype=bool
            )
            removed = int((~keep).sum())
            if visible_to is not None and removed:
                vis_field = (st.sft.user_data or {}).get("geomesa.vis.field")
                if vis_field:
                    from geomesa_tpu.security.visibility import parse_visibility

                    auths = frozenset(visible_to)
                    vvals = combined.columns[vis_field].values
                    for i in np.nonzero(~keep)[0]:
                        expr = vvals[i] if vvals[i] else ""
                        if not parse_visibility(expr).evaluate(auths):
                            raise PermissionError(
                                "target features not visible"
                            )
            if removed == 0:
                return 0
            # the delta drops only after the new state swaps in — a failed
            # rebuild must not lose hot-tier rows
            self._rebuild(
                st, combined.take(np.nonzero(keep)[0]), consumed_tables=n_tables
            )
            return removed

    def update_features(self, type_name: str, data, fids, visible_to=None) -> int:
        """Replace the features with the given ids (the
        ``GeoMesaFeatureWriter`` MODIFY flavor): delete + append under the
        mutation lock. Like the reference (no cross-index transactions,
        ``IndexAdapter.scala:139`` validates-then-writes), the replacement
        is not atomic for concurrent readers — a query racing the update may
        briefly miss the row; it never sees both versions after return.
        ``visible_to``: see :meth:`delete_features`.

        Every target fid must already exist — a missing id raises
        ``KeyError`` (no silent upsert; WFS-T Update's replace contract),
        checked under the mutation lock for both restricted and
        unrestricted callers."""
        fids = [str(f) for f in fids]
        if len(set(fids)) != len(fids):
            raise ValueError("update_features: duplicate fids")
        if isinstance(data, list):
            if len(data) != len(fids):
                raise ValueError(
                    f"update_features: {len(data)} records for {len(fids)} fids"
                )
        elif [str(f) for f in data.fids] != fids:
            # a table carries its own fids; they must BE the replaced ids or
            # the delete and the append would target different features
            raise ValueError("update_features: table fids != fids argument")
        st = self._state(type_name)
        # wal_lock OUTSIDE mutate_lock: the inner delete/write journal
        # under wal_lock, and wal_lock > mutate_lock is the canonical
        # order (docs/concurrency.md) — taking mutate first would invert
        with st.wal_lock, st.mutate_lock:
            # validate the replacement BEFORE deleting: a malformed update
            # must fail without destroying the original rows (the reference's
            # validates-then-writes pattern)
            table = (
                FeatureTable.from_records(st.sft, data, fids)
                if isinstance(data, list)
                else data
            )
            self._validate(st.sft, table)
            # every target must exist (no silent upsert — WFS-T Update is
            # replace). Fid sets are read per-tier (no delta concat; the
            # delete below builds the merged view once). Restricted callers
            # get PermissionError for missing ids — the same error hidden
            # rows raise — so a 403/404 split cannot become an existence
            # oracle for rows their auths cannot see.
            existing: set[str] = set()
            with st.lock:
                tiers = [st.table, *st.delta.tables]
            for t in tiers:
                if t is not None and len(t):
                    existing.update(str(f) for f in t.fids)
            missing = [f for f in fids if f not in existing]
            if missing:
                if visible_to is not None:
                    raise PermissionError("target features not visible")
                raise KeyError(
                    f"update_features: no such feature id(s) {missing[:5]}"
                    + ("..." if len(missing) > 5 else "")
                )
            # wal_lock is ALREADY HELD (outer, reentrant) — the inner
            # delete/write re-acquire it, so the static mutate->wal edge
            # seen here cannot deadlock against the canonical wal->mutate
            # order
            # tpurace: disable-next-line=R002
            self.delete_features(type_name, fids, visible_to=visible_to)
            return self.write(type_name, table)

    @mutation(kind="clear", invalidates=("plan-cache", "agg-pyramids"))
    def clear(self, type_name: str) -> int:
        """Drop every row of a type, keeping the schema (the bus tier's
        ``Clear`` barrier as a store op; WFS-T "delete all" role). Returns
        the rows removed. Journaled like every other mutation — a
        recovered store is empty exactly when the acked state was."""
        st = self._state(type_name)
        if self._wal_active():
            from geomesa_tpu.store import wal as _walmod

            ticket = None
            with st.wal_lock:
                removed = self._apply_clear(st)
                if removed:
                    ticket = self._wal.append(
                        _walmod.topic_for(type_name), {"op": "clear"})
                    with st.lock:
                        st.wal_seq = ticket.seq
            if ticket is not None:
                self._wal.commit(ticket)
            return removed
        return self._apply_clear(st)

    def _apply_clear(self, st: _TypeState) -> int:
        with st.mutate_lock:
            with st.lock:
                removed = st.total_rows
                if removed == 0:
                    return 0
                n_tables = len(st.delta.tables)
                st.table = None
                st.indices = build_indices(st.sft)
                st.backend_state = None
                st.stats = None
                st.delta.drop_first(n_tables)
                st.purge_derived()
                st.epoch += 1
            return removed

    @mutation(kind="write", invalidates=("plan-cache", "agg-pyramids"))
    def compact(self, type_name: str) -> None:
        """Merge the delta tier into the sorted main tier (re-sort + device
        reload + stats rebuild). Atomic: state swaps only on success, and
        writes landing mid-compaction stay in the hot tier."""
        st = self._state(type_name)
        with st.mutate_lock:
            main, prev_indices, delta, n_tables = st.consume_snapshot()
            if delta is None:
                return
            n_prev = 0 if main is None else len(main)
            table = delta if main is None else FeatureTable.concat([main, delta])
            self._rebuild(
                st, table, prev_indices=prev_indices, n_prev=n_prev,
                consumed_tables=n_tables,
            )

    # bulk builds below this many rows host-sort (device round-trip beats
    # the sort only at scale); env-tunable so tests can force the mesh path
    DEVICE_SORT_MIN_ROWS = 2_000_000

    def _device_sorter(self, n_rows: int):
        """The mesh sample-sort for index builds, when it applies.

        The ``DefaultSplitter`` role wired into the store lifecycle (VERDICT
        r2 item 4): bulk ingest/compaction on the TPU backend routes
        arrival-order keys through stats-driven splits + ``all_to_all``
        (``device_ingest.device_sort_perm``) instead of the host sort.
        Returns None (→ host sort) for small tables, non-TPU backends, or
        an open device circuit.
        """
        if self.backend.name != "tpu" or not self._device_available():
            return None
        env_thresh = os.environ.get("GEOMESA_DEVICE_SORT_MIN_ROWS")
        if env_thresh is None:
            import jax

            if jax.default_backend() != "tpu":
                # without an explicit opt-in the mesh sample sort only pays
                # on a real accelerator: on the CPU test mesh its
                # all_to_all materializations cost ~20x the native host
                # radix sort (the env knob stays an opt-in anywhere — the
                # sharding tests set it to exercise the device path)
                return None
        threshold = int(
            env_thresh if env_thresh is not None else self.DEVICE_SORT_MIN_ROWS
        )
        if n_rows < max(threshold, 1):
            return None
        from geomesa_tpu.store.device_ingest import device_sort_perm

        try:
            mesh = self.backend._get_mesh()
        except Exception as e:  # noqa: BLE001 — degrade, don't die
            if not self._is_device_error(e):
                raise
            self._trip_device_circuit(e)
            return None

        def sorter(route_key, tiebreak):
            return device_sort_perm(mesh, route_key, tiebreak)

        return sorter

    def _rebuild(self, st: _TypeState, table: FeatureTable, prev_indices=None,
                 n_prev: int = 0, consumed_tables: int = 0, new_sft=None) -> None:
        """Swap in a new main tier built from ``table`` (delta folded in).

        Indexes exposing ``merge_build`` fold a sorted delta into the
        already-sorted previous state linearly (LSM compaction, SURVEY.md
        §2.11) instead of re-sorting everything. ``consumed_tables`` is the
        delta-table count the caller folded into ``table`` (from
        :meth:`_TypeState.consume_snapshot`); only those first tables drop
        from the hot tier, so writes landing during the rebuild survive.
        ``new_sft`` swaps the schema atomically with the state (evolution).
        Callers must hold ``st.mutate_lock``.
        """
        sft = new_sft if new_sft is not None else st.sft
        indices = build_indices(sft)
        # the NEXT rebuild epoch (mutate_lock serializes mutators, so the
        # increment is race-free): the backend load's donation fingerprint
        # and, at swap, the new data-epoch component
        next_epoch = st.epoch + 1
        sorter = self._device_sorter(len(table))
        for name, index in indices.items():
            prev = (prev_indices or {}).get(name)
            if prev is not None and n_prev > 0 and hasattr(index, "merge_build"):
                index.merge_build(table, prev, n_prev)
            elif sorter is not None:
                try:
                    index.build(table, sorter=sorter)
                    self._note_device_ok()  # half-open circuit closes
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    if not self._is_device_error(e):
                        raise
                    self._trip_device_circuit(e)
                    self.metrics.counter("store.device.sort_failures").inc()
                    sorter = None  # host sorts for the remaining indexes too
                    index.build(table)
            else:
                index.build(table)
        try:
            backend_state = self.backend.load(
                sft, table, indices, fingerprint=next_epoch)
        except Exception as e:  # noqa: BLE001 — write must not die with the device
            if not self._is_device_error(e):
                raise
            self._trip_device_circuit(e)
            self.metrics.counter("store.device.load_failures").inc()
            backend_state = None  # host paths serve until recover()
        from geomesa_tpu.stats.store_stats import StoreStats

        stats = StoreStats(sft)
        stats.rebuild(table, indices.get("z3"))
        with st.lock:
            if new_sft is not None:
                st.sft = new_sft
            st.table = table
            st.indices = indices
            st.backend_state = backend_state
            st.stats = stats
            st.delta.drop_first(consumed_tables)
            st.purge_derived()  # pyramids were built from the OLD main tier
            st.epoch = next_epoch

    # -- age-off (AgeOffIterator / DtgAgeOffIterator role) --------------------
    @staticmethod
    def _age_off_ttl_ms(sft: FeatureType) -> int | None:
        """TTL from schema user-data ``geomesa.age.off`` (milliseconds)."""
        v = sft.user_data.get("geomesa.age.off")
        return None if v is None else int(v)

    @mutation(kind="age_off", invalidates=("plan-cache", "agg-pyramids"))
    def age_off(self, type_name: str, now_ms: int | None = None) -> int:
        """Physically drop rows older than the schema's TTL; returns count.

        The query path also masks expired rows on the fly, so ``age_off`` is a
        maintenance compaction (the reference runs the same logic as a
        server-side iterator at scan AND at major compaction —
        ``AgeOffIterator``, SURVEY.md §2.3/§2.6).
        """
        st = self._state(type_name)
        ttl = self._age_off_ttl_ms(st.sft)
        if ttl is None or st.sft.dtg_field is None or st.total_rows == 0:
            return 0
        if now_ms is None:
            # resolve the clock BEFORE journaling: a replayed age-off must
            # drop exactly the rows the live one did
            import time as _time

            now_ms = int(_time.time() * 1000)
        if self._wal_active():
            from geomesa_tpu.store import wal as _walmod

            ticket = None
            with st.wal_lock:
                removed = self._apply_age_off(st, now_ms, ttl)
                if removed:
                    ticket = self._wal.append(
                        _walmod.topic_for(type_name),
                        {"op": "age_off", "now_ms": int(now_ms)})
                    with st.lock:
                        st.wal_seq = ticket.seq
            if ticket is not None:
                self._wal.commit(ticket)
            return removed
        return self._apply_age_off(st, now_ms, ttl)

    def _apply_age_off(self, st: _TypeState, now_ms: int, ttl: int) -> int:
        cutoff = _ttl_cutoff_ms(ttl, now_ms)
        with st.mutate_lock:
            main, _, delta, n_tables = st.consume_snapshot()
            parts = [t for t in (main, delta) if t is not None]
            if not parts:  # raced another maintenance pass that emptied it
                return 0
            table = FeatureTable.concat(parts) if len(parts) > 1 else parts[0]
            keep = table.columns[st.sft.dtg_field].values >= cutoff
            removed = int((~keep).sum())
            if removed == 0:
                return 0
            if keep.any():
                self._rebuild(
                    st, table.take(np.nonzero(keep)[0]), consumed_tables=n_tables
                )
            else:  # everything expired: reset to empty
                with st.lock:
                    st.table = None
                    st.indices = build_indices(st.sft)
                    st.backend_state = None
                    st.stats = None
                    st.delta.drop_first(n_tables)
                    st.purge_derived()
                    st.epoch += 1
            return removed

    @staticmethod
    def _validate(sft: FeatureType, table: FeatureTable) -> None:
        if sft.geom_field is not None:
            col = table.columns[sft.geom_field]
            if not col.is_valid().all():
                bad = int((~col.is_valid()).sum())
                raise ValueError(
                    f"{bad} feature(s) with null geometry {sft.geom_field!r}: "
                    "indexed geometries must be non-null"
                )
        if sft.dtg_field is not None:
            col = table.columns[sft.dtg_field]
            if not col.is_valid().all():
                bad = int((~col.is_valid()).sum())
                raise ValueError(
                    f"{bad} feature(s) with null date {sft.dtg_field!r}: "
                    "indexed dates must be non-null"
                )
        # visibility expressions must parse at write time so one malformed row
        # can never poison every subsequent auth-filtered read
        vis_field = sft.user_data.get("geomesa.vis.field")
        if vis_field:
            from geomesa_tpu.security.visibility import parse_visibility

            for v in set(
                "" if v is None else str(v) for v in table.columns[vis_field].values
            ):
                # comma lists are per-ATTRIBUTE expressions (attribute-level
                # visibility); each part must parse on its own
                for part in v.split(","):
                    parse_visibility(part.strip())  # raises on bad input

    # -- queries (QueryPlanner.runQuery role) --------------------------------
    def query(
        self, type_name: str, q: "Query | str | ast.Filter | None" = None, **kwargs
    ) -> QueryResult:
        st = self._state(type_name)
        if isinstance(q, (str, ast.Filter)) or q is None:
            q = Query(filter=q, **kwargs)
        elif kwargs:
            raise ValueError(
                "pass query options inside the Query object, not as kwargs: "
                f"{sorted(kwargs)}"
            )
        # the per-query trace root (child when already inside a request or
        # batch span); every stage below opens a child span, so EXPLAIN
        # ANALYZE and the Perfetto export read straight off this tree
        with obs.span("query", type_name=type_name):
            # sampled device-time attribution (GEOMESA_TPU_DEVPROF env or
            # the per-query "devprof" hint): every cached-jit dispatch in
            # this tree brackets with block_until_ready timing, and the
            # breakdown lands in the flight record + cost table (_audit)
            from geomesa_tpu.obs import devmon
            from geomesa_tpu.obs import ledger as _rtledger

            # host-roundtrip ledger (obs.ledger): every device dispatch /
            # host sync under this query charges the per-query ledger;
            # _audit folds it into the per-signature fusion rollup
            with _rtledger.roundtrip():
                if devmon.sampled(q.hints.get("devprof")):
                    with devmon.profiled():
                        return self._run_query(st, type_name, q)
                return self._run_query(st, type_name, q)

    def _run_query(self, st: _TypeState, type_name: str, q: Query) -> QueryResult:
        import time as _time

        # user query-rewrite hooks run before anything else sees the query
        # (QueryPlanner.scala:178 configureQuery → interceptors)
        if self._interceptors:
            q = self._intercept(type_name, st.sft, q)

        self.metrics.counter("store.queries").inc()
        if st.total_rows == 0:
            # still runs the shared reduce pipeline so aggregation hints
            # produce empty aggregates (not None) — callers index res.stats
            from geomesa_tpu.store.reduce import reduce_result

            empty = FeatureTable.from_records(st.sft, [])
            with obs.span("reduce", rows=0):
                table, rows, density, stats_out, bin_data = reduce_result(
                    st.sft, empty, np.empty(0, dtype=np.int64), q
                )
            self._audit(type_name, q, 0.0, 0.0, 0)
            return QueryResult(
                table, rows, density=density, stats=stats_out, bin_data=bin_data
            )

        # query-time age-off (AgeOffIterator-at-scan role): expired rows are
        # masked even before a physical age_off() compaction runs
        ttl = self._age_off_ttl_ms(st.sft)
        if ttl is not None and st.sft.dtg_field is not None:
            from dataclasses import replace as _replace

            cut = ast.Compare(
                ">=", st.sft.dtg_field,
                _ttl_cutoff_ms(ttl, q.hints.get("now_ms")),
            )
            q = _replace(q, filter=ast.And((q.resolved_filter(), cut)))

        # correctness-audit tagging (obs/audit.py): the off path is one
        # module-global bool plus a dict lookup. A sampled (or
        # hint-tagged) query captures the DATA EPOCH *before* the scan
        # snapshot — a write landing in between moves the live epoch
        # past the captured one, and the shadow re-check then abstains
        # instead of alarming (the capture-order rule cached aggregates
        # already follow)
        from geomesa_tpu.obs import audit as _obsaudit

        audit_epoch = None
        if (not _obsaudit.in_shadow()
                and (_obsaudit.ENABLED or q.hints.get("audit"))
                and _obsaudit.eligible_select(q)
                # eligibility FIRST: an ineligible (density/limit) query
                # must not burn a sampling tick — the configured rate
                # applies to auditable traffic
                and (q.hints.get("audit") or _obsaudit.sampled())):
            audit_epoch = st.data_epoch()

        t_start = _time.perf_counter()
        plan_box = {"info": None, "plan_ms": 0.0}

        def _scan_and_reduce():
            f = q.resolved_filter()
            # COHERENT state snapshot: a background compaction (lambda
            # persister) must never let this query pair a new table with old
            # index permutations or a stale device residency
            main, indices, backend_state, stats, delta_table = st.snapshot()
            main_n = 0 if main is None else len(main)
            if main_n == 0:
                rows = np.empty(0, dtype=np.int64)
            elif isinstance(self.backend, OracleBackend):
                # referee path: no planning, brute force
                rows = self.backend.select(None, None, None, None, f, main)
            else:
                t0 = _time.perf_counter()
                # TTL stores rewrite the filter with a now_ms cut per call —
                # the key would never repeat, so don't pay the cache overhead
                cache_key = None if ttl is not None else self._plan_cache_key(q)
                with obs.span("plan") as _plan_sp:
                    cached = self._plan_lookup(st, indices, cache_key)
                    if cached is not None:
                        plan, f, plan_box["info"] = cached
                        _plan_sp.set(cache="hit")
                    else:
                        planner = QueryPlanner(st.sft, indices, stats)
                        plan, f, plan_box["info"] = planner.plan(
                            q, under_burn=self._under_burn(type_name))
                        self._plan_store(
                            st, indices, cache_key, (plan, f, plan_box["info"])
                        )
                    _plan_sp.set(index=plan_box["info"].index_name)
                plan_box["plan_ms"] = (_time.perf_counter() - t0) * 1000.0
                info = plan_box["info"]
                # circuit open → don't touch the device; exact host scan
                state = backend_state if self._device_available() else None
                try:
                    if info.sub_plans:
                        # FilterSplitter union: scan each arm on its own index
                        # (full filter as residual keeps each arm exact), union
                        parts = [
                            self.backend.select(
                                state, indices[n], p, e_c, f, main
                            )
                            for n, p, e_c in info.sub_plans
                        ]
                        rows = np.unique(np.concatenate(parts))
                    else:
                        index = indices[info.index_name]
                        rows = self.backend.select(
                            state, index, plan, info.extraction, f, main,
                        )
                except Exception as e:  # noqa: BLE001 — failover, re-raise rest
                    if state is None or not self._is_device_error(e):
                        raise
                    self._trip_device_circuit(e)
                    self.metrics.counter("store.query.device_failovers").inc()
                    with obs.span("refine", mode="failover"):
                        rows = np.nonzero(f.mask(main))[0]
                else:
                    if state is not None:
                        self._note_device_ok()
            rows = np.sort(rows)

            # hot-tier merge (LambdaQueryRunner role): brute-force the small
            # unsorted delta and append, row ids offset past the main tier
            if delta_table is not None:
                with obs.span("delta", rows=len(delta_table)):
                    dmask = f.mask(delta_table)
                    drows = np.nonzero(dmask)[0]
                    rows = np.concatenate([rows, drows + main_n])

            with obs.span("reduce", rows=len(rows)):
                table = _take_combined(st.sft, main, main_n, delta_table, rows)

                # shared post-scan pipeline: visibility, sampling, aggregation
                # hints, sort/limit/projection/CRS (LocalQueryRunner shape)
                from geomesa_tpu.store.reduce import reduce_result

                return reduce_result(st.sft, table, rows, q)

        # query watchdog (ThreadManagement role): per-query ``timeout`` hint
        # in seconds; timed-out scans are abandoned and counted
        from geomesa_tpu.utils.timeouts import QueryTimeout, run_with_timeout

        timeout_s = q.hints.get("timeout")
        # end-to-end deadline (hints["deadline"]: utils.timeouts.Deadline):
        # the remaining budget CAPS any per-query timeout, and a budget
        # already spent upstream sheds the scan before any device work —
        # no worker thread is spawned, so nothing lands in the abandoned
        # gauge for work that never started
        deadline = q.hints.get("deadline")
        if deadline is not None:
            rem = deadline.remaining_s()
            if rem <= 0:
                self.metrics.counter("store.query.timeouts").inc()
                self.metrics.counter("store.query.deadline_shed").inc()
                if not _obsaudit.in_shadow():
                    self.slo.observe("store.query", ok=False, key=type_name)
                self._meter_failed(type_name, q, 0.0)
                raise QueryTimeout(
                    f"deadline spent before scan of {type_name!r} started")
            timeout_s = rem if timeout_s is None else min(timeout_s, rem)
        token = self.watchdog.register(f"{type_name}: {q.filter!r}")
        timed_out = False
        try:
            table, rows, density, stats_out, bin_data = run_with_timeout(
                _scan_and_reduce, timeout_s
            )
        except QueryTimeout:
            timed_out = True
            wall = (_time.perf_counter() - t_start) * 1000.0
            self.metrics.counter("store.query.timeouts").inc()
            if not _obsaudit.in_shadow():
                self.slo.observe(
                    "store.query", ok=False, key=type_name, latency_ms=wall)
            self._meter_failed(type_name, q, wall)
            raise
        finally:
            # finally: scan errors (not just timeouts) must release the
            # registration instead of leaking it in the active set
            self.watchdog.complete(token, timed_out=timed_out)
        info = plan_box["info"]
        plan_ms = plan_box["plan_ms"]
        scan_ms = (_time.perf_counter() - t_start) * 1000.0 - plan_ms
        self._audit(type_name, q, plan_ms, scan_ms, len(table), info=info)
        if audit_epoch is not None:
            # shadow re-execution against the independent referee: the
            # LIVE answer (post-reduce fids) rides along so the check
            # compares without re-running this path
            _obsaudit.get().enqueue_select(
                self, type_name, q, audit_epoch, table)
        return QueryResult(
            table, rows, info, density=density, stats=stats_out, bin_data=bin_data
        )

    _PLAN_CACHE_MAX = 128

    def _under_burn(self, type_name: str) -> bool:
        """Is this type burning its error budget? Fed to the planner's
        SLO-aware tie-breaking: under burn, near-tied strategies resolve
        to the lower-variance plan. Computed only on plan-cache misses."""
        try:
            return (
                self.slo.tracker("store.query", type_name).burn_rate(300.0)
                > 1.0
            )
        except Exception:  # noqa: BLE001 — telemetry must never fail a plan
            return False

    @staticmethod
    def _plan_cache_key(q: "Query"):
        """Cache key for a query's PLANNING inputs, or None if uncacheable.
        Planning reads only the filter and the forced-index hint."""
        f = q.filter
        if f is None:
            text = "INCLUDE"
        elif isinstance(f, str):
            text = f
        else:
            try:
                text = ast.to_cql(f)
            except ValueError:
                return None
        return (text, q.hints.get("index"))

    def _plan_lookup(self, st: _TypeState, indices, key):
        if key is None:
            return None
        with st.lock:
            if st.indices is not indices:
                return None  # our snapshot is older than the live state
            hit = st.plan_cache.get(key)
            if hit is not None:
                st.plan_cache.move_to_end(key)
                self.metrics.counter("store.plan_cache.hits").inc()
            return hit

    @feedback_sink
    def _plan_store(self, st: _TypeState, indices, key, value) -> None:
        if key is None:
            return
        # a probe-tick plan deliberately took the LOSING strategy so its
        # cost profile stays fresh — caching it would replay the loser
        # for every later identical query, turning a bounded 1-in-16
        # exploration into a permanent per-filter regression. The next
        # identical query replans (a non-probe tick) and caches normally.
        if getattr(value[2], "strategy_source", "") == "probe":
            return
        with st.lock:
            if st.indices is not indices:
                return  # state swapped since our snapshot: plan is stale
            st.plan_cache[key] = value
            while len(st.plan_cache) > self._PLAN_CACHE_MAX:
                st.plan_cache.popitem(last=False)

    def cache_report(self) -> dict:
        """The buffer-pool / query-cache / pyramid gauge block
        (docs/observability.md § Buffer pool & query cache): served by
        ``GET /api/metrics`` and rendered by ``explain(analyze=True)``."""
        pool = getattr(self.backend, "pool", None)
        pyramid_bytes = {}
        for name, st in list(self._types.items()):
            with st.lock:
                total = sum(
                    p.nbytes for p, _stamp in st.pyramids.values()
                    if p is not None
                )
            if total:
                pyramid_bytes[name] = total
        return {
            "agg_cache": self.agg_cache.snapshot(),
            "pyramid_bytes": pyramid_bytes,
            "pool": pool.snapshot() if pool is not None else None,
        }

    def cache_prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        lines = self.agg_cache.prometheus_lines(prefix)
        rep = self.cache_report()
        lines.append(f"# TYPE {prefix}_pyramid_bytes gauge")
        for t, b in sorted(rep["pyramid_bytes"].items()):
            lines.append(f'{prefix}_pyramid_bytes{{type="{t}"}} {b}')
        pool = getattr(self.backend, "pool", None)
        if pool is not None:
            lines += pool.prometheus_lines(prefix)
        return lines

    def device_residency(self, type_name: str) -> dict:
        """HBM residency report for one type: per-index device bytes, total,
        and the backend's budget (the managed hot-tier view of SURVEY.md
        §2.20 P9 — indexes over budget serve from the host path instead)."""
        st = self._state(type_name)
        with st.lock:
            state = st.backend_state
        per_index = (
            TpuBackend.residency(state)
            if isinstance(self.backend, TpuBackend)
            else {}
        )
        return {
            "indices": per_index,
            "total_bytes": int(sum(per_index.values())),
            "budget_bytes": getattr(self.backend, "max_device_bytes", None),
            "resident": bool(per_index),
        }

    def evict_device(self, type_name: str) -> None:
        """Drop one type's device-resident arrays (host stays authoritative;
        queries fall back to exact host scans). ``recover(type_name)``
        re-uploads — together the explicit HBM tier controls."""
        st = self._state(type_name)
        # mutate_lock: a concurrent rebuild/recover mid backend.load() would
        # otherwise re-install device state right after this eviction
        with st.mutate_lock:
            with st.lock:
                st.backend_state = None
                # pyramids hold the device count mirrors (ledger group
                # "pyramid"): they must not outlive an explicit eviction
                st.pyramids.clear()
        # explicit eviction is operator intent to free the HBM NOW: the
        # pool's pins AND its donation stash for this type both drop (a
        # stashed copy would silently keep the bytes resident)
        pool = getattr(self.backend, "pool", None)
        if pool is not None:
            pool.purge(type_name)
        # the ledger entries unregister themselves when the dropped state
        # is collected; the spill report is explicit bookkeeping, clear it
        from geomesa_tpu.obs import devmon

        devmon.ledger().clear_spills(type_name)
        self.metrics.counter("store.device.evictions").inc()

    def query_iter(
        self,
        type_name: str,
        q: "Query | str | ast.Filter | None" = None,
        batch_rows: int = 65536,
        **kwargs,
    ):
        """Stream query results as bounded ``FeatureTable`` batches — the
        GeoTools feature-reader / ``CloseableIterator`` role
        (``GeoMesaDataStore.scala:390``): exports and clients page through
        results without holding one giant formatted payload."""
        if batch_rows < 1:
            raise ValueError("batch_rows must be >= 1")
        # run the query eagerly so schema/filter errors raise at the call
        # site, not at the consumer's first next()
        t = self.query(type_name, q, **kwargs).table

        def _gen():
            for lo in range(0, len(t), batch_rows):
                yield t.take(np.arange(lo, min(lo + batch_rows, len(t))))

        return _gen()

    def _batch_gate(self, st: _TypeState, want_bbox: bool):
        """Shared gate for the batched device fan-outs (count_many /
        density_many): coherent snapshot, device residency, and the
        conditions under which loose batched execution is NOT equivalent
        (hot-tier rows, TTL masking, no resident columns). Returns
        (main, main_n, point_state, bbox_state, batchable, perm) — ``perm``
        maps the point state's sorted positions to original rows (the
        exact-count correction path needs it)."""
        main, indices, backend_state, _stats, delta_table = st.snapshot()
        main_n = 0 if main is None else len(main)
        dev = bbox_dev = None
        perm = None
        if isinstance(self.backend, TpuBackend) and self._device_available():
            dev, dev_name = TpuBackend.point_state(backend_state)
            if dev is not None and dev_name in (indices or {}):
                perm = indices[dev_name].perm
            if dev is None and want_bbox:
                # extended-geometry store: loose tests are bbox overlaps
                bbox_dev, bbox_name = TpuBackend.bbox_state(backend_state)
                if bbox_dev is not None and bbox_name in (indices or {}):
                    perm = indices[bbox_name].perm
        batchable = not (
            (dev is None and bbox_dev is None)
            or delta_table is not None
            or main_n == 0
            # TTL masking is injected per-query in query(); loose batched
            # passes would include expired rows — take the exact path
            or self._age_off_ttl_ms(st.sft) is not None
        )
        return main, main_n, dev, bbox_dev, batchable, perm

    def _batch_payloads(self, st: _TypeState, qs, overlap: bool, viewport=None):
        """Shared batchability loop: which queries are pure bbox+time
        conjunctions on the default geom/date fields (anything else has
        residual semantics the loose kernels can't honor) → their int-domain
        payloads. ``viewport``: intersect every query's spatial bounds with
        this (xmin, ymin, xmax, ymax) box — rows outside it must not match
        (the density viewport). Returns [(query idx, payload | None,
        exactable)] — ``exactable`` is False when packing WIDENED the
        payload (more boxes/intervals than the kernel slots), i.e. the int
        result is a superset even beyond edge-bucket quantization and
        cannot be corrected to exact."""
        from dataclasses import replace as _replace

        from geomesa_tpu.filter.bounds import extract as _extract
        from geomesa_tpu.ops.refine import MAX_BOXES, MAX_TIMES

        pending: list[tuple[int, tuple | None, bool]] = []
        for i, q in enumerate(qs):
            f = q.resolved_filter()
            if (
                not _pure_bbox_time(f, st.sft)
                or _semantic_hints(q)
                or q.auths is not None
                or q.limit is not None
                or q.start_index is not None
            ):
                continue
            e = _extract(f, st.sft.geom_field, st.sft.dtg_field)
            if viewport is not None and not e.disjoint:
                vx1, vy1, vx2, vy2 = viewport
                boxes = e.boxes if e.boxes is not None else [
                    (-180.0, -90.0, 180.0, 90.0)
                ]
                clipped = []
                for x1, y1, x2, y2 in boxes:
                    nx1, ny1 = max(x1, vx1), max(y1, vy1)
                    nx2, ny2 = min(x2, vx2), min(y2, vy2)
                    if nx1 <= nx2 and ny1 <= ny2:
                        clipped.append((nx1, ny1, nx2, ny2))
                if not clipped:
                    pending.append((i, None, True))
                    continue
                e = _replace(e, boxes=clipped)
            payload = (
                None
                if e.disjoint
                else self.backend._payload(st.sft, e, overlap=overlap)
            )
            n_boxes = len(e.boxes) if e.boxes is not None else 1
            n_times = len(e.intervals) if e.intervals is not None else 1
            exactable = n_boxes <= MAX_BOXES and n_times <= MAX_TIMES
            pending.append((i, payload, exactable))
        return pending

    @dispatch_budget(2, signatures=("*:rows",))
    def select_many(self, type_name: str, queries) -> list:
        """Batched row retrieval: results identical to
        ``[self.query(type_name, q) for q in queries]`` with the whole
        batch's device work in TWO dispatches — a planned pair-count pass
        that sizes the gather exactly, then one block gather serving every
        query (``TpuBackend.select_many_positions``). Dispatch RTT
        amortizes across the batch the way the fused count/density paths
        do (SURVEY.md §2.20 P4; the reference's BatchScanner multi-range
        scan, ``AccumuloQueryPlan.scala:136`` role). Queries that don't
        fit the batched shape — sub-plan unions, non-resident indexes, an
        open device circuit, the oracle backend, per-query timeouts —
        transparently run per-query instead, same results either way.
        Point AND extended-geometry (XZ bbox-layout) stores both batch.
        """
        queries = list(queries)
        # ONE batch span; every query lands a per-query child span (the
        # fallback path through query() and the batched tail both open one)
        with obs.span("select_many", n_queries=len(queries)):
            # one SHARED roundtrip ledger for the whole batch: the batched
            # dispatches charge every member query's signature (the
            # coalescer attribution contract); per-query fallbacks open
            # their own nested ledger inside query()
            from geomesa_tpu.obs import ledger as _rtledger

            with _rtledger.roundtrip():
                return self._run_select_many(type_name, queries)

    def _run_select_many(self, type_name: str, queries) -> list:
        import time as _time

        st = self._state(type_name)
        qs_raw = [
            Query(filter=q) if isinstance(q, (str, ast.Filter)) or q is None
            else q
            for q in queries
        ]
        qs = (
            [self._intercept(type_name, st.sft, q) for q in qs_raw]
            if self._interceptors
            else list(qs_raw)
        )
        ttl = self._age_off_ttl_ms(st.sft)
        if ttl is not None and st.sft.dtg_field is not None:
            from dataclasses import replace as _replace

            qs = [
                _replace(
                    q,
                    filter=ast.And((
                        q.resolved_filter(),
                        ast.Compare(
                            ">=", st.sft.dtg_field,
                            _ttl_cutoff_ms(ttl, q.hints.get("now_ms")),
                        ),
                    )),
                )
                for q in qs
            ]

        def _fallback(i):
            # the ORIGINAL query object: query() runs interceptors itself,
            # so handing it the intercepted copy would intercept twice
            return self.query(type_name, qs_raw[i])

        if (
            st.total_rows == 0
            or isinstance(self.backend, OracleBackend)
            or not self._device_available()
        ):
            return [_fallback(i) for i in range(len(qs))]
        # audit epoch for the batched tail (the coalescer's shared
        # dispatches and the sharded view's per-member batches both land
        # here): read BEFORE the snapshot so a racing write abstains
        from geomesa_tpu.obs import audit as _obsaudit

        audit_epoch = None
        if not _obsaudit.in_shadow() and (
                _obsaudit.ENABLED
                or any(q.hints.get("audit") for q in qs)):
            audit_epoch = st.data_epoch()

        t_start = _time.perf_counter()
        main, indices, backend_state, stats, delta_table = st.snapshot()
        main_n = 0 if main is None else len(main)
        if main_n == 0 or not backend_state:
            return [_fallback(i) for i in range(len(qs))]

        planned = []
        with obs.span("plan", queries=len(qs)):
            for q in qs:
                cache_key = None if ttl is not None else self._plan_cache_key(q)
                cached = self._plan_lookup(st, indices, cache_key)
                if cached is None:
                    planner = QueryPlanner(st.sft, indices, stats)
                    cached = planner.plan(
                        q, under_burn=self._under_burn(type_name))
                    self._plan_store(st, indices, cache_key, cached)
                planned.append((q, *cached))  # (q, plan, f, info)
        plan_ms = (_time.perf_counter() - t_start) * 1000.0

        results: list = [None] * len(qs)
        groups: dict[str, list[int]] = {}
        for i, (q, plan, f, info) in enumerate(planned):
            dev = backend_state.get(info.index_name)
            if (
                info.sub_plans
                or dev is None
                or getattr(dev, "kind", None) not in ("points", "bboxes")
                or q.hints.get("timeout") is not None
                or q.hints.get("deadline") is not None
            ):
                results[i] = _fallback(i)
            else:
                groups.setdefault(info.index_name, []).append(i)

        from geomesa_tpu.store.reduce import reduce_result

        for index_name, idxs in groups.items():
            dev = backend_state[index_name]
            index = indices[index_name]
            try:
                pos_lists = self.backend.select_many_positions(
                    dev, index,
                    [planned[i][3].extraction for i in idxs],
                    [planned[i][1].intervals for i in idxs],
                )
            except Exception as e:  # noqa: BLE001 — failover, re-raise rest
                if not self._is_device_error(e):
                    raise
                self._trip_device_circuit(e)
                self.metrics.counter("store.query.device_failovers").inc()
                for i in idxs:
                    results[i] = _fallback(i)
                continue
            self._note_device_ok()
            # audit decomposition: the shared device dispatches split
            # evenly across the batch; each query's host tail (residual +
            # reduce) is timed individually — a later query's audit row
            # must not absorb earlier queries' reduce time
            shared_ms = (_time.perf_counter() - t_start) * 1000.0 - plan_ms
            for i, positions in zip(idxs, pos_lists):
                q, plan, f, info = planned[i]
                tq0 = _time.perf_counter()
                # per-query child span: the host tail each batched query
                # pays individually (residual refine + reduce); the shared
                # dispatch spans above cover the device half
                with obs.span("query", batch_index=i, batched=True):
                    with obs.span("refine", candidates=len(positions)):
                        rows = index.perm[positions]
                        # exact residual: same contract as backend.select
                        # (int superset culled on device, f64 filter
                        # settles the rest)
                        if len(rows) and not isinstance(f, ast.Include):
                            rows = rows[ast.residual_mask(f, main, rows)]
                        rows = np.sort(rows)
                        if delta_table is not None:
                            drows = np.nonzero(f.mask(delta_table))[0]
                            rows = np.concatenate([rows, drows + main_n])
                    with obs.span("reduce", rows=len(rows)):
                        table = _take_combined(st.sft, main, main_n,
                                               delta_table, rows)
                        tbl, rws, density, stats_out, bin_data = reduce_result(
                            st.sft, table, rows, q)
                    tail_ms = (_time.perf_counter() - tq0) * 1000.0
                    from geomesa_tpu.obs import devmon as _devmon

                    self._audit(type_name, q, plan_ms / len(qs),
                                shared_ms / len(idxs) + tail_ms, len(tbl),
                                sig=_devmon.plan_signature(info, q))
                results[i] = QueryResult(
                    tbl, rws, info, density=density, stats=stats_out,
                    bin_data=bin_data,
                )
                if (audit_epoch is not None
                        and _obsaudit.eligible_select(q)
                        and (q.hints.get("audit")
                             or (_obsaudit.ENABLED and _obsaudit.sampled()))):
                    _obsaudit.get().enqueue_select(
                        self, type_name, q, audit_epoch, tbl)
        return results

    def count_many(self, type_name: str, queries, loose: bool = True):
        """Batched counts for many queries in ONE device pass.

        The multi-query fan-out path (SURVEY.md §2.20 P4): all bbox+time
        queries are evaluated against the resident columns in a single fused
        scan (``ops.pallas_kernels.batched_count``). ``loose`` counts in the
        int key domain without the exact residual refine — the reference's
        loose-bbox hint semantics (``QueryHints`` ``geomesa.loose.bbox``).

        ``loose=False`` STAYS batched on both store kinds: the fused int
        count plus a device gather of the spatial edge-bucket candidates
        (the only rows where the int superset can diverge from f64 —
        interior buckets of a closed box are f64-certain, and strict int
        inequality on an overlap axis implies the f64 inequality)
        re-tested host-side against the full filter AST. Mixed-filter
        queries, widened payloads, truncated candidate lanes, or a
        non-empty hot tier fall back to exact per-query execution.
        """
        st = self._state(type_name)
        qs = [
            Query(filter=q) if isinstance(q, (str, ast.Filter)) or q is None else q
            for q in queries
        ]
        # interceptors see every query exactly as query() would show them
        if self._interceptors:
            qs = [self._intercept(type_name, st.sft, q) for q in qs]

        def _exact(q):
            return self.query(type_name, q).count

        # audit epoch for batched EXACT counts (loose counts are a
        # documented int-domain superset — comparing them to the exact
        # referee would alarm by design, so only loose=False audits);
        # read BEFORE the _batch_gate snapshot so racing writes abstain
        from geomesa_tpu.obs import audit as _obsaudit

        audit_epoch = None
        if (not loose and not _obsaudit.in_shadow()
                and (_obsaudit.ENABLED
                     or any(q.hints.get("audit") for q in qs))):
            audit_epoch = st.data_epoch()

        main, main_n, dev, bbox_dev, batchable, perm = self._batch_gate(
            st, want_bbox=True
        )
        # exact batched mode needs resident columns + a position→row map
        # for the edge-candidate residual; anything else goes per-query
        if not batchable or (
            not loose and (perm is None or main is None)
        ):
            return [_exact(q) for q in qs]
        pending = self._batch_payloads(
            st, qs, overlap=bbox_dev is not None
        )

        out: list = [None] * len(qs)
        live = [
            (i, p) for i, p, ok in pending
            if p is not None and (loose or ok)
        ]
        for i, p, ok in pending:
            if p is None:
                out[i] = 0  # disjoint filter: exactly zero either mode
        if live:
            import jax.numpy as jnp

            from geomesa_tpu.parallel.query import (
                cached_batched_count_step,
                cached_batched_overlap_step,
            )

            boxes = np.stack([p[0] for _, p in live])
            times = np.stack([p[1] for _, p in live])
            # one fused scan over the mesh-sharded columns, counts
            # psum-merged over the data axis (P4 + P6); the query batch must
            # divide the mesh query axis — pad with duplicates and discard
            from geomesa_tpu.obs.jaxmon import count_h2d
            from geomesa_tpu.parallel.mesh import pad_query_axis

            mesh = self.backend._get_mesh()
            (boxes, times), _ = pad_query_axis(mesh, boxes, times)
            count_h2d(boxes, times)  # per-batch payload staging
            edge_pos = edge_hits = None
            cap = 512
            try:
                if not loose:
                    # ONE fused pass returns counts AND the boundary
                    # candidates — exact mode costs the same device scan
                    from geomesa_tpu.parallel.query import (
                        cached_batched_edge_gather_step,
                    )

                    gather = cached_batched_edge_gather_step(
                        mesh, cap, overlap=bbox_dev is not None
                    )
                    col_args = (bbox_dev or dev).spatial_cols()
                    counts, edge_pos, edge_hits = gather(
                        *col_args, jnp.int32(main_n),
                        jnp.asarray(boxes), jnp.asarray(times),
                    )
                    counts = np.asarray(counts)
                    edge_pos = np.asarray(edge_pos)   # (Qp, D, cap)
                    edge_hits = np.asarray(edge_hits)  # (Qp, D)
                elif bbox_dev is not None:
                    step = cached_batched_overlap_step(mesh, with_time=True)
                    counts = np.asarray(
                        step(
                            *bbox_dev.spatial_cols(),
                            jnp.int32(main_n),
                            jnp.asarray(boxes), jnp.asarray(times),
                        )
                    )
                else:
                    c = dev.cols
                    step = cached_batched_count_step(mesh)
                    counts = np.asarray(
                        step(
                            c["x"], c["y"], c["bins"], c["offs"],
                            jnp.int32(main_n),
                            jnp.asarray(boxes), jnp.asarray(times),
                        )
                    )
            except Exception as e:  # noqa: BLE001 — failover to exact host path
                if not self._is_device_error(e):
                    raise
                self._trip_device_circuit(e)
                self.metrics.counter("store.query.device_failovers").inc()
                counts = None
            if counts is not None:
                self._note_device_ok()
                if loose:
                    for k, (i, _) in enumerate(live):
                        out[i] = int(counts[k])
                else:
                    # exact mode: subtract edge-bucket candidates failing
                    # the full f64 filter AST (a handful of rows per query)
                    cap = edge_pos.shape[2]
                    for k, (i, _) in enumerate(live):
                        if (edge_hits[k] > cap).any():
                            continue  # truncated lanes → per-query exact
                        cand = np.concatenate([
                            edge_pos[k, d, : edge_hits[k, d]]
                            for d in range(edge_pos.shape[1])
                        ]).astype(np.int64)
                        corr = 0
                        if len(cand):
                            rows = perm[cand]
                            f = qs[i].resolved_filter()
                            m = ast.residual_mask(f, main, rows)
                            corr = int((~m).sum())
                        out[i] = int(counts[k]) - corr
        # batched queries still hit metrics + the audit trail
        for i, _p, _ok in pending:
            if out[i] is None:
                continue  # device failover: the exact path audits these
            self.metrics.counter("store.queries").inc()
            self._audit(type_name, qs[i], 0.0, 0.0, out[i])
            if (audit_epoch is not None
                    and _obsaudit.eligible_select(qs[i])
                    and (qs[i].hints.get("audit")
                         or (_obsaudit.ENABLED and _obsaudit.sampled()))):
                _obsaudit.get().enqueue_count(
                    self, type_name, qs[i], audit_epoch, int(out[i]))
        for i, q in enumerate(qs):
            if out[i] is None:
                out[i] = _exact(q)
        return out

    # -- distributed SQL aggregation (GROUP BY on the mesh) ------------------

    _AGG_MAX_GROUPS = 65536  # beyond this the host fold is the better engine

    def _agg_group_ids(self, main, group_by):
        """Factorize the GROUP BY key columns over ``main`` → (int32 group id
        per row, group keys as tuples in first-occurrence row order — the
        order the host fold produces, so results are order-identical)."""
        n = len(main)
        if not group_by:
            return np.zeros(n, dtype=np.int32), [()]
        ids: list[np.ndarray] = []
        vocabs: list[list] = []
        for g in group_by:
            col = main.columns[g]
            vals = col.values
            if (
                isinstance(vals, np.ndarray)
                and vals.dtype.kind == "f"
                and np.isnan(vals).any()
            ):
                # host parity is impossible: the host fold's per-object dict
                # makes EVERY NaN key its own group (nan != nan), while
                # np.unique collapses them — decline the device path
                raise ValueError("NaN GROUP BY keys take the host fold")
            # string columns: the cached dictionary codes (ArrowDictionary
            # role) replace an O(n log n) OBJECT-array sort with int32 work —
            # the dominant cost of cold aggregation staging at 10M+ rows.
            # Only when every value is a SET STRING: the dictionary maps
            # invalid AND stray non-str values to "", which would collide
            # with a real "" / diverge from the host fold's raw-value keys
            d = None
            if col.valid is None and all(type(v) is str for v in vals):
                d = col.dictionary()
            if d is not None:
                vocab, codes = d
                vocabs.append(list(vocab))
                ids.append(codes.astype(np.int64))
                continue
            try:
                uniq, inv = np.unique(vals, return_inverse=True)
                vocabs.append(list(uniq))
                ids.append(inv.astype(np.int64))
            except TypeError:
                # object column with None/mixed values: dict factorize
                seen: dict = {}
                inv = np.empty(n, dtype=np.int64)
                vocab: list = []
                for i, v in enumerate(vals):
                    j = seen.get(v)
                    if j is None:
                        j = seen[v] = len(vocab)
                        vocab.append(v)
                    inv[i] = j
                vocabs.append(vocab)
                ids.append(inv)
        code = ids[0]
        for k in range(1, len(ids)):
            base = len(vocabs[k]) + 1
            if int(code.max(initial=0)) > (2**62) // base:
                raise ValueError("group key space overflows the device path")
            code = code * base + ids[k]
        uniq_codes, first, inv = np.unique(
            code, return_index=True, return_inverse=True
        )
        order = np.argsort(first, kind="stable")
        rank = np.empty(len(order), dtype=np.int64)
        rank[order] = np.arange(len(order))
        gid = rank[inv].astype(np.int32)
        keys = []
        for ci in uniq_codes[order]:
            c = int(ci)
            parts = []
            for k in range(len(ids) - 1, 0, -1):
                base = len(vocabs[k]) + 1
                parts.append(vocabs[k][c % base])
                c //= base
            parts.append(vocabs[0][c])
            keys.append(tuple(reversed(parts)))
        return gid, keys

    def _agg_residency(self, dev, main, perm, group_by, value_cols,
                       type_name: str = "?", index_name: str = "?"):
        """Stage (or fetch from ``dev.agg_cache``) the group-id column and a
        stacked (V, N) f64 value matrix into the mesh layout, aligned with
        ``dev``'s sharded x/y columns (same perm, same padding). The cache
        lives on the state object, so compactions that rebuild the layout
        drop it automatically. Raises TypeError/ValueError for columns the
        f64 device fold cannot carry (strings, geometries) — callers fall
        back to the host fold."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from geomesa_tpu.parallel.mesh import (
            DATA_AXIS,
            data_shards,
            pad_rows,
            shard_columns,
        )
        from geomesa_tpu.store.backends import JOIN_BLOCK

        mesh = self.backend._get_mesh()
        # the process-level pool budget covers agg staging too: every
        # device allocation below asks for room first (evicting colder
        # buffers), and a refusal raises ValueError → the host fold
        padded_est = pad_rows(
            max(len(main), data_shards(mesh)), data_shards(mesh), JOIN_BLOCK
        )

        def _room(nbytes: int, what: str) -> None:
            if not self.backend.pool.ensure_room(int(nbytes)):
                raise ValueError(f"device budget refuses agg {what}")

        gkey = ("gid", tuple(group_by or ()))
        cached = dev.agg_cache.get(gkey)
        if cached is None:
            _room(padded_est * 4, "group-id staging")
            gid_orig, keys = self._agg_group_ids(main, group_by)
            if len(keys) > self._AGG_MAX_GROUPS:
                raise ValueError("group cardinality beyond the device path")
            cols, _, _ = shard_columns(
                mesh, {"gid": gid_orig[perm].astype(np.int32)},
                multiple=JOIN_BLOCK,
            )
            cached = (cols["gid"], gid_orig, keys)
            dev.agg_cache[gkey] = cached
            # agg staging is device residency too: ledger it under the
            # "agg" column group (dies with `dev`, so unregistration rides
            # the same finalizer as the spatial columns); the pool entry
            # for (type, index) absorbs the bytes — same owner, same pins
            from geomesa_tpu.obs import devmon
            from geomesa_tpu.store.bufferpool import register_residency

            register_residency(
                self.backend.pool, type_name, index_name, devmon.GROUP_AGG,
                int(cols["gid"].nbytes), owner=dev)
        rowid = dev.agg_cache.get(("rowid",))
        if rowid is None:
            _room(padded_est * 4, "row-id staging")
            # original row index per lane: the device computes each group's
            # first MATCHING row (segment_min), which orders the output
            # groups exactly as the host fold's first-occurrence-over-
            # filtered-rows construction does
            rcols, _, _ = shard_columns(
                mesh, {"rowid": np.asarray(perm, dtype=np.int32)},
                multiple=JOIN_BLOCK, pad_value=np.iinfo(np.int32).max,
            )
            rowid = rcols["rowid"]
            dev.agg_cache[("rowid",)] = rowid
            from geomesa_tpu.obs import devmon
            from geomesa_tpu.store.bufferpool import register_residency

            register_residency(
                self.backend.pool, type_name, index_name, devmon.GROUP_AGG,
                int(rowid.nbytes), owner=dev)
        # value columns cache PER COLUMN (one device + one host copy each,
        # however many SELECT-list combinations arrive); the per-request
        # (V, N) matrix is a device-side concat — no host↔device transfer
        sharding = NamedSharding(mesh, PartitionSpec(None, DATA_AXIS))
        shards = data_shards(mesh)
        padded = pad_rows(max(len(main), shards), shards, JOIN_BLOCK)
        per_dev, per_host = [], []
        for c in value_cols:
            got = dev.agg_cache.get(("val", c))
            if got is None:
                _room(padded * 8, "value-column staging")
                col = main.columns[c]
                v = np.asarray(col.values, dtype=np.float64).copy()
                if col.valid is not None:
                    v[~col.valid] = np.nan
                pv = np.zeros((1, padded), dtype=np.float64)
                pv[0, : len(main)] = v[perm]
                got = (jax.device_put(pv, sharding), v)
                dev.agg_cache[("val", c)] = got
                from geomesa_tpu.obs import devmon
                from geomesa_tpu.obs.jaxmon import count_h2d
                from geomesa_tpu.store.bufferpool import register_residency

                # pool warm-up staging, attributed to the POOL: the query
                # that happened to trigger the miss must not absorb these
                # bytes in its devprof h2d split (satellite red/green in
                # tests/test_geoblocks.py)
                count_h2d(pv, label="pool")
                register_residency(
                    self.backend.pool, type_name, index_name,
                    devmon.GROUP_AGG, int(got[0].nbytes), owner=dev)
            per_dev.append(got[0])
            per_host.append(got[1])
        if per_dev:
            import jax.numpy as jnp

            dv = jax.device_put(jnp.concatenate(per_dev, axis=0), sharding)
        else:
            dv = jax.device_put(
                np.zeros((0, padded), dtype=np.float64), sharding
            )
        hv = (
            np.stack(per_host)
            if per_host
            else np.zeros((0, len(main)), dtype=np.float64)
        )
        return cached, rowid, dv, hv

    # -- GeoBlocks helpers (ops/geoblocks.py) --------------------------------

    @staticmethod
    def _agg_cache_key(q, group_by, value_cols):
        """Exact-repeat aggregation cache key: the literal predicate text
        plus GROUP BY and value columns. None = uncacheable (hints, auths,
        paging, or an un-serializable filter)."""
        if (_semantic_hints(q) or q.auths is not None
                or q.limit is not None or q.start_index is not None):
            return None
        base = DataStore._plan_cache_key(q)
        if base is None:
            return None
        return (base[0], tuple(group_by or ()), tuple(value_cols or ()))

    def _pyramid_extraction(self, st, q):
        """The query's Extraction when it can ride the pyramid: a pure
        bbox+time conjunction over the default geom/date fields with at
        most ONE box and ONE interval (the interior/boundary decomposition
        is per-rectangle). None = take the fused or host path."""
        f = q.resolved_filter()
        if (
            not _pure_bbox_time(f, st.sft)
            or _semantic_hints(q)
            or q.auths is not None
            or q.limit is not None
            or q.start_index is not None
        ):
            return None
        from geomesa_tpu.filter.bounds import extract as _extract

        e = _extract(f, st.sft.geom_field, st.sft.dtg_field)
        if e.boxes is not None and len(e.boxes) != 1:
            return None
        if e.intervals is not None and len(e.intervals) != 1:
            return None
        return e

    def _pyramid(self, st: _TypeState, type_name: str, main, group_by,
                 value_cols, main_epoch: int):
        """The (group_by, value_cols) pre-aggregation pyramid for the
        CURRENT main tier, built lazily once per rebuild epoch (an O(n)
        host pass — one stable sort — amortized over every subsequent
        aggregate). None when the shape can't ride: non-point geometries,
        string/geometry value columns, group cardinality or byte cap
        exceeded — the failure is remembered per epoch so it isn't
        retried per query."""
        pkey = (tuple(group_by or ()), tuple(value_cols))
        with st.lock:
            cached = st.pyramids.get(pkey)
        if cached is not None:
            pyr, stamp = cached
            if stamp == main_epoch:
                if pyr is not None and pyr.device.get("cnt") is not None:
                    # a recover()-path backend.load parked this pyramid's
                    # pool entry in the donation stash (release keeps
                    # same-fingerprint entries) while the mirror kept
                    # serving from st.pyramids — re-admit it: stash bytes
                    # are reclaimable spare capacity, these are working
                    # set and must stay budget-accounted and evictable
                    pool = getattr(self.backend, "pool", None)
                    if pool is not None:
                        pool.take_donated(
                            type_name, _pyramid_index_name(pkey),
                            main_epoch,
                            on_evict=_pyramid_evictor(st, pkey, pyr))
                return pyr  # pyr may be None: the remembered failure
        from geomesa_tpu.ops.geoblocks import AggPyramid

        import time as _time

        t0 = _time.perf_counter()
        pyr = None
        try:
            col = main.geom_column() if st.sft.geom_field else None
            if col is None or col.x is None:
                raise ValueError("pyramid needs point geometries")
            gid_orig, keys = self._agg_group_ids(main, group_by)
            if len(keys) > self._AGG_MAX_GROUPS:
                raise ValueError("group cardinality beyond the pyramid")
            from geomesa_tpu.curve.binned_time import BinnedTime
            from geomesa_tpu.curve.normalize import (
                lat as norm_lat,
                lon as norm_lon,
            )
            from geomesa_tpu.store.backends import REFINE_PRECISION

            xi = norm_lon(REFINE_PRECISION).normalize(col.x).astype(np.int64)
            yi = norm_lat(REFINE_PRECISION).normalize(col.y).astype(np.int64)
            if st.sft.dtg_field:
                bins, _offs = BinnedTime(
                    st.sft.z3_interval).to_bin_and_offset(main.dtg_millis())
            else:
                bins = np.zeros(len(main), dtype=np.int64)
            vals = []
            for c in value_cols:
                cv = main.columns[c]
                v = np.asarray(cv.values, dtype=np.float64).copy()
                if cv.valid is not None:
                    v[~cv.valid] = np.nan
                vals.append(v)
            vmat = (np.stack(vals) if vals
                    else np.zeros((0, len(main)), dtype=np.float64))
            pyr = AggPyramid(xi, yi, bins, gid_orig, keys, vmat,
                             epoch=main_epoch)
        except (TypeError, ValueError):
            pyr = None
        if pyr is not None:
            self._pyramid_mirror(st, type_name, pkey, pyr, main_epoch)
            self.metrics.histogram("store.agg.pyramid_build_ms").update(
                (_time.perf_counter() - t0) * 1000.0)
        with st.lock:
            if st.epoch == main_epoch:
                st.pyramids[pkey] = (pyr, main_epoch)
        return pyr

    def _pyramid_mirror(self, st, type_name, pkey, pyr, main_epoch) -> None:
        """Device mirror of the finest level's count partials — the layout
        a fused device kernel reads — registered with the residency ledger
        and pinned/evictable through the buffer pool. The staging bytes
        are POOL traffic, not any query's (jaxmon ``label="pool"``).
        Best-effort: an open device circuit just skips the mirror."""
        pool = getattr(self.backend, "pool", None)
        if pool is None or not self._device_available():
            return
        try:
            import jax

            from geomesa_tpu.obs import devmon
            from geomesa_tpu.obs.jaxmon import count_h2d

            host = pyr.levels[-1].cnt.astype(np.int32)
            if not pool.ensure_room(int(host.nbytes)):
                return  # pool budget refuses the mirror: host-only pyramid
            count_h2d(host, label="pool")
            dev = jax.device_put(host)
            pyr.device["cnt"] = dev
            devmon.ledger().register(
                type_name, "geoblocks", devmon.GROUP_PYRAMID,
                int(dev.nbytes), owner=pyr)
            # pool key is per-PYRAMID (pkey = group_by + value_cols), not
            # per-type: a second aggregation shape registering under the
            # same key would REPLACE the first's entry (bufferpool
            # register semantics) while st.pyramids still held its mirror
            # resident — bytes in HBM invisible to the budget, evictor
            # lost
            pool.register(
                type_name, _pyramid_index_name(pkey), devmon.GROUP_PYRAMID,
                int(dev.nbytes), owner=pyr, fingerprint=main_epoch,
                on_evict=_pyramid_evictor(st, pkey, pyr))
        except Exception as e:  # noqa: BLE001 — mirror is optional
            if not self._is_device_error(e):
                raise
            self._trip_device_circuit(e)

    def _pyramid_answer(self, q, st, main, delta, pyr, e, value_cols,
                        group_by):
        """One exact grouped aggregate from the pyramid: interior partials
        + boundary rows refined against the full f64 filter AST + the
        delta fold — the same correction machinery the fused device path
        feeds (:meth:`_assemble_agg`)."""
        from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon
        from geomesa_tpu.store.backends import REFINE_PRECISION, time_quads

        box = None
        if e.boxes is not None:
            nlon = norm_lon(REFINE_PRECISION)
            nlat = norm_lat(REFINE_PRECISION)
            x1, y1, x2, y2 = e.boxes[0]
            box = (int(nlon.normalize(x1)), int(nlon.normalize(x2)),
                   int(nlat.normalize(y1)), int(nlat.normalize(y2)))
        window = None
        quads = time_quads(st.sft, e.intervals)
        if quads is not None:
            blo, olo, bhi, ohi = (int(v) for v in quads[0])
            if (blo, olo) > (bhi, ohi):  # clamped to unsatisfiable
                return self._assemble_agg_empty(value_cols)
            window = (blo, olo, bhi, ohi)
        cnt, first, vcnt, vsum, vmin, vmax, brows = pyr.answer(box, window)
        return self._assemble_agg(
            q, main, delta, pyr.keys, value_cols,
            cnt, first, vcnt, vsum, vmin, vmax,
            np.sort(brows), pyr.gid, pyr.host_vals, group_by,
        )

    @dispatch_budget(1, signatures=("*:stats",))
    def aggregate_many(self, type_name: str, queries, group_by=None,
                       value_cols=(), now_ms: int | None = None):
        """See :meth:`_aggregate_many_impl` (the engine). This wrapper
        adds the correctness-audit hook: sampled (or hint-tagged)
        answered lanes enqueue a shadow grouped-agg comparison against
        the independent referee, stamped with the data epoch the engine
        read BEFORE its snapshot (abstain-on-write semantics)."""
        from geomesa_tpu.obs import audit as _obsaudit

        box: dict = {}
        out = self._aggregate_many_impl(
            type_name, queries, group_by=group_by, value_cols=value_cols,
            now_ms=now_ms, audit_box=box)
        if "epoch" in box and not _obsaudit.in_shadow():
            qs = box["qs"]
            for i, r in enumerate(out):
                if r is None:
                    continue  # declined: the caller's host fold answers
                q = qs[i]
                if (_obsaudit.eligible_agg(q)
                        and (q.hints.get("audit")
                             or (_obsaudit.ENABLED and _obsaudit.sampled()))):
                    _obsaudit.get().enqueue_agg(
                        self, type_name, q, box["epoch"], r,
                        group_by, value_cols,
                        cutoff_ms=box.get("cutoff_ms"))
        return out

    def _aggregate_many_impl(self, type_name: str, queries, group_by=None,
                             value_cols=(), now_ms: int | None = None,
                             audit_box: dict | None = None):
        """Batched grouped aggregation on the mesh: ONE fused pass computes,
        per query, COUNT(*) plus per-value-column count/sum/min/max for
        every GROUP BY key — a per-shard segment-reduce merged across the
        data axis with psum (counts/sums) and pmin/pmax (extrema). The
        distributed relational-aggregation role the reference delegates to
        Spark (``geomesa-spark-sql/.../GeoMesaRelation.scala:47,94``,
        SURVEY.md §2.14).

        Returns one entry per query: ``None`` when that query cannot ride
        the mesh (residual filters beyond bbox+time, hints/auths/limits,
        truncated edge lanes, non-numeric value columns, TTL stores, device
        trouble) — callers run their host fold for those — else
        ``{"groups": [key tuples], "count": (G,) int64, "cols": {col:
        {"count": (G,) int64, "sum"/"min"/"max": (G,) f64 (NaN = empty)}}}``
        with groups in first-occurrence row order (host-fold parity) and
        only groups with at least one matching row included.

        Exactness: the device folds the int-domain interior; edge-bucket
        rows (the only int/f64 divergence sites) are EXCLUDED on device,
        re-tested host-side against the full f64 filter AST, and ADDED —
        sound for min/max, unlike subtracting false positives. Pending
        hot-tier (delta) rows are folded host-side, so live stores stay on
        the mesh path. TTL stores stay too: rows strictly below the
        cutoff's quantized unit drop on device, rows AT the ambiguous unit
        ride the boundary gather for an exact-millisecond host re-add
        (``now_ms`` pins the clock for tests). Value sums ride f64 (ints
        beyond 2**53 lose precision — the documented Spark-parity caveat).
        """
        st = self._state(type_name)
        qs = [
            Query(filter=q)
            if isinstance(q, (str, ast.Filter)) or q is None
            else q
            for q in queries
        ]
        if self._interceptors:
            qs = [self._intercept(type_name, st.sft, q) for q in qs]
        out: list = [None] * len(qs)
        group_by = list(group_by) if group_by else None
        value_cols = list(value_cols)
        ttl = self._age_off_ttl_ms(st.sft)
        if ttl is not None and st.sft.dtg_field is None:
            return out
        cutoff_ms = None
        if ttl is not None:
            cutoff_ms = _ttl_cutoff_ms(ttl, now_ms)
        # DATA EPOCH first, snapshot second (_TypeState.data_epoch): a
        # mutation landing between the two leaves cache entries stamped
        # with a pair that never recurs — a miss, never a stale hit
        epoch = st.data_epoch()
        if audit_box is not None:
            # the audit wrapper stamps its shadow checks with the SAME
            # pre-snapshot epoch (and the normalized/intercepted queries)
            audit_box["epoch"] = epoch
            audit_box["qs"] = qs
            audit_box["cutoff_ms"] = cutoff_ms
        main, indices, backend_state, _stats, delta = st.snapshot()
        main_n = 0 if main is None else len(main)
        if main_n == 0:
            return out
        for c in (group_by or []) + value_cols:
            if c not in main.columns:
                return out

        # -- GeoBlocks tier (ops/geoblocks.py): the epoch-validated query
        # cache serves exact repeats outright; eligible misses route to
        # the pre-aggregation pyramid when the cost-table consult agrees.
        # The oracle backend stays a pure brute-force referee, and TTL
        # stores stay on the fused path (their answers are clock-relative).
        import time as _time

        from geomesa_tpu.obs import audit as _obsaudit
        from geomesa_tpu.obs import devmon as _devmon

        devmon_costs = _devmon.costs()
        # audit-shadow re-executions must not train the gagg route
        # verdict (the same hygiene _audit applies to the cost table)
        _observe_gagg = (
            (lambda *a, **k: None) if _obsaudit.in_shadow()
            else devmon_costs.observe
        )
        cache_ctx = None
        if isinstance(self.backend, TpuBackend) and ttl is None:
            cache_ctx = {"epoch": epoch, "keys": {}}
            for i, q in enumerate(qs):
                key = self._agg_cache_key(q, group_by, value_cols)
                if key is None:
                    continue
                cache_ctx["keys"][i] = key
                hit = self.agg_cache.get(type_name, key, epoch)
                if hit is not None:
                    out[i] = hit
                    self.metrics.counter("store.queries").inc()
                    self.metrics.counter("store.agg.cache_hits").inc()
                    self._audit(type_name, q, 0.0, 0.0,
                                int(hit["count"].sum()))
            from geomesa_tpu.ops import geoblocks as _geoblocks
            from geomesa_tpu.planning.planner import choose_agg_path

            if _geoblocks.enabled() and choose_agg_path(
                    devmon_costs, type_name) == "pyramid":
                pyr = None
                for i, q in enumerate(qs):
                    if out[i] is not None:
                        continue
                    e = self._pyramid_extraction(st, q)
                    if e is None:
                        continue
                    if e.disjoint:
                        out[i] = self._assemble_agg_empty(value_cols)
                        continue
                    if pyr is None:
                        pyr = self._pyramid(st, type_name, main, group_by,
                                            value_cols, epoch[0])
                        if pyr is None:
                            break  # shape can't ride: fused/host paths
                    t0 = _time.perf_counter()
                    res = self._pyramid_answer(
                        q, st, main, delta, pyr, e, value_cols, group_by)
                    if res is None:
                        continue
                    out[i] = res
                    wall = (_time.perf_counter() - t0) * 1000.0
                    total = int(res["count"].sum())
                    self.metrics.counter("store.queries").inc()
                    self.metrics.counter("store.agg.pyramid_served").inc()
                    _observe_gagg(type_name, "gagg:pyramid",
                                  wall_ms=wall, rows=total)
                    self._audit(type_name, q, 0.0, wall, total)
                    key = cache_ctx["keys"].get(i)
                    if key is not None:
                        self.agg_cache.put(type_name, key, epoch, res)
            if all(o is not None for o in out):
                return out

        dev = dev_name = None
        overlap = False
        if isinstance(self.backend, TpuBackend) and self._device_available():
            dev, dev_name = TpuBackend.point_state(backend_state)
            if dev is None:
                # extended-geometry store (XZ layout): the spatial fold is
                # int-bbox OVERLAP — exact for the envelope-semantics BBOX
                # predicate away from edge buckets
                dev, dev_name = TpuBackend.bbox_state(backend_state)
                overlap = dev is not None
        perm = None
        if dev is not None and dev_name in (indices or {}):
            perm = indices[dev_name].perm
        if dev is None or perm is None:
            return out
        try:
            (dev_gid, gid_orig, keys), dev_rowid, dev_vals, host_vals = (
                self._agg_residency(dev, main, perm, group_by, value_cols,
                                    type_name=type_name,
                                    index_name=dev_name or "?")
            )
        except (TypeError, ValueError):
            return out
        G = len(keys)
        # only unanswered lanes pay extraction/packing (cache- and
        # pyramid-served queries are done)
        todo = [i for i in range(len(qs)) if out[i] is None]
        pending = [
            (todo[j], p, ok)
            for j, p, ok in self._batch_payloads(
                st, [qs[i] for i in todo], overlap=overlap)
        ]
        live = [(i, p) for i, p, ok in pending if p is not None and ok]
        for i, p, ok in pending:
            if p is None:
                # provably-disjoint filter: zero rows, no groups
                out[i] = self._assemble_agg_empty(value_cols)
        if not live:
            return out
        t_scan0 = _time.perf_counter()
        import jax.numpy as jnp

        from geomesa_tpu.parallel.mesh import pad_query_axis
        from geomesa_tpu.parallel.query import cached_grouped_agg_step

        mesh = self.backend._get_mesh()
        G_pad = 1 << max(0, (G - 1).bit_length())
        cap = 512
        boxes = np.stack([p[0] for _, p in live])
        times = np.stack([p[1] for _, p in live])
        (boxes, times), _ = pad_query_axis(mesh, boxes, times)
        from geomesa_tpu.obs.jaxmon import count_h2d

        count_h2d(boxes, times)  # per-batch payload staging
        try:
            step = cached_grouped_agg_step(
                mesh, G_pad, len(value_cols), cap,
                with_ttl=cutoff_ms is not None, overlap=overlap,
            )
            ttl_args = ()
            if cutoff_ms is not None:
                from geomesa_tpu.curve.binned_time import BinnedTime

                (cb,), (co,) = BinnedTime(
                    st.sft.z3_interval
                ).to_bin_and_offset(np.array([cutoff_ms]))
                ttl_args = (
                    jnp.asarray(np.array([cb, co], dtype=np.int32)),
                )
            # pool pin: the fused pass reads the resident columns — a
            # pinned buffer is never an eviction victim mid-dispatch
            self.backend.pool.touch(type_name, dev_name)
            with self.backend.pool.pinned(type_name, dev_name):
                res = step(
                    *dev.spatial_cols(), dev_gid, dev_rowid,
                    dev_vals, jnp.int32(main_n), jnp.asarray(boxes),
                    jnp.asarray(times), *ttl_args,
                )
                cnt, first, vcnt, vsum, vmin, vmax, epos, ehits = map(
                    np.asarray, res
                )
        except Exception as e:  # noqa: BLE001 — failover to the host fold
            if not self._is_device_error(e):
                raise
            self._trip_device_circuit(e)
            self.metrics.counter("store.query.device_failovers").inc()
            return out
        self._note_device_ok()
        # cost decomposition: the shared device dispatch splits evenly
        # across the batch; each lane's host assembly is timed on its own
        # (a later lane's observation must not absorb earlier assemblies)
        shared_ms = (_time.perf_counter() - t_scan0) * 1000.0 / len(live)
        for k, (i, _) in enumerate(live):
            if (ehits[k] > cap).any():
                continue  # truncated correction lanes: host fold
            tq0 = _time.perf_counter()
            ecand = np.concatenate(
                [epos[k, d, : ehits[k, d]] for d in range(epos.shape[1])]
            ).astype(np.int64)
            out[i] = self._assemble_agg(
                qs[i], main, delta, keys, value_cols,
                cnt[k, :G].astype(np.int64).copy(),
                first[k, :G].astype(np.int64).copy(),
                vcnt[k, :, :G].astype(np.int64).copy(),
                vsum[k, :, :G].copy(),
                vmin[k, :, :G].copy(),
                vmax[k, :, :G].copy(),
                perm[ecand] if len(ecand) else ecand,
                gid_orig, host_vals, group_by,
                cutoff_ms,
            )
            self.metrics.counter("store.queries").inc()
            # audit the POST-correction total (edge + delta rows included),
            # matching what count_many/density_many record
            self._audit(
                type_name, qs[i], 0.0, 0.0, int(out[i]["count"].sum())
            )
            if cache_ctx is not None:
                key = cache_ctx["keys"].get(i)
                if key is not None:
                    self.agg_cache.put(
                        type_name, key, cache_ctx["epoch"], out[i])
                _observe_gagg(
                    type_name, "gagg:scan",
                    wall_ms=shared_ms
                    + (_time.perf_counter() - tq0) * 1000.0,
                    rows=int(out[i]["count"].sum()))
        return out

    @staticmethod
    def _assemble_agg_empty(value_cols):
        z64 = np.zeros(0, dtype=np.int64)
        zf = np.zeros(0, dtype=np.float64)
        return {
            "groups": [],
            "count": z64,
            "cols": {
                c: {"count": z64, "sum": zf, "min": zf, "max": zf}
                for c in value_cols
            },
        }

    def _assemble_agg(self, q, main, delta, keys, value_cols, cnt, first,
                      vcnt, vsum, vmin, vmax, cand_rows, gid_orig,
                      host_vals, group_by, cutoff_ms=None):
        """Fold the host-side corrections into the pre-aggregated partials
        (device interior OR pyramid interior — both feed this): boundary/
        edge candidate rows re-tested exactly (added, never subtracted;
        ``cutoff_ms`` adds the exact-millisecond TTL check a quantized
        mask cannot make) and pending delta rows (which may introduce new
        group keys). Groups are ordered by their first MATCHING row index
        — identical to the host fold's first-occurrence-over-filtered-rows
        construction (delta rows order after the main tier at
        ``main_n + delta_row``, as in query())."""
        f = q.resolved_filter()
        V = len(value_cols)
        main_n = len(main)

        def _fold_row(g: int, row_order: int, vals_at):
            cnt[g] += 1
            first[g] = min(first[g], row_order)
            for v in range(V):
                x = vals_at(v)
                if x is not None and not np.isnan(x):
                    vcnt[v][g] += 1
                    vsum[v][g] += x
                    vmin[v][g] = min(vmin[v][g], x)
                    vmax[v][g] = max(vmax[v][g], x)

        if len(cand_rows):
            rows = cand_rows
            if f is not None:
                rows = rows[ast.residual_mask(f, main, rows)]
            if cutoff_ms is not None and len(rows):
                rows = rows[main.dtg_millis()[rows] >= cutoff_ms]
            for r in rows:
                _fold_row(int(gid_orig[r]), int(r), lambda v: host_vals[v][r])

        keys = list(keys)
        if delta is not None and len(delta):
            dm = (
                np.ones(len(delta), dtype=bool)
                if f is None
                else np.asarray(f.mask(delta), dtype=bool)
            )
            if cutoff_ms is not None:
                dm &= delta.dtg_millis() >= cutoff_ms
            drows = np.nonzero(dm)[0]
            if len(drows):
                key_pos = {kk: i for i, kk in enumerate(keys)}
                extra_n = 0
                dvals = [delta.columns[c] for c in value_cols]
                gcols = [delta.columns[g].values for g in (group_by or [])]
                for r in drows:
                    kk = tuple(gc[r] for gc in gcols)
                    g = key_pos.get(kk)
                    if g is None:
                        g = key_pos[kk] = len(keys)
                        keys.append(kk)
                        extra_n += 1
                    if g >= len(cnt):
                        grow = g + 1 - len(cnt)
                        cnt = np.concatenate([cnt, np.zeros(grow, np.int64)])
                        first = np.concatenate(
                            [first, np.full(grow, np.iinfo(np.int64).max)]
                        )
                        vcnt = np.concatenate(
                            [vcnt, np.zeros((V, grow), np.int64)], axis=1
                        ) if V else vcnt
                        vsum = np.concatenate(
                            [vsum, np.zeros((V, grow))], axis=1
                        ) if V else vsum
                        vmin = np.concatenate(
                            [vmin, np.full((V, grow), np.inf)], axis=1
                        ) if V else vmin
                        vmax = np.concatenate(
                            [vmax, np.full((V, grow), -np.inf)], axis=1
                        ) if V else vmax
                    _fold_row(
                        g, main_n + int(r),
                        lambda v: (
                            None
                            if dvals[v].valid is not None
                            and not dvals[v].valid[r]
                            else float(dvals[v].values[r])
                        ),
                    )
        # keep only groups with matching rows (host parity: groups are
        # formed FROM the matched rows), ordered by first matching row —
        # the host fold's first-occurrence order; no-GROUP-BY keeps its
        # single group
        if group_by:
            alive = np.nonzero(cnt > 0)[0]
            alive = alive[np.argsort(first[alive], kind="stable")]
        else:
            alive = np.arange(len(cnt))
        cols = {}
        for v, c in enumerate(value_cols):
            mn = vmin[v][alive].astype(np.float64)
            mx = vmax[v][alive].astype(np.float64)
            empty = vcnt[v][alive] == 0
            mn[empty] = np.nan
            mx[empty] = np.nan
            cols[c] = {
                "count": vcnt[v][alive],
                "sum": vsum[v][alive].astype(np.float64),
                "min": mn,
                "max": mx,
            }
        return {
            "groups": [keys[int(i)] for i in alive],
            "count": cnt[alive],
            "cols": cols,
        }

    def density_many(
        self,
        type_name: str,
        queries,
        bbox,
        width: int = 256,
        height: int = 256,
        loose: bool = True,
    ):
        """Batched density grids for many queries in ONE device pass: the
        ``DensityScan`` multi-query fan-out (SURVEY.md §2.20 P4 + P6).
        Every query rasterizes into the SHARED ``bbox`` viewport at
        ``width×height``; returns one (height, width) float64 grid per
        query. Pure bbox+time queries ride the fused device step with grids
        ``psum``-merged over the data axis (query bounds are intersected
        with the viewport, so out-of-viewport rows never count); anything
        else (residual filters, hints incl. ``weight_by``, auths, hot-tier
        rows, extended geometries) falls back to the exact per-query density
        hint path. Like :meth:`count_many`, the batched pass tests in the
        31-bit int key domain (the loose-bbox semantics — boundary-epsilon
        rows may differ from the exact float path); ``loose=False`` forces
        the exact path for every query.
        """
        st = self._state(type_name)
        qs = [
            Query(filter=q) if isinstance(q, (str, ast.Filter)) or q is None else q
            for q in queries
        ]
        if self._interceptors:
            qs = [self._intercept(type_name, st.sft, q) for q in qs]
        width, height = int(width), int(height)  # one coercion for ALL uses
        opts = {"bbox": tuple(bbox), "width": width, "height": height}

        def _exact(q):
            from dataclasses import replace as _replace

            # the shared viewport wins, caller density options (weight_by,
            # ...) survive
            caller = q.hints.get("density")
            merged = {
                **(caller if isinstance(caller, dict) else {}),
                **opts,
            }
            return self.query(
                type_name, _replace(q, hints={**q.hints, "density": merged})
            ).density

        _main, main_n, dev, _bbox_dev, batchable, _perm = self._batch_gate(
            st, want_bbox=False
        )
        if not loose or not batchable or dev is None:
            return [_exact(q) for q in qs]
        pending = self._batch_payloads(
            st, qs, overlap=False, viewport=opts["bbox"]
        )

        out: list = [None] * len(qs)
        empty_grid = np.zeros((height, width))
        live = [(i, p) for i, p, _ok in pending if p is not None]
        for i, p, _ok in pending:
            if p is None:
                out[i] = empty_grid.copy()
        if live:
            import jax.numpy as jnp

            from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon
            from geomesa_tpu.parallel.mesh import pad_query_axis
            from geomesa_tpu.parallel.query import cached_batched_density_step
            from geomesa_tpu.store.backends import REFINE_PRECISION

            nlon = norm_lon(REFINE_PRECISION)
            nlat = norm_lat(REFINE_PRECISION)
            x1, y1, x2, y2 = opts["bbox"]
            gb = np.array(
                [int(nlon.normalize(x1)), int(nlon.normalize(x2)),
                 int(nlat.normalize(y1)), int(nlat.normalize(y2))],
                dtype=np.int32,
            )
            boxes = np.stack([p[0] for _, p in live])
            times = np.stack([p[1] for _, p in live])
            gbs = np.broadcast_to(gb, (len(live), 4)).copy()
            mesh = self.backend._get_mesh()
            (boxes, times, gbs), _ = pad_query_axis(mesh, boxes, times, gbs)
            from geomesa_tpu.obs.jaxmon import count_h2d

            count_h2d(boxes, times, gbs)  # per-batch payload staging
            c = dev.cols
            try:
                grids = np.asarray(
                    cached_batched_density_step(mesh, width, height)(
                        c["x"], c["y"], c["bins"], c["offs"],
                        jnp.int32(main_n),
                        jnp.asarray(boxes), jnp.asarray(times), jnp.asarray(gbs),
                    )
                )
            except Exception as e:  # noqa: BLE001 — failover to exact path
                if not self._is_device_error(e):
                    raise
                self._trip_device_circuit(e)
                self.metrics.counter("store.query.device_failovers").inc()
                grids = None
            if grids is not None:
                self._note_device_ok()
                for k, (i, _) in enumerate(live):
                    out[i] = grids[k].astype(np.float64)
        for i, _p, _ok in pending:
            if out[i] is None:
                continue
            self.metrics.counter("store.queries").inc()
            self._audit(type_name, qs[i], 0.0, 0.0, int(out[i].sum()))
        for i, q in enumerate(qs):
            if out[i] is None:
                out[i] = _exact(q)
        return out

    def _meter_failed(self, type_name: str, q: Query, wall_ms: float) -> None:
        """Tenant accounting for queries that never reach ``_audit``
        (deadline shed, watchdog timeout): the heaviest tenants are
        exactly the ones that time out, and an admission controller
        metering only SUCCESSES would never shed them. Burns the
        tenant's SLO budget (ok=False) and accrues the wall time spent.
        Audit-shadow executions are excluded (same hygiene as
        :meth:`_audit`)."""
        from geomesa_tpu.obs import audit as _obsaudit
        from geomesa_tpu.obs import usage

        if _obsaudit.in_shadow():
            return
        tenant = q.hints.get("tenant") or usage.current_tenant()
        usage.observe(tenant, type_name, "timeout", wall_ms=wall_ms,
                      ok=False)

    def _audit(self, type_name: str, q: Query, plan_ms: float, scan_ms: float,
               hits: int, info=None, sig: str | None = None) -> None:
        # audit-shadow executions (obs/audit.py: referee comparisons,
        # the divergence minimizer, bundle replay) are invisible to the
        # feedback planes — cost table, usage metering, SLO burn,
        # workload capture — the same rule ISSUE 11's replay applies to
        # capture: the auditor must never train the planner it audits,
        # bill a tenant for verification, or recapture itself
        from geomesa_tpu.obs import audit as _obsaudit

        if _obsaudit.in_shadow():
            return
        self.metrics.histogram("store.query.hits").update(hits)
        self.metrics.histogram("store.query.scan_ms").update(scan_ms)
        filt = q.filter if isinstance(q.filter, str) else str(q.filter or "INCLUDE")
        # always-on observability: one flight-recorder audit record + one
        # SLO availability observation + one cost-table observation per
        # completed query (all leaf-lock appends — the <2% cached-jit
        # bound is gated in scripts/lint.sh). A query that ran under
        # devprof additionally carries its device-time breakdown.
        from geomesa_tpu.obs import devmon, usage, workload
        from geomesa_tpu.obs import flight as _flight

        prof = devmon.current_profile() if devmon.PROFILING else None
        device = prof.breakdown() if prof is not None else None
        # tenant attribution (obs.usage): an explicit per-query hint wins
        # (the web layer sets it from X-Geomesa-Tenant); otherwise the
        # request-scoped context the web layer / replay harness bound —
        # anonymous embedded callers land on the default tenant
        tenant = q.hints.get("tenant") or usage.current_tenant()
        # batched paths (select_many) pass their planned signature
        # explicitly: they audit with info=None (amortized timings must
        # not train the cost table) but their lens/ledger attribution
        # still keys on the REAL plan signature
        sig = sig if sig is not None else devmon.plan_signature(info, q)
        predicted = None
        # only FULLY PLANNED, individually timed executions feed the cost
        # table: batched paths audit with amortized-zero timings and no
        # plan info, and an empty store audits 0 ms — letting those in
        # would pull every p50 toward zero under the wrong signature
        # (the table is the adaptive planner's training signal)
        if info is not None:
            index_name = getattr(info, "index_name", None) or ""
            costs = devmon.costs()
            # predicted-vs-actual calibration: read the table's p50 BEFORE
            # this run observes into it (what the planner would have
            # predicted), then feed the error into the cost model's drift
            # report (/api/obs/costs "calibration" section)
            predicted = costs.predict(type_name, sig)
            costs.observe(
                type_name, sig,
                wall_ms=plan_ms + scan_ms,
                device_ms=(device["device_compute"] + device["dispatch"]
                           + device["compile"]) if device else None,
                rows=hits,
                bytes_scanned=(
                    devmon.ledger().index_bytes(type_name, index_name)
                    if index_name and "union" not in index_name else 0
                ),
            )
            if predicted is not None and predicted.get("observations", 0) >= 4:
                from geomesa_tpu.planning import costmodel

                costmodel.model().record_calibration(
                    type_name, sig,
                    predicted["wall_ms_p50"], plan_ms + scan_ms,
                )
        predicted_ms = predicted["wall_ms_p50"] if predicted else None
        device_ms = (device["device_compute"] + device["dispatch"]
                     + device["compile"]) if device else 0.0
        _flight.record(
            op="query", type_name=type_name, source="store", plan=filt,
            latency_ms=plan_ms + scan_ms, rows=hits,
            breakdown={"plan": plan_ms, "scan": scan_ms},
            device=device or {},
            tenant=tenant or "", auths=q.auths,
            plan_signature=sig, predicted_ms=predicted_ms,
        )
        self.slo.observe("store.query", ok=True, key=type_name,
                         latency_ms=plan_ms + scan_ms)
        # retained profiling plane (obs.lens) + roundtrip rollup
        # (obs.ledger): the lens takes the latency histogram point with
        # the submitter's trace exemplar (a coalesced follower's stamped
        # trace_id hint wins over the leader's batch span, so exemplars
        # resolve to DISJOINT stitched trees); the rollup charges this
        # query's dispatch/sync/host-gap ledger to its plan signature —
        # every member of a coalesced batch charges the SHARED ledger once
        from geomesa_tpu.obs import ledger as _rtledger
        from geomesa_tpu.obs import lens as _lensmod

        ql = _rtledger.current()
        trace_id = q.hints.get("trace_id") or ""
        if not trace_id:
            sp = obs.current()
            trace_id = sp.trace_id if sp is not None else ""
        _lensmod.get().observe(
            type_name, sig, latency_ms=plan_ms + scan_ms, rows=hits,
            dispatches=ql.dispatches if ql is not None else 0,
            trace_id=trace_id)
        if ql is not None:
            _rtledger.table().charge(type_name, sig, ql,
                                     wall_ms=plan_ms + scan_ms)
        # per-tenant usage metering (obs.usage): one leaf-lock append, the
        # same cost class as the flight record — the accounting substrate
        # ROADMAP item 4's admission controller consumes
        usage.observe(
            tenant, type_name, sig, rows=hits,
            wall_ms=plan_ms + scan_ms, device_ms=device_ms,
        )
        # workload capture (obs.workload): one wide event per query when
        # GEOMESA_TPU_WORKLOAD_DIR is set; the off path is one bool check
        if workload.ENABLED:
            import time as _time

            workload.record(
                ts=_time.time(), op="query", type_name=type_name,
                source="store", filter_text=filt, hints=q.hints,
                tenant=tenant or "", auths=q.auths, plan_signature=sig,
                predicted_ms=predicted_ms,
                latency_ms=plan_ms + scan_ms, rows=hits,
                device_ms=device_ms,
            )
        # SLO → buffer-pool feedback, sampled (1/32 queries): a type
        # burning its error budget weighs heavier in eviction scoring, so
        # its buffers stay resident while an idle type's go first
        pool = getattr(self.backend, "pool", None)
        if pool is not None:
            self._slo_feed = getattr(self, "_slo_feed", 0) + 1
            if self._slo_feed % 32 == 1:
                pool.note_slo(
                    type_name,
                    self.slo.tracker("store.query", type_name)
                    .budget_remaining(300.0),
                )
        if self.audit_writer is None:
            return
        from geomesa_tpu.utils.audit import QueryEvent, now_millis
        hints = ", ".join(f"{k}={v!r}" for k, v in sorted(q.hints.items()))
        # audit↔trace join: the innermost live span is this query's (the
        # "query" span in query()/select_many); empty when tracing is off
        sp = obs.current()
        self.audit_writer.write_event(
            QueryEvent(
                store_type=type(self.backend).__name__,
                type_name=type_name,
                date=now_millis(),
                user=self.user,
                filter=filt,
                hints=hints,
                plan_time_ms=plan_ms,
                scan_time_ms=scan_ms,
                hits=hits,
                trace_id=sp.trace_id if sp is not None else "",
                span_id=sp.span_id if sp is not None else "",
            )
        )

    def explain(
        self,
        type_name: str,
        q: "Query | str | ast.Filter",
        analyze: bool = False,
    ) -> "str | ExplainAnalyze":
        """Static plan explain; ``analyze=True`` additionally EXECUTES the
        query under a collected trace and returns an :class:`ExplainAnalyze`
        whose stage timeline (plan → dispatch → refine → reduce, plus an
        ``other`` residual) sums to the measured wall time. Range
        decomposition shows as a ``decompose`` span NESTED under ``plan``
        in the full trace tree (``timeline.root``), not as a top-level
        stage."""
        st = self._state(type_name)
        if isinstance(q, (str, ast.Filter)):
            q = Query(filter=q)
        planner = QueryPlanner(st.sft, st.indices, st.stats)
        _, _, info = planner.plan(q)
        out = info.explain()
        if st.delta.rows:
            # intervals above cover the SORTED main tier only; pending hot-
            # tier rows are brute-forced at query time until compact()
            out += f"\n  Hot tier (unsorted, merged at query time): {st.delta.rows} rows"
        if not analyze:
            return out
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.obs import trace as _trace

        # predicted cost BEFORE the run (query() observes into the table);
        # the analyzed execution always runs under devprof so the stage
        # rows split into compile / dispatch / device-compute / h2d / d2h
        sig = devmon.plan_signature(info, q)
        predicted = devmon.costs().predict(type_name, sig)
        import time as _time

        # under active auditing the analyzed execution is audit-tagged
        # and the auditor drains synchronously, so the verdict renders
        # as the `Audit:` line of this ExplainAnalyze
        from dataclasses import replace as _q_replace

        from geomesa_tpu.obs import audit as _obsaudit

        q_run = q
        if _obsaudit.enabled():
            q_run = _q_replace(q, hints={**q.hints, "audit": True})
        with _trace.collect("explain.analyze", type_name=type_name) as root:
            with devmon.profiled() as prof:
                t0 = _time.perf_counter()
                res = self.query(type_name, q_run)
                actual_ms = (_time.perf_counter() - t0) * 1000.0
        audit_verdict = None
        if _obsaudit.enabled():
            aud = _obsaudit.get()
            aud.drain()
            audit_verdict = aud.last_verdict(
                type_name, _obsaudit.filter_text(q_run))
        qspans = root.find("query")
        from geomesa_tpu.planning.costmodel import calibration_error

        return ExplainAnalyze(
            plan=out,
            timeline=_trace.StageTimeline(qspans[0] if qspans else root),
            hits=res.count,
            device=prof.breakdown(),
            cost={
                "signature": sig,
                "predicted": predicted,
                "actual_ms": round(actual_ms, 3),
                # relative prediction error for THIS run (None before the
                # table has a prediction) — the per-query view of the
                # /api/obs/costs calibration report
                "calibration_error": (
                    round(calibration_error(
                        predicted["wall_ms_p50"], actual_ms), 4)
                    if predicted else None
                ),
                "strategy_source": getattr(info, "strategy_source", ""),
                # the decider's rejected alternatives with their estimates
                "alternatives": getattr(info, "alternatives", None) or [],
            },
            cache=self.cache_report(),
            audit=audit_verdict,
        )

    # -- stats API (GeoMesaStats role: exact or estimated) -------------------
    def stats_count(self, type_name: str, cql=None, exact: bool = False):
        """Row count: stored total, sketch estimate, or exact via query.

        ``cql`` may be a CQL string or a pre-built filter AST (the merged
        view passes ASTs so per-store scope filters compose exactly)."""
        st = self._state(type_name)
        if st.total_rows == 0:
            return 0
        if cql is None:
            return st.total_rows
        if exact:
            return self.query(type_name, Query(filter=cql)).count
        if st.stats is None:  # only delta-tier data so far: count it exactly
            return self.query(type_name, Query(filter=cql)).count
        from geomesa_tpu.filter.cql import parse as _parse

        f_ast = _parse(cql) if isinstance(cql, str) else cql
        # the composed sketch estimate (StoreStats.estimate_filter_rows):
        # one definition shared with the planner's cheap-path gate and the
        # cost model's seeds
        est = st.stats.estimate_filter_rows(f_ast)
        # stats cover the main tier only; the hot delta is small enough to
        # count exactly so fresh writes stay visible to estimates
        delta_table = st.delta.merged()
        if delta_table is not None:
            est += float(f_ast.mask(delta_table).sum())
        return est

    # -- persistence (checkpoint/resume) -------------------------------------
    def save(self, path: str, file_format: str = "parquet") -> dict:
        from geomesa_tpu.store import persistence

        return persistence.save(self, path, file_format=file_format)

    @staticmethod
    def load(
        path: str,
        backend: str = "tpu",
        column_group: str | None = None,
        filter=None,
    ) -> "DataStore":
        from geomesa_tpu.store import persistence

        return persistence.load(
            path, backend=backend, column_group=column_group, filter=filter
        )

    # -- durability plane (checkpoint + WAL recovery) -------------------------
    @classmethod
    def open(
        cls,
        path: str,
        backend: str = "tpu",
        recover: bool = False,
        wal_dir: str | None = None,
        checkpointer: bool = True,
        ckpt_bytes: int | None = None,
        ckpt_interval_s: float | None = None,
    ) -> "DataStore":
        """Open a durable catalog: WAL lock → checkpoint load → WAL-tail
        replay (docs/operations.md § Durability & recovery).

        ``wal_dir`` defaults to ``GEOMESA_TPU_WAL`` or ``<path>/wal``. The
        cross-process catalog lock is taken FIRST, so a double-open fails
        fast with :class:`~geomesa_tpu.store.wal.WalLockedError` before
        any state loads. With ``recover=True`` the WAL tail past the
        manifest stamps replays exactly-once in global seq order (typed
        records are idempotent by seq; schema ops interleave in order);
        without it, an unreplayed tail raises
        :class:`~geomesa_tpu.store.wal.WalTailError` instead of being
        silently dropped. ``checkpointer=True`` starts the background
        incremental checkpointer (WAL-bytes / interval triggers,
        deterministic shutdown via :meth:`close`)."""
        import json as _json
        import time as _time

        from geomesa_tpu.resilience import faults as _faults
        from geomesa_tpu.store import persistence
        from geomesa_tpu.store import wal as _walmod
        from pathlib import Path as _Path

        if wal_dir is None:
            wal_dir = os.environ.get("GEOMESA_TPU_WAL") or os.path.join(
                path, "wal")
        ds = cls(backend=backend, wal_dir=wal_dir)
        ds._wal_catalog = path
        wal = ds._wal
        try:
            # a SIGKILLed checkpoint leaves its catalog-lease claim behind
            # and every later save would wait out the full TTL on it; we
            # hold the exclusive WAL lock, so dead local claims are safe
            # to reap now
            from geomesa_tpu.utils.locks import reap_dead_claims

            reap_dead_claims(path)
            stamps: dict[str, int] = {}
            global_floor = 0
            ds._wal_replay = True
            try:
                mpath = _Path(path) / persistence.MANIFEST
                manifest = None
                if mpath.exists():
                    manifest = _json.loads(mpath.read_text())
                    persistence.load(path, backend=backend, into=ds)
                if manifest and manifest.get("wal"):
                    wstamp = manifest["wal"]
                    global_floor = int(wstamp.get("seq", 0))
                    stamps = {str(k): int(v)
                              for k, v in (wstamp.get("topics") or {}).items()}
                    # re-issuing a stamped seq would make the NEXT replay
                    # skip the acked write that reused it
                    wal.ensure_seq_floor(
                        max([global_floor, *stamps.values()] or [0]))
                    ds._wal_schema_seq = stamps.get(_walmod.SCHEMA_TOPIC, 0)
                    for name in ds.list_schemas():
                        st = ds._state(name)
                        st.wal_seq = stamps.get(_walmod.topic_for(name), 0)
                        ident = (manifest.get("types", {})
                                 .get(name, {}).get("ident"))
                        if ident:
                            st.ident = ident
                tail = wal.records_after(stamps, default_floor=global_floor)
                if tail and not recover:
                    raise _walmod.WalTailError(
                        f"catalog {path!r} has {len(tail)} acked WAL "
                        f"record(s) past the last checkpoint; open with "
                        f"recover=True to replay them")
                if tail:
                    t0 = _time.perf_counter()
                    with obs.span("store.recover", catalog=path,
                                  records=len(tail)):
                        for seq, topic, hdr, payload in tail:
                            _faults.crash_point("recover.mid_replay")
                            ds._wal_apply(seq, topic, hdr, payload)
                    _walmod._note(
                        recoveries=1, replayed_records=len(tail),
                        replay_ms_total=(_time.perf_counter() - t0) * 1000.0)
            finally:
                ds._wal_replay = False
            # the tail (if any) is replayed and the stamps are live:
            # mutation/checkpointing can no longer shadow acked history
            ds._wal_unreplayed = False
            if checkpointer:
                ds._wal_ckpt = _walmod.WalCheckpointer(
                    ds, path, bytes_trigger=ckpt_bytes,
                    interval_s=ckpt_interval_s)
            return ds
        except BaseException:
            wal.close()
            raise

    def _wal_apply(self, seq: int, topic: str, hdr: dict,
                   payload: bytes) -> None:
        """Apply one replayed WAL record to the in-memory state. Data ops
        are exact (same pre-state → same effect); schema ops are
        EFFECT-IDEMPOTENT — a checkpoint staged mid-save can already
        reflect a schema op whose seq is above the schema stamp, so an
        already-applied create/evolve/rename/delete skips (counted)."""
        from geomesa_tpu.io.arrow import from_ipc_bytes
        from geomesa_tpu.store import wal as _walmod

        op = hdr.get("op")
        if topic == _walmod.SCHEMA_TOPIC:
            try:
                if op == "create_schema":
                    if hdr["name"] in self._types:
                        _walmod._note(replay_skipped=1)
                    else:
                        sft = parse_spec(hdr["name"], hdr["spec"])
                        if hdr.get("index_layout") == "legacy":
                            sft.user_data["geomesa.index.layout"] = "legacy"
                        self.create_schema(sft)
                elif op == "delete_schema":
                    if hdr["name"] not in self._types:
                        _walmod._note(replay_skipped=1)
                    else:
                        self.delete_schema(hdr["name"])
                elif op == "update_schema":
                    tname = hdr["type"]
                    if tname not in self._types:
                        _walmod._note(replay_skipped=1)  # renamed/gone: done
                    else:
                        self.update_schema(
                            tname, add=hdr.get("add"),
                            keywords=hdr.get("keywords"),
                            rename_to=hdr.get("rename_to"))
            except ValueError:
                # already-applied evolution (attribute exists / rename
                # target exists): the checkpoint was newer than the stamp
                _walmod._note(replay_skipped=1)
            # recovery replay is single-threaded and runs before the
            # store is shared with any other thread
            # tpurace: disable-next-line=R001
            self._wal_schema_seq = max(self._wal_schema_seq, seq)
            return
        name = _walmod.type_for(topic)
        if name is None or name not in self._types:
            # stale incarnation (type deleted before the checkpoint) or a
            # topic whose create never acked: nothing to apply to
            _walmod._note(replay_skipped=1)
            return
        st = self._state(name)
        if op == "write":
            self.write(name, from_ipc_bytes(st.sft, payload))
        elif op == "delete":
            self.delete_features(name, hdr["fids"],
                                 visible_to=hdr.get("visible_to"))
        elif op == "clear":
            self.clear(name)
        elif op == "age_off":
            self.age_off(name, now_ms=hdr["now_ms"])
        else:
            _walmod._note(replay_skipped=1)
            return
        with st.lock:
            st.wal_seq = max(st.wal_seq, seq)

    def close(self) -> None:
        """Deterministic shutdown of the durability plane: stop the
        background checkpointer, flush pending group commits, release the
        cross-process catalog lock. Idempotent; a plain (WAL-less) store
        is a no-op."""
        ck = self._wal_ckpt
        if ck is not None:
            self._wal_ckpt = None
            ck.close()
        if self._wal is not None:
            self._wal.close()

    def _stats(self, type_name: str):
        st = self._state(type_name)
        if st.stats is None and st.delta.rows > 0:
            # delta-only data: fold the hot tier in so sketches exist (writes
            # below the compaction threshold don't build stats eagerly)
            self.compact(type_name)
        if st.stats is None:
            raise ValueError(f"no statistics for {type_name!r}: no data written yet")
        return st.stats

    def stats_bounds(self, type_name: str, attr: str):
        """(min, max) of an attribute from sketches."""
        mm = self._stats(type_name).min_max(attr)
        return (mm.min, mm.max)

    def stats_top_k(self, type_name: str, attr: str, k: int = 10):
        return self._stats(type_name).top_k(attr, k)

    def stats_histogram(self, type_name: str, attr: str):
        return self._stats(type_name).histogram(attr)

    def stats_cardinality(self, type_name: str, attr: str) -> float:
        return self._stats(type_name).cardinality(attr)


def _pyramid_index_name(pkey) -> str:
    """Pool entry name for one pyramid's device mirror — unique per
    aggregation shape so two shapes on a type never share (and clobber)
    one pool entry. Shows in the spill report as ``geoblocks[...]``."""
    group_by, value_cols = pkey
    return "geoblocks[%s;%s]" % (",".join(group_by), ",".join(value_cols))


def _pyramid_evictor(st: "_TypeState", pkey, pyr):
    """Pool-eviction callback for a pyramid's device mirror: drop the
    whole pyramid from the type state (it rebuilds lazily on the next
    eligible aggregate). Runs outside every pool lock."""

    def _evict():
        with st.lock:
            cached = st.pyramids.get(pkey)
            if cached is not None and cached[0] is pyr:
                del st.pyramids[pkey]

    return _evict


def _take_combined(sft, main, main_n: int, delta_table, rows: np.ndarray) -> FeatureTable:
    """Materialize rows addressed in the virtual (main ++ delta) row space."""
    parts = []
    main_sel = rows[rows < main_n]
    delta_sel = rows[rows >= main_n] - main_n
    if len(main_sel):
        parts.append(main.take(main_sel))
    if len(delta_sel):
        parts.append(delta_table.take(delta_sel))
    if not parts:
        return FeatureTable.from_records(sft, [])
    return parts[0] if len(parts) == 1 else FeatureTable.concat(parts)


