"""Write-ahead durability plane: acked writes survive SIGKILL.

The reference inherits write durability from its backends — Accumulo/HBase
ride their own WALs and the Kafka tier persists via the external broker
(``KafkaDataStore.scala``'s offset-managed crash survival) — so a JVM crash
never loses an acked mutation. Here the store is in-process: between
checkpoints (:mod:`geomesa_tpu.store.persistence`) the delta tier is
memory-only. This module closes that gap (docs/operations.md § Durability &
recovery):

- every mutating DataStore op (write / delete / clear / age-off, schema
  create / delete / rename / evolve) appends a typed, seq-stamped record to
  a per-type :class:`~geomesa_tpu.stream.journal.JournalBus` topic under
  ``GEOMESA_TPU_WAL`` (or ``DataStore(wal_dir=)``) and only ACKS — returns
  to the caller — once the record is durably committed;
- appends batch through GROUP COMMIT: the first waiter becomes the flush
  leader, gathers everything enqueued behind the in-flight flush (plus an
  optional ``GEOMESA_TPU_WAL_FLUSH_MS`` window), and lands the batch as ONE
  journal append + commit flip (+ one fsync in ``group`` mode) — an idle
  writer pays no window, so acked-write p99 stays near the WAL-off
  baseline;
- checkpoints stamp ``(global seq, per-topic applied seq)`` into the
  catalog manifest; recovery (``DataStore.open(catalog, recover=True)``)
  loads the checkpoint then replays exactly the records above the stamps,
  in global seq order, and committed segments below the stamps are durably
  head-trimmed (:meth:`JournalBus.trim`) so disk use is bounded;
- a cross-process ``flock`` on ``<wal_dir>/wal.lock`` is held for the WAL's
  lifetime: a second open of the same catalog fails fast with
  :class:`WalLockedError` (and a SIGKILLed holder releases implicitly —
  kernel-owned, no stale-lease window).

Fsync modes (``GEOMESA_TPU_WAL_FSYNC``): ``off`` — no fsync; acked writes
survive process death (SIGKILL: the page cache outlives the process) but a
MACHINE crash can lose the un-synced tail. ``group`` (default) — one fsync
per group-commit batch; machine-crash RPO is one batch. ``each`` — fsync
per record; the strictest RPO, the slowest acks.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time

from geomesa_tpu.stream.journal import JournalBus, TrimmedError  # noqa: F401

__all__ = [
    "SCHEMA_TOPIC", "WalLockedError", "WalTailError", "WriteAheadLog",
    "WalCheckpointer", "topic_for", "type_for", "prometheus_text",
    "wal_metrics",
]

SCHEMA_TOPIC = "wal.__schema__"
_TOPIC_PREFIX = "wal.t."
_REC = struct.Struct("<I")  # u32 json-header length prefix inside a payload


class WalLockedError(RuntimeError):
    """Another live process holds this WAL's catalog lock (double-open)."""


class WalTailError(RuntimeError):
    """The WAL holds acked records past the checkpoint but the catalog was
    opened without ``recover=True`` — refusing to silently drop them."""


def topic_for(type_name: str) -> str:
    """The per-feature-type WAL topic name."""
    return _TOPIC_PREFIX + type_name


def type_for(topic: str) -> str | None:
    """Inverse of :func:`topic_for`; None for the schema topic."""
    if topic.startswith(_TOPIC_PREFIX):
        return topic[len(_TOPIC_PREFIX):]
    return None


def encode_record(seq: int, hdr: dict, payload: bytes = b"") -> bytes:
    h = dict(hdr)
    h["seq"] = int(seq)
    hb = json.dumps(h, sort_keys=True).encode("utf-8")
    return _REC.pack(len(hb)) + hb + payload


def decode_record(data: bytes) -> tuple[dict, bytes]:
    (n,) = _REC.unpack_from(data, 0)
    hdr = json.loads(data[_REC.size : _REC.size + n].decode("utf-8"))
    return hdr, data[_REC.size + n :]


# -- process-wide WAL/recovery metrics ----------------------------------------
# module-global like the devmon ledger: one durability plane per process is
# the normal shape, and the exposition (web/app.py prometheus branch) must
# not need a store reference. All counters under one leaf lock.
_metrics_lock = threading.Lock()
_METRICS: dict[str, float] = {
    "records": 0, "bytes": 0, "flushes": 0, "fsyncs": 0,
    "group_max": 0, "ack_wait_ms_total": 0.0,
    "trims": 0, "trimmed_bytes": 0,
    "checkpoints": 0, "checkpoint_skipped_types": 0,
    "recoveries": 0, "replayed_records": 0, "replay_skipped": 0,
    "replay_ms_total": 0.0,
}


def _note(**kw) -> None:
    with _metrics_lock:
        for k, v in kw.items():
            if k == "group_max":
                _METRICS[k] = max(_METRICS[k], v)
            else:
                _METRICS[k] += v


def wal_metrics() -> dict:
    """Snapshot of the process-wide WAL/recovery counters."""
    with _metrics_lock:
        return dict(_METRICS)


def reset_metrics() -> None:
    """Test isolation: zero the process-wide counters."""
    with _metrics_lock:
        for k in _METRICS:
            _METRICS[k] = 0


def prometheus_text() -> str:
    """``geomesa_wal_*`` / ``geomesa_recovery_*`` exposition lines
    (appended to ``GET /api/metrics?format=prometheus``)."""
    m = wal_metrics()
    rows = [
        ("geomesa_wal_records_total", "counter",
         "WAL records durably appended", m["records"]),
        ("geomesa_wal_bytes_total", "counter",
         "WAL bytes durably appended", m["bytes"]),
        ("geomesa_wal_flushes_total", "counter",
         "group-commit flush batches", m["flushes"]),
        ("geomesa_wal_fsyncs_total", "counter",
         "fsync calls issued by the WAL", m["fsyncs"]),
        ("geomesa_wal_group_width_max", "gauge",
         "largest group-commit batch observed", m["group_max"]),
        ("geomesa_wal_ack_wait_ms_total", "counter",
         "total milliseconds writers waited for durability acks",
         m["ack_wait_ms_total"]),
        ("geomesa_wal_trims_total", "counter",
         "durable head-trims after checkpoints", m["trims"]),
        ("geomesa_wal_trimmed_bytes_total", "counter",
         "WAL bytes reclaimed by head-trims", m["trimmed_bytes"]),
        ("geomesa_wal_checkpoints_total", "counter",
         "WAL-stamped checkpoints", m["checkpoints"]),
        ("geomesa_recovery_total", "counter",
         "checkpoint+WAL recoveries completed", m["recoveries"]),
        ("geomesa_recovery_replayed_records_total", "counter",
         "WAL records replayed by recoveries", m["replayed_records"]),
        ("geomesa_recovery_replay_skipped_total", "counter",
         "stale/idempotent WAL records skipped during replay",
         m["replay_skipped"]),
        ("geomesa_recovery_replay_ms_total", "counter",
         "total milliseconds spent replaying WAL tails",
         m["replay_ms_total"]),
    ]
    out = []
    for name, kind, help_, v in rows:
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {kind}")
        out.append(f"{name} {v}")
    return "\n".join(out) + "\n"


class _Ticket:
    """One enqueued record's durability handle: ``wait`` blocks until the
    group-commit flush covering it has committed (or re-raises the flush
    failure)."""

    __slots__ = ("seq", "event", "error")

    def __init__(self, seq: int):
        self.seq = seq
        self.event = threading.Event()
        self.error: BaseException | None = None

    def wait(self, timeout: float = 60.0) -> None:
        if not self.event.wait(timeout):
            raise TimeoutError("WAL group-commit flush did not complete")
        if self.error is not None:
            raise self.error


class WriteAheadLog:
    """The durability journal: per-type topics on a :class:`JournalBus`,
    group-commit batched appends, seq stamping, checkpoint-coordinated
    trimming, and the cross-process catalog lock."""

    def __init__(self, path: str, fsync_mode: str | None = None,
                 flush_window_s: float | None = None):
        self.path = path
        os.makedirs(path, exist_ok=True)
        if fsync_mode is None:
            fsync_mode = os.environ.get("GEOMESA_TPU_WAL_FSYNC", "group")
        if fsync_mode not in ("off", "group", "each"):
            raise ValueError(f"unknown WAL fsync mode {fsync_mode!r}")
        self.fsync_mode = fsync_mode
        if flush_window_s is None:
            flush_window_s = float(
                os.environ.get("GEOMESA_TPU_WAL_FLUSH_MS", "0")) / 1000.0
        self.flush_window_s = flush_window_s
        # the red-leg chaos switch (scripts/crash_smoke.py --red): ack
        # BEFORE durability — the exact bug the harness must detect
        self.unsafe = os.environ.get("GEOMESA_TPU_WAL_UNSAFE") == "1"
        self._acquire_lock()
        # commit sidecars sync with the batch (publish_many fsync arg);
        # the bus-level default stays off
        self.bus = JournalBus(path, partitions=1, fsync=False)
        self._seq_lock = threading.Lock()  # leaf: seq allocation only
        self._seq = self._scan_max_seq() + 1
        # schema-op ordering guard: create/delete/evolve/rename hold this
        # across (apply + append) so schema-topic seq order == apply order;
        # checkpoint stamp capture holds it too (docs/concurrency.md)
        self.schema_lock = threading.RLock()
        # group-commit state: _gc_lock (leaf) guards the pending batch;
        # _flush_lock serializes physical flushes (leader election)
        self._gc_lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._pending: list[tuple[str, bytes]] = []
        self._waiters: list[_Ticket] = []
        self._closed = False
        # checkpointer trigger: bytes appended since the last stamped
        # checkpoint (reset by note_checkpoint)
        self._bytes_since_ckpt = 0

    # -- lifecycle ------------------------------------------------------------
    def _acquire_lock(self) -> None:
        import errno
        import fcntl

        lock_path = os.path.join(self.path, "wal.lock")
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            holder = ""
            try:
                holder = os.read(fd, 256).decode("utf-8", "replace").strip()
            except OSError:
                pass
            os.close(fd)
            if e.errno in (errno.EACCES, errno.EAGAIN):
                raise WalLockedError(
                    f"WAL catalog {self.path!r} is locked by another live "
                    f"process ({holder or 'holder unknown'}); double-open "
                    f"refused") from None
            raise
        os.ftruncate(fd, 0)
        os.write(fd, f"{socket.gethostname()}:{os.getpid()}".encode())
        self._lock_fd = fd

    def close(self) -> None:
        """Flush pending records, release the catalog lock, stop the bus —
        deterministic and idempotent."""
        with self._gc_lock:
            already = self._closed
            self._closed = True
        if already:
            return
        try:
            self.flush()
        finally:
            self.bus.close()
            try:
                os.close(self._lock_fd)
            except OSError:  # pragma: no cover
                pass

    def abandon(self) -> None:
        """Crash SIMULATION for in-process tests: drop the catalog lock
        and bus WITHOUT flushing pending acks — the state a SIGKILL
        leaves behind. Never call this on a production store."""
        with self._gc_lock:
            self._closed = True
            self._pending.clear()
            self._waiters.clear()
        self.bus.close()
        try:
            os.close(self._lock_fd)
        except OSError:  # pragma: no cover
            pass

    # -- append / group commit ------------------------------------------------
    def append(self, topic: str, hdr: dict, payload: bytes = b"") -> _Ticket:
        """Assign the next global seq and enqueue one typed record for the
        next group-commit flush. The caller holds the scope's ordering
        lock (the type's ``wal_lock`` / :attr:`schema_lock`) so per-topic
        seq order equals apply order; durability is NOT yet established —
        call :meth:`commit` on the ticket before acking the client."""
        with self._gc_lock:
            if self._closed:
                raise RuntimeError(f"WAL {self.path!r} is closed")
        with self._seq_lock:
            seq = self._seq
            self._seq += 1
        rec = encode_record(seq, hdr, payload)
        t = _Ticket(seq)
        if self.unsafe:
            # RED LEG ONLY (scripts/crash_smoke.py --red): the ack
            # precedes durability — the record idles in the pending buffer
            # behind a deferred flush, and the crash point fires while
            # EARLIER acked records are still unflushed: the injected
            # acked-write loss the harness must detect
            from geomesa_tpu.resilience import faults as _faults

            with self._gc_lock:
                if self._pending:
                    _faults.crash_point("wal.unsafe_ack_window")
                self._pending.append((topic, rec))
                self._waiters.append(t)
            t.event.set()
            threading.Timer(0.05, self._unsafe_flush).start()
            return t
        with self._gc_lock:
            self._pending.append((topic, rec))
            self._waiters.append(t)
        return t

    def _unsafe_flush(self) -> None:
        try:
            self.flush()
        except Exception:  # noqa: BLE001 — the acks already happened
            pass

    def commit(self, ticket: _Ticket, timeout: float = 60.0) -> None:
        """Block until ``ticket``'s record is durable. Leader-based group
        commit: whoever gets the flush lock first flushes EVERYTHING
        pending (gathering an optional ``flush_window_s``); waiters that
        arrive mid-flush gather into the next batch behind it. An idle
        writer flushes immediately — no fixed window tax."""
        t0 = time.perf_counter()
        while not ticket.event.is_set():
            with self._flush_lock:
                if ticket.event.is_set():
                    break
                if self.flush_window_s > 0:
                    # the flush lock EXISTS to serialize the flush,
                    # including its optional gather window — followers
                    # keep enqueueing under _gc_lock meanwhile
                    # tpurace: disable-next-line=R003
                    time.sleep(self.flush_window_s)  # gather followers
                self._flush_locked()
        _note(ack_wait_ms_total=(time.perf_counter() - t0) * 1000.0)
        ticket.wait(timeout)

    def flush(self) -> None:
        """Drain every pending record to the journal (checkpoint barrier,
        shutdown)."""
        with self._flush_lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        with self._gc_lock:
            batch, waiters = self._pending, self._waiters
            self._pending, self._waiters = [], []
        if not batch:
            return
        by_topic: dict[str, list[tuple[str, bytes]]] = {}
        for topic, rec in batch:
            by_topic.setdefault(topic, []).append(("", rec))
        fsync = {"off": False, "group": "group", "each": "each"}[self.fsync_mode]
        err: BaseException | None = None
        nbytes = 0
        published: set[str] = set()
        try:
            for topic, recs in by_topic.items():
                # exclusive pinned writer (the catalog lock guarantees
                # single-writer): the steady flush is write + commit flip,
                # not open/lock/read/close per batch. Idempotent per call —
                # a failed flush UNPINS (the repair path), and this re-pin
                # restores the invariant via ftruncate-to-commit
                self.bus.pin_writer(topic)
                start, end = self.bus.publish_many(
                    topic, recs, fsync=fsync, crash_points=True)
                published.add(topic)
                nbytes += end - start
        except BaseException as e:  # noqa: BLE001 — waiters must wake
            err = e
        if err is not None:
            # a transient flush failure (ENOSPC, EIO) must not LOSE the
            # records: the failing op raises (its ack never happened) but
            # the in-memory apply already stands — re-enqueue the
            # un-COMMITTED records at the head so the next flush retries
            # them in order. Topics whose publish_many returned are
            # committed and must not re-enqueue (a same-seq duplicate
            # would replay twice); a topic that failed MID-publish left
            # only an un-committed torn tail the next append repairs.
            with self._gc_lock:
                self._pending[:0] = [
                    (t, r) for t, r in batch if t not in published]
        for w in waiters:
            w.error = err
            w.event.set()
        if err is None:
            _note(records=len(batch), bytes=nbytes, flushes=1,
                  group_max=len(batch),
                  fsyncs=(0 if self.fsync_mode == "off"
                          else len(batch) if self.fsync_mode == "each"
                          else len(by_topic)))
            with self._gc_lock:
                self._bytes_since_ckpt += nbytes
        if err is not None:
            raise err

    # -- recovery / checkpoint coordination -----------------------------------
    def seq_highwater(self) -> int:
        """The last seq handed out (records at/below it are either durable,
        pending, or belong to ops that never acked)."""
        with self._seq_lock:
            return self._seq - 1

    def ensure_seq_floor(self, floor: int) -> None:
        """Never hand out a seq at/below ``floor``. Recovery calls this
        with the manifest's global stamp: a checkpoint can stamp seqs of
        enqueued-but-unflushed records (they are IN the checkpoint image),
        so after a crash the on-disk max can sit BELOW the stamp — resuming
        from the disk max alone would re-issue stamped seqs and the NEXT
        replay would skip those acked writes as already-covered."""
        with self._seq_lock:
            self._seq = max(self._seq, int(floor) + 1)

    @property
    def bytes_since_checkpoint(self) -> int:
        with self._gc_lock:
            return self._bytes_since_ckpt

    def topics(self) -> list[str]:
        """WAL topics present on disk (schema topic + per-type topics)."""
        return [t for t in self.bus.topics()
                if t == SCHEMA_TOPIC or t.startswith(_TOPIC_PREFIX)]

    def has_records(self) -> bool:
        """Any retained (committed, untrimmed) records on disk? A plain
        ``DataStore(wal_dir=)`` attach over such a journal has NOT
        replayed them — mutating/checkpointing that store could trim or
        shadow acked history, so the store gates on this until a
        recovery (``DataStore.open``) accounts for the tail."""
        return any(
            self.bus.committed_offset(t) > self.bus.head_offset(t)
            for t in self.topics()
        )

    def _scan_max_seq(self) -> int:
        """Largest seq present in the on-disk logs (resume point)."""
        high = 0
        for topic in self.topics():
            for _s, _e, payload in self.bus.iter_records(topic):
                try:
                    hdr, _ = decode_record(payload)
                    high = max(high, int(hdr.get("seq", 0)))
                except (ValueError, KeyError, json.JSONDecodeError):
                    continue  # unreadable record: replay will surface it
        return high

    def records_after(self, stamps: dict[str, int], default_floor: int = 0):
        """Every durable record with ``seq > stamps.get(topic,
        default_floor)``, merged across topics in GLOBAL seq order — the
        recovery replay stream. ``default_floor`` is the checkpoint's
        global seq: topics the manifest does not stamp (deleted / stale
        incarnations, or types created after the checkpoint) replay only
        their post-checkpoint records."""
        out: list[tuple[int, str, dict, bytes]] = []
        for topic in self.topics():
            floor = stamps.get(topic, default_floor)
            for _s, _e, payload in self.bus.iter_records(topic):
                hdr, body = decode_record(payload)
                seq = int(hdr["seq"])
                if seq > floor:
                    out.append((seq, topic, hdr, body))
        out.sort(key=lambda r: r[0])
        return out

    def records_between(self, topic: str, floor: int, ceiling: int):
        """Durable records of ONE topic with ``floor < seq <= ceiling``,
        in seq order — the live-migration tail-replay stream
        (serving/elastic.py): the shard bundle's ``wal_floor`` bounds it
        below, the migrator's post-drain stop seq bounds it above, so
        the destination replays exactly the records the snapshot missed
        and the dual-apply window has not delivered."""
        floor, ceiling = int(floor), int(ceiling)
        out: list[tuple[int, dict, bytes]] = []
        for _s, _e, payload in self.bus.iter_records(topic):
            hdr, body = decode_record(payload)
            seq = int(hdr["seq"])
            if floor < seq <= ceiling:
                out.append((seq, hdr, body))
        out.sort(key=lambda r: r[0])
        return out

    def note_checkpoint(self, stamps: dict[str, int], global_seq: int) -> None:
        """A checkpoint with these per-topic applied-seq stamps just
        committed: durably head-trim every topic below its stamp (topics
        the manifest no longer stamps trim below the global seq — dead
        incarnations drain; records of types created after the stamp
        capture carry larger seqs and survive) and reset the byte
        trigger."""
        for topic in self.topics():
            floor = stamps.get(topic, global_seq)
            boundary = None
            for start, end, payload in self.bus.iter_records(topic):
                try:
                    hdr, _ = decode_record(payload)
                except (ValueError, json.JSONDecodeError):
                    break
                if int(hdr.get("seq", 0)) > floor:
                    break
                boundary = end
            if boundary is not None:
                trimmed = self.bus.trim(topic, boundary)
                if trimmed:
                    _note(trims=1, trimmed_bytes=trimmed)
        with self._gc_lock:
            self._bytes_since_ckpt = 0
        _note(checkpoints=1)


class WalCheckpointer:
    """Background incremental checkpointer: saves the store's catalog when
    the WAL grows past ``bytes_trigger`` (``GEOMESA_TPU_WAL_CKPT_BYTES``,
    default 64 MiB) or every ``interval_s`` (``GEOMESA_TPU_WAL_CKPT_
    INTERVAL_S``, default off). Deterministic shutdown: :meth:`close` sets
    the stop event and joins the thread; a checkpoint failure is counted
    and retried on the next trigger, never fatal."""

    POLL_S = 0.2

    def __init__(self, ds, catalog_path: str,
                 bytes_trigger: int | None = None,
                 interval_s: float | None = None):
        self.ds = ds
        self.catalog_path = catalog_path
        if bytes_trigger is None:
            bytes_trigger = int(
                os.environ.get("GEOMESA_TPU_WAL_CKPT_BYTES", str(1 << 26)))
        if interval_s is None:
            interval_s = float(
                os.environ.get("GEOMESA_TPU_WAL_CKPT_INTERVAL_S", "0"))
        self.bytes_trigger = bytes_trigger
        self.interval_s = interval_s
        self.errors = 0
        self.checkpoints = 0
        self._stop = threading.Event()
        self._last = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="geomesa-wal-checkpointer")
        self._thread.start()

    def _due(self) -> bool:
        wal = getattr(self.ds, "_wal", None)
        if wal is None:
            return False
        if self.bytes_trigger and wal.bytes_since_checkpoint >= self.bytes_trigger:
            return wal.bytes_since_checkpoint > 0
        if self.interval_s and (time.monotonic() - self._last) >= self.interval_s:
            return wal.bytes_since_checkpoint > 0
        return False

    def _run(self) -> None:
        while not self._stop.wait(self.POLL_S):
            if not self._due():
                continue
            try:
                self.ds.save(self.catalog_path)
                self.checkpoints += 1
            except Exception:  # noqa: BLE001 — retried on the next trigger
                self.errors += 1
            self._last = time.monotonic()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30.0)
