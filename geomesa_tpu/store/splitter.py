"""Shard split-point computation (the TableSplitter role).

Role parity: ``geomesa-index-api/.../conf/splitter/DefaultSplitter.scala:33``
(SURVEY.md §2.3): the reference seeds each index's table with initial split
points (z-prefix patterns, attribute prefix letters, id hex) so load spreads
across tablet servers before any data arrives. The TPU analog is the *device
shard boundary*: where the z-sorted columnar store is cut across the mesh's
data axis. Two flavors:

- :func:`default_splits` — static, config-driven (no data yet): evenly spaced
  points in the index's key domain, the DefaultSplitter behavior.
- :func:`balanced_splits` — stats-driven (data resident): quantile cuts of the
  actual sorted keys so every device holds the same row count — the reference
  achieves this a-posteriori via tablet splits; we can do it exactly at
  (re)shard time (SURVEY.md §2.20 P1).
"""

from __future__ import annotations

import numpy as np

__all__ = ["default_splits", "balanced_splits", "shard_of"]


def default_splits(index_name: str, n_shards: int, bits: int = 62) -> np.ndarray:
    """``n_shards - 1`` static split keys for an empty index.

    z2/z3/xz2/xz3: evenly spaced in the key domain (``2^bits``); attr/id:
    evenly spaced in the first-byte domain, mirroring the reference's
    hex/alpha prefix patterns.
    """
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    name = index_name.lower()
    if name.startswith(("z2", "z3", "xz2", "xz3")):
        domain = 1 << bits
        return (np.arange(1, n_shards) * (domain // n_shards)).astype(np.int64)
    # attribute / id indexes: split the leading byte
    return (np.arange(1, n_shards) * (256 // max(n_shards, 1))).astype(np.int64)


def balanced_splits(sorted_keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Quantile split keys over resident data → equal-count shards.

    Returns ``n_shards - 1`` keys; shard i = rows with
    ``splits[i-1] <= key < splits[i]``.
    """
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    n = len(sorted_keys)
    if n == 0 or n_shards == 1:
        return np.empty(0, dtype=np.asarray(sorted_keys).dtype)
    cuts = (np.arange(1, n_shards) * n) // n_shards
    return np.asarray(sorted_keys)[cuts]


def shard_of(keys: np.ndarray, splits: np.ndarray) -> np.ndarray:
    """Shard id per key under the given split points (searchsorted)."""
    if len(splits) == 0:
        return np.zeros(len(keys), dtype=np.int32)
    return np.searchsorted(splits, keys, side="right").astype(np.int32)
