"""Device-side bulk ingest & rebalance: stats-driven splits + all_to_all.

Role parity: ``DefaultSplitter.scala:33`` (stats-driven table cut points) and
the tablet split/migration rebalancing the reference delegates to its storage
layer (SURVEY.md §2.20 P1/P8). TPU-native lifecycle step: rows land on the
mesh in ARRIVAL order (no host sort), split keys are sampled-quantile cuts of
the *resident* keys, and one ``all_to_all`` reshard routes every row to its
z-range owner shard with a local sort — after which per-device row counts are
balanced to within sampling error even for fully skewed geodata (all points
in one hemisphere). Used by the lambda-tier persister when draining the hot
tier and by bulk mesh ingest.
"""

from __future__ import annotations

import numpy as np

import jax

from geomesa_tpu.parallel.mesh import Mesh, data_shards, shard_columns
from geomesa_tpu.parallel.reshard import reshard
from geomesa_tpu.store.splitter import balanced_splits

__all__ = ["sampled_splits", "device_bulk_build", "device_sort_perm"]


def sampled_splits(
    key_sharded, true_n: int, n_shards: int, per_shard_samples: int = 2048
) -> np.ndarray:
    """Stats-driven shard cut points from a strided device-side key sample.

    Pulls ~``per_shard_samples × n_shards`` keys (a few KB) instead of the
    full column — the quantile estimate errs by O(1/samples), far inside the
    10% balance budget.
    """
    total = int(key_sharded.shape[0])
    want = max(per_shard_samples * n_shards, n_shards)
    stride = max(1, true_n // want)
    sample = np.asarray(jax.device_get(key_sharded[:true_n:stride]))
    return balanced_splits(np.sort(sample), n_shards)


def device_bulk_build(mesh: Mesh, keys: np.ndarray, payload: dict):
    """Arrival-order rows → balanced, per-shard-sorted device store.

    ``keys``: (n,) uint64 curve keys in arrival order; ``payload``: int32
    columns riding along. Returns (key_out, cols_out, counts, splits):
    device arrays sharded over the mesh data axis where shard d owns keys in
    ``[splits[d-1], splits[d])``, locally sorted, with ``counts[d]`` real
    rows. Overflowing capacity lanes (badly skewed arrival order) retry with
    doubled capacity — fixed shapes stay compile-cached per capacity.
    """
    key_out, cols_out, counts, splits = _reshard_with_retry(
        mesh, keys, payload
    )
    return key_out, cols_out, counts, splits


def _reshard_with_retry(mesh: Mesh, keys: np.ndarray, payload: dict,
                        lex_cols: int = 0):
    """Shard + split + reshard with capacity-doubling retries on overflow
    (shared by :func:`device_bulk_build` and :func:`device_sort_perm`).
    Raises RuntimeError if overflow persists at full per-shard capacity."""
    n = len(keys)
    shards = data_shards(mesh)
    cols, padded, rows_per_shard = shard_columns(
        mesh, {"key": keys.astype(np.uint64), **payload}
    )
    splits = sampled_splits(cols["key"], n, shards)
    payload_dev = {k: cols[k] for k in payload}
    capacity = None
    for _ in range(8):
        key_out, cols_out, counts, ovf = reshard(
            mesh, cols["key"], n, splits, payload_dev,
            capacity=capacity, lex_cols=lex_cols,
        )
        if ovf == 0:
            return key_out, cols_out, counts, splits
        capacity = (capacity or max(8, (2 * rows_per_shard) // shards + 8)) * 2
        if capacity >= rows_per_shard:
            capacity = rows_per_shard  # one lane can hold a whole shard
    key_out, cols_out, counts, ovf = reshard(
        mesh, cols["key"], n, splits, payload_dev,
        capacity=rows_per_shard, lex_cols=lex_cols,
    )
    if ovf != 0:
        raise RuntimeError(f"reshard overflow persisted at full capacity: {ovf}")
    return key_out, cols_out, counts, splits


def device_sort_perm(
    mesh: Mesh, route_key: np.ndarray, tiebreak: np.ndarray | None = None
) -> np.ndarray:
    """Distributed sample sort on the mesh → the sorting permutation.

    The index-build path's host ``lexsort`` replacement (SURVEY.md §2.20 P1,
    the ``DefaultSplitter.scala:33`` stats-driven-cuts role made a device
    primitive): rows route to their key-range owner shard via stats-driven
    ``sampled_splits`` + one ``all_to_all``, each shard sorts locally, and
    concatenating shards in split order yields the global sort. Composite
    keys wider than 64 bits (z3's (bin, 63-bit z)) pass the high bits as
    ``route_key`` and the low bits as ``tiebreak`` — the reshard step
    lexsorts by (route_key, tiebreak), which equals the exact wide-key
    order whenever ``route_key`` is a monotone prefix of it.

    Returns a (n,) int64 permutation with the same row-set semantics as the
    host sort (tie ORDER between fully-equal keys may differ; all sorted key
    products are identical). Raises ValueError for inputs the device path
    cannot represent (≥ int32 rows; a route key equal to the reshard padding
    sentinel, which would silently drop the row) and RuntimeError on
    persistent reshard overflow — callers fall back to the host sort.
    """
    n = len(route_key)
    if n >= 2**31:
        raise ValueError("device_sort_perm: > int32 rows per build")
    if n and int(route_key.max()) == 2**64 - 1:
        raise ValueError("device_sort_perm: route key collides with sentinel")
    shards = data_shards(mesh)
    rowid = np.arange(n, dtype=np.int32)
    payload = {"rowid": rowid}
    lex = 0
    if tiebreak is not None:
        payload = {"tie": tiebreak.astype(np.int32), "rowid": rowid}
        lex = 1
    _, cols_out, counts, _splits = _reshard_with_retry(
        mesh, route_key, payload, lex_cols=lex
    )
    # per-shard sorted rowids, concatenated in shard order = global sort.
    # cols_out["rowid"] is (S * S*capacity) device-sharded; shard d's first
    # counts[d] rows are real.
    rid = np.asarray(jax.device_get(cols_out["rowid"]))
    per_shard = rid.reshape(shards, -1)
    return np.concatenate(
        [per_shard[d, : int(counts[d])] for d in range(shards)]
    ).astype(np.int64)
