"""Device-side bulk ingest & rebalance: stats-driven splits + all_to_all.

Role parity: ``DefaultSplitter.scala:33`` (stats-driven table cut points) and
the tablet split/migration rebalancing the reference delegates to its storage
layer (SURVEY.md §2.20 P1/P8). TPU-native lifecycle step: rows land on the
mesh in ARRIVAL order (no host sort), split keys are sampled-quantile cuts of
the *resident* keys, and one ``all_to_all`` reshard routes every row to its
z-range owner shard with a local sort — after which per-device row counts are
balanced to within sampling error even for fully skewed geodata (all points
in one hemisphere). Used by the lambda-tier persister when draining the hot
tier and by bulk mesh ingest.
"""

from __future__ import annotations

import numpy as np

import jax

from geomesa_tpu.parallel.mesh import Mesh, data_shards, shard_columns
from geomesa_tpu.parallel.reshard import reshard
from geomesa_tpu.store.splitter import balanced_splits

__all__ = ["sampled_splits", "device_bulk_build"]


def sampled_splits(
    key_sharded, true_n: int, n_shards: int, per_shard_samples: int = 2048
) -> np.ndarray:
    """Stats-driven shard cut points from a strided device-side key sample.

    Pulls ~``per_shard_samples × n_shards`` keys (a few KB) instead of the
    full column — the quantile estimate errs by O(1/samples), far inside the
    10% balance budget.
    """
    total = int(key_sharded.shape[0])
    want = max(per_shard_samples * n_shards, n_shards)
    stride = max(1, true_n // want)
    sample = np.asarray(jax.device_get(key_sharded[:true_n:stride]))
    return balanced_splits(np.sort(sample), n_shards)


def device_bulk_build(mesh: Mesh, keys: np.ndarray, payload: dict):
    """Arrival-order rows → balanced, per-shard-sorted device store.

    ``keys``: (n,) uint64 curve keys in arrival order; ``payload``: int32
    columns riding along. Returns (key_out, cols_out, counts, splits):
    device arrays sharded over the mesh data axis where shard d owns keys in
    ``[splits[d-1], splits[d])``, locally sorted, with ``counts[d]`` real
    rows. Overflowing capacity lanes (badly skewed arrival order) retry with
    doubled capacity — fixed shapes stay compile-cached per capacity.
    """
    n = len(keys)
    shards = data_shards(mesh)
    cols, padded, rows_per_shard = shard_columns(
        mesh, {"key": keys.astype(np.uint64), **payload}
    )
    splits = sampled_splits(cols["key"], n, shards)
    payload_dev = {k: cols[k] for k in payload}
    capacity = None
    for _ in range(8):
        key_out, cols_out, counts, ovf = reshard(
            mesh, cols["key"], n, splits, payload_dev, capacity=capacity
        )
        if ovf == 0:
            return key_out, cols_out, counts, splits
        capacity = (capacity or max(8, (2 * rows_per_shard) // shards + 8)) * 2
        if capacity >= rows_per_shard:
            capacity = rows_per_shard  # one lane can hold a whole shard
    key_out, cols_out, counts, ovf = reshard(
        mesh, cols["key"], n, splits, payload_dev, capacity=rows_per_shard
    )
    if ovf != 0:
        raise RuntimeError(f"reshard overflow persisted at full capacity: {ovf}")
    return key_out, cols_out, counts, splits
