"""Execution backends: the bottom seam (``IndexAdapter`` role, SURVEY.md §1).

Two implementations, mirroring the reference's two-tier test architecture
(SURVEY.md §4 "lesson"):

- :class:`OracleBackend` — brute-force vectorized filter evaluation over the
  host columnar table. The result-set parity referee (the ``GeoCQEngine`` /
  ``TestGeoMesaDataStore`` role).
- :class:`TpuBackend` — device-resident int32 columns per index order; scans
  gather host-planned candidate slots and run the fused jit refine kernel
  (:mod:`geomesa_tpu.ops.refine`), then apply the exact f64 residual filter to
  the survivors on the host (the coprocessor/iterator stack role).
"""

from __future__ import annotations

import os
from contextlib import ExitStack
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.analysis.contracts import dispatch_budget
from geomesa_tpu.obs import ledger as _rtledger
from geomesa_tpu.curve.binned_time import BinnedTime
from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon
from geomesa_tpu.filter import ast
from geomesa_tpu.filter.bounds import Extraction
from geomesa_tpu.index.api import FeatureIndex, IndexPlan, gather_indices, pad_bucket
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType

REFINE_PRECISION = 31  # device coords are 31-bit fixed point (Z2 resolution)
JOIN_BLOCK = 4096  # block-sparse join granularity; shards pad to multiples
# per-plan dispatch-payload memo cap (IndexPlan.exec_cache): total idx/count
# slots above this re-derive per query instead of pinning device arrays the
# ledger/pool don't account for (128 cached plans x wide-scan splits would
# silently hold MBs of HBM outside the budget)
_EXEC_MEMO_MAX_SLOTS = 1 << 18  # 256k slots ≈ 2 MB of int32 per plan
# row-select one-pass threshold: total gather slots (shards x per-shard
# capacity) below which the count pass is skipped and the gather runs
# straight at the planner's candidate bound — one device dispatch instead
# of two (each dispatch is a full host->device round trip; dominant over
# the relay tunnel). 4M int32 slots = 16 MB of pos transfer worst-case.
try:
    _ONE_PASS_MAX_SLOTS = int(
        os.environ.get("GEOMESA_SELECT_ONE_PASS_SLOTS", str(4 * 1024 * 1024))
    )
except ValueError as _e:
    raise ValueError(
        "GEOMESA_SELECT_ONE_PASS_SLOTS must be an integer slot count: "
        f"{os.environ['GEOMESA_SELECT_ONE_PASS_SLOTS']!r}"
    ) from _e


class ExecutionBackend:
    name = "base"

    def load(self, sft: FeatureType, table: FeatureTable, indices: dict,
             fingerprint=None) -> Any:
        """(Re)build backend state for a snapshot of the data.

        ``fingerprint`` identifies the MAIN-TIER snapshot (the owning
        type's rebuild epoch): backends with a buffer pool use it to
        re-admit donated buffers from an identical prior load without
        re-staging (delta-only writes keep the fingerprint stable)."""
        raise NotImplementedError

    def select(
        self,
        state: Any,
        index: FeatureIndex,
        plan: IndexPlan,
        extraction: Extraction,
        residual: ast.Filter,
        table: FeatureTable,
    ) -> np.ndarray:
        """Execute a scan plan → matching global row indices (unsorted)."""
        raise NotImplementedError


class OracleBackend(ExecutionBackend):
    """Brute force: evaluate the full filter over every row (referee)."""

    name = "oracle"

    def load(self, sft, table, indices, fingerprint=None):
        return None

    def select(self, state, index, plan, extraction, residual, table):
        with obs.span("refine", mode="oracle", rows=len(table)):
            return np.nonzero(residual.mask(table))[0]


@dataclass
class _MeshIndexState:
    """Per-index mesh-sharded device columns, sorted in index order.

    ``cols`` holds the device jnp arrays sharded contiguously over the mesh
    ``data`` axis (curve order = shard order, SURVEY.md §2.20 P1); padding
    rows live past ``n`` and never appear in scan intervals. ``kind`` is
    ``"points"`` (x/y/bins/offs — containment refine) or ``"bboxes"``
    (xmin/xmax/ymin/ymax/bins/offs — overlap refine for extended
    geometries, the XZ2/XZ3 device path).
    """

    cols: dict[str, Any]
    rows_per_shard: int
    n: int
    kind: str = "points"
    # lazily-staged grouped-aggregation residency (DataStore.aggregate_many):
    # group-id/value columns keyed by their column tuple. Lives and dies with
    # this state object, so a compact/ingest that rebuilds the layout also
    # drops the cache — no invalidation protocol needed.
    agg_cache: dict = field(default_factory=dict)

    @property
    def nbytes(self) -> int:
        """Device bytes held by this index's sharded columns (incl. padding)."""
        return int(sum(int(c.nbytes) for c in self.cols.values()))

    def spatial_cols(self) -> tuple:
        """THE ordered spatial+time column tuple every kernel expects:
        (x, y, bins, offs) for point layouts, (xmin, ymin, xmax, ymax,
        bins, offs) for bbox layouts — one definition so the positional
        contract cannot drift per call site (the kernels accept any int32
        arrays, so a mis-ordered tuple is silently wrong, not an error)."""
        c = self.cols
        if self.kind == "bboxes":
            return (c["xmin"], c["ymin"], c["xmax"], c["ymax"],
                    c["bins"], c["offs"])
        return (c["x"], c["y"], c["bins"], c["offs"])


def _slot_clearer(state: dict, name: str):
    """Pool-eviction callback: clear the index's slot in the backend-state
    dict so subsequent snapshots take the exact host path. Queries that
    already snapshotted the state keep their reference — the arrays stay
    alive until the last reader drops them, so eviction never invalidates
    an in-flight dispatch."""

    def _clear():
        state[name] = None

    return _clear


def time_quads(sft: FeatureType, intervals) -> "np.ndarray | None":
    """Interval list → (T, 4) [bin_lo, off_lo, bin_hi, off_hi] int32 quads
    (the kernels' and the GeoBlocks pyramid's shared time payload), or
    None for no temporal constraint. Every interval clamping away yields
    the unsatisfiable quad — a temporally-impossible predicate must not
    become a full-window scan."""
    if intervals is None or not sft.dtg_field:
        return None
    binned = BinnedTime(sft.z3_interval)
    from geomesa_tpu.curve.binned_time import MAX_BIN

    quads = []
    for lo, hi in intervals:
        lo = max(int(lo), 0)
        # last indexable millisecond: one before the start of bin MAX_BIN+1
        hi_cap = int(binned.bin_start_millis(np.array([MAX_BIN + 1]))[0]) - 1
        hi = min(int(hi), hi_cap)
        if hi < lo:
            continue
        (blo,), (olo,) = binned.to_bin_and_offset(np.array([lo]))
        (bhi,), (ohi,) = binned.to_bin_and_offset(np.array([hi]))
        quads.append([int(blo), int(olo), int(bhi), int(ohi)])
    if quads:
        return np.array(quads, dtype=np.int32)
    return np.array([[1, 0, 0, -1]], dtype=np.int32)


class TpuBackend(ExecutionBackend):
    """Mesh-sharded columnar execution: the distributed-scan role of the
    tablet-server fleet. Row retrieval is two-pass — per-shard refine counts
    size the capacity lanes, then an on-device compaction gathers matching
    global row positions per shard (``ArrowScan.scala:37`` /
    ``QueryPlan.scala:106`` role, collectives instead of scan RPC)."""

    name = "tpu"

    def __init__(self, mesh=None, max_device_bytes: int | None = None,
                 pool=None):
        self._mesh = mesh
        # shared HBM buffer pool (store/bufferpool.py): pins hot buffers
        # across queries, evicts by SLO-weighted access frequency under
        # the GEOMESA_TPU_HBM process budget, and re-admits donated
        # buffers on fingerprint-stable reloads. One pool per backend so
        # test stores never fight over same-named types.
        if pool is None:
            from geomesa_tpu.store.bufferpool import BufferPool

            pool = BufferPool()
        self.pool = pool
        # PER-TYPE HBM residency budget, enforced on each load() (the
        # hot-tier half of SURVEY.md §2.20 P9 at device granularity):
        # indexes past the budget stay host-resident — select() already
        # falls back per index. The budget counts TOTAL bytes across the
        # mesh (all shards summed), not per device; a store holding T types
        # can reach T × budget — size accordingly. Env default so operators
        # can set it without code.
        if max_device_bytes is None:
            env = os.environ.get("GEOMESA_DEVICE_BUDGET_BYTES")
            if env:
                try:
                    max_device_bytes = int(env)
                except ValueError:
                    raise ValueError(
                        "GEOMESA_DEVICE_BUDGET_BYTES must be an integer "
                        f"byte count, got {env!r}"
                    ) from None
        self.max_device_bytes = max_device_bytes

    def _get_mesh(self):
        if self._mesh is None:
            from geomesa_tpu.parallel.mesh import default_mesh

            self._mesh = default_mesh()
        return self._mesh

    @staticmethod
    def point_state(state) -> tuple["_MeshIndexState | None", str | None]:
        """The preferred point-index device state: (state, index name).

        Shared by every batched device fast path (count_many, knn_many) so
        index preference stays in one place.
        """
        if not state:
            return None, None
        for name in ("z3", "z2"):
            dev = state.get(name)
            if dev is not None:
                return dev, name
        return None, None

    @staticmethod
    def bbox_state(state) -> tuple["_MeshIndexState | None", str | None]:
        """The preferred extended-geometry device state (xz3/xz2): feature
        bbox SoA for overlap-mode batched fast paths."""
        if not state:
            return None, None
        for name in ("xz3", "xz2"):
            dev = state.get(name)
            if dev is not None and dev.kind == "bboxes":
                return dev, name
        return None, None

    # residency priority when a device-byte budget applies: the batched
    # fast paths prefer z3/z2 (point containment) then xz3/xz2 (overlap)
    _LOAD_PRIORITY = ("z3", "z2", "xz3", "xz2")

    @classmethod
    def residency(cls, state) -> dict[str, int]:
        """Per-index device bytes for a backend-state snapshot."""
        if not state:
            return {}
        return {
            name: dev.nbytes
            for name, dev in state.items()
            if isinstance(dev, _MeshIndexState)
        }

    def load(self, sft, table, indices, fingerprint=None):
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.store.bufferpool import register_residency
        from geomesa_tpu.parallel.mesh import shard_columns

        # HBM residency ledger: every device allocation this load makes is
        # registered (type, index, column group, bytes) and auto-unregisters
        # when the state object is dropped (evict/reload/compact); indexes
        # the budget refuses land in the host-resident spill report instead
        ledger = devmon.ledger()
        type_name = getattr(sft, "name", "?")
        ledger.begin_load(type_name)
        ledger.set_budget(self.max_device_bytes)
        # retire this type's live pool entries: fingerprint-stable states
        # (same main tier — recover() after a pressure eviction, reloads
        # across delta-only writes) park in the donation stash for
        # zero-copy re-admission below; anything stale is freed
        self.pool.release(type_name, keep_fingerprint=fingerprint)
        state: dict[str, _MeshIndexState | None] = {}
        nlon = norm_lon(REFINE_PRECISION)
        nlat = norm_lat(REFINE_PRECISION)
        binned = BinnedTime(sft.z3_interval) if sft.dtg_field else None
        mesh = None
        ordered = sorted(
            indices.items(),
            key=lambda kv: (
                self._LOAD_PRIORITY.index(kv[0])
                if kv[0] in self._LOAD_PRIORITY
                else len(self._LOAD_PRIORITY)
            ),
        )
        used_bytes = 0
        est = 0
        if (self.max_device_bytes is not None
                or self.pool.max_total_bytes is not None):
            # admission estimate: int32 columns at the REAL padded row count
            # (block-aligned shards — parallel/mesh.pad_rows with the
            # JOIN_BLOCK multiple — can round small tables up substantially)
            from geomesa_tpu.parallel.mesh import data_shards, pad_rows

            mesh = self._get_mesh()
            n_cols = (
                4 if (sft.geom_field and table.geom_column().x is not None) else 6
            )
            shards = data_shards(mesh)
            est = n_cols * 4 * pad_rows(
                max(len(table), shards), shards, JOIN_BLOCK
            )
        # buffers admitted by THIS load stay pinned until the loop ends:
        # a later (lower-priority) index's ensure_room must not evict the
        # just-staged higher-priority one — fresh entries have hits=0 and
        # would otherwise be the coldest eviction candidates, inverting
        # _LOAD_PRIORITY and wasting the h2d staging just paid for. With
        # release() having retired this type's prior entries above, load-
        # pressure evictions can only fall on OTHER types' cold buffers.
        load_pins = ExitStack()
        try:
            for name, index in ordered:
                col = table.geom_column() if sft.geom_field else None
                if col is None or len(table) == 0 or name in ("id",):
                    state[name] = None  # host path BY DESIGN — never a spill
                    continue
                if col.x is None and col.bounds is None:
                    state[name] = None
                    continue
                if self.max_device_bytes is not None:
                    if used_bytes + est > self.max_device_bytes:
                        state[name] = None  # host path serves this index
                        ledger.record_spill(type_name, name, est)
                        # a donated state for a refused index would hold the
                        # very bytes the budget just declined — free it
                        self.pool.drop_donated(type_name, name)
                        continue
                # donation fast path: an identical prior load of this index
                # (same fingerprint = same main tier) parked in the pool's
                # stash — re-admit it without staging a byte host→device.
                # The evictor rebinds to THIS load's state dict.
                donated = self.pool.take_donated(
                    type_name, name, fingerprint,
                    on_evict=_slot_clearer(state, name))
                if donated is not None:
                    state[name] = donated
                    used_bytes += donated.nbytes
                    load_pins.enter_context(self.pool.pinned(type_name, name))
                    continue
                # process-level pool budget (GEOMESA_TPU_HBM): make room by
                # evicting the coldest unpinned buffers (other types' first,
                # by SLO-weighted access frequency); an immovable working set
                # spills this index to the host path, same as per-type
                if not self.pool.ensure_room(est or 0):
                    state[name] = None
                    ledger.record_spill(type_name, name, est)
                    continue
                if mesh is None:
                    mesh = self._get_mesh()
                perm = index.perm
                if binned is not None:
                    bins, offs = binned.to_bin_and_offset(table.dtg_millis()[perm])
                    bins = bins.astype(np.int32)
                    offs = offs.astype(np.int32)
                else:
                    bins = np.zeros(len(table), dtype=np.int32)
                    offs = np.zeros(len(table), dtype=np.int32)
                if col.x is not None:
                    xi = nlon.normalize(col.x[perm]).astype(np.int32)
                    yi = nlat.normalize(col.y[perm]).astype(np.int32)
                    # block-aligned shards so block-granular kernels (the
                    # block-sparse join over the z2 layout) divide evenly
                    cols, padded, rows_per_shard = shard_columns(
                        mesh, {"x": xi, "y": yi, "bins": bins, "offs": offs},
                        multiple=JOIN_BLOCK,
                    )
                    state[name] = _MeshIndexState(
                        cols=cols, rows_per_shard=rows_per_shard, n=len(table)
                    )
                    used_bytes += state[name].nbytes
                    register_residency(
                        self.pool, type_name, name, devmon.GROUP_SPATIAL,
                        state[name].nbytes, owner=state[name],
                        fingerprint=fingerprint,
                        on_evict=_slot_clearer(state, name))
                    load_pins.enter_context(self.pool.pinned(type_name, name))
                else:
                    # extended geometries: shard the bbox SoA for overlap refine.
                    # Null geometries leave NaN bounds — normalize a dummy, then
                    # stamp an unsatisfiable interval so they never match (the
                    # residual filter already excludes them on the host path)
                    b = col.bounds[perm]
                    invalid = (
                        np.zeros(len(b), dtype=bool)
                        if col.valid is None
                        else ~col.valid[perm]
                    )
                    invalid |= ~np.isfinite(b).all(axis=1)
                    if invalid.any():
                        b = np.where(invalid[:, None], 0.0, b)
                    xmin = nlon.normalize(b[:, 0]).astype(np.int32)
                    ymin = nlat.normalize(b[:, 1]).astype(np.int32)
                    xmax = nlon.normalize(b[:, 2]).astype(np.int32)
                    ymax = nlat.normalize(b[:, 3]).astype(np.int32)
                    if invalid.any():
                        imax = np.iinfo(np.int32).max
                        xmin[invalid] = imax
                        xmax[invalid] = -1  # hi < 0 <= qlo: overlap always false
                        ymin[invalid] = imax
                        ymax[invalid] = -1
                    cols, padded, rows_per_shard = shard_columns(
                        mesh,
                        {
                            "xmin": xmin, "ymin": ymin, "xmax": xmax, "ymax": ymax,
                            "bins": bins, "offs": offs,
                        },
                        multiple=JOIN_BLOCK,
                    )
                    state[name] = _MeshIndexState(
                        cols=cols, rows_per_shard=rows_per_shard, n=len(table),
                        kind="bboxes",
                    )
                    used_bytes += state[name].nbytes
                    register_residency(
                        self.pool, type_name, name, devmon.GROUP_BBOX,
                        state[name].nbytes, owner=state[name],
                        fingerprint=fingerprint,
                        on_evict=_slot_clearer(state, name))
                    load_pins.enter_context(self.pool.pinned(type_name, name))
        finally:
            load_pins.close()
        # deterministic device-corruption fault injection (resilience/
        # faults.py kind="flip"): flips ONE staged device-column value so
        # the correctness auditor's red legs have a real silent-wrong-
        # answer to catch. Consulted only when an injector is active —
        # the fault-free path is one module-global read.
        from geomesa_tpu.resilience import faults as _faults

        inj = _faults.active()
        if inj is not None:
            self._apply_device_flips(inj, type_name, state)
        return state

    @staticmethod
    def _apply_device_flips(inj, type_name: str, state: dict) -> None:
        """Apply fired ``kind=flip`` rules: XOR bit 30 into row ``at``
        of the x/xmin column of EVERY resident index layout — a large
        silent coordinate corruption the host table does NOT share, so
        whichever index the planner scans diverges from the referee on
        exactly the rows the flipped coordinate moves across a query
        boundary (one flipped value per resident layout; the strategy
        decider picks the layout freely, so a single-index flip would
        make the red leg depend on planner mood)."""
        rules = inj.device_flips(type_name)
        if not rules:
            return
        import jax

        for r in rules:
            for dev in state.values():
                if not isinstance(dev, _MeshIndexState):
                    continue
                col = "x" if dev.kind == "points" else "xmin"
                arr = dev.cols[col]
                host = np.asarray(arr).copy()
                flat = host.reshape(-1)
                row = (r.truncate_at or 0) % max(len(flat), 1)
                flat[row] = np.int32(int(flat[row]) ^ (1 << 30))
                sharding = getattr(arr, "sharding", None)
                dev.cols[col] = (
                    jax.device_put(host, sharding) if sharding is not None
                    else jax.device_put(host)
                )

    # -- refine payload (int-domain superset bounds) -------------------------
    def _payload(self, sft: FeatureType, e: Extraction, overlap: bool = False):
        from geomesa_tpu.ops.refine import pack_boxes, pack_times

        nlon = norm_lon(REFINE_PRECISION)
        nlat = norm_lat(REFINE_PRECISION)
        boxes = None
        if e.boxes is not None:
            boxes = np.array(
                [
                    [
                        int(nlon.normalize(x1)),
                        int(nlon.normalize(x2)),
                        int(nlat.normalize(y1)),
                        int(nlat.normalize(y2)),
                    ]
                    for x1, y1, x2, y2 in e.boxes
                ],
                dtype=np.int32,
            )
        times = time_quads(sft, e.intervals)
        return pack_boxes(boxes, overlap=overlap), pack_times(times)

    @dispatch_budget(2, signatures=("*:rows",))
    def select(self, state, index, plan, extraction, residual, table):
        import time as _time

        intervals = plan.intervals
        if len(intervals) == 0:
            return np.empty(0, dtype=np.int64)
        dev = state.get(index.name) if state else None
        type_name = getattr(index.sft, "name", "?")
        if dev is None:
            # host path: expand + residual. A pool MISS only when this
            # index COULD have been resident (a device-servable layout
            # over a non-empty geometry table — i.e. it was evicted or
            # budget-spilled); host-by-design indexes (id, geometry-less
            # types) must not drown the hit rate in noise
            if (
                state
                and index.name in self._LOAD_PRIORITY
                and len(table)
                and index.sft.geom_field is not None
            ):
                self.pool.note_miss(type_name, index.name)
            with obs.span("refine", mode="host", index=index.name):
                positions, total = gather_indices(intervals)
                rows = index.perm[positions[:total]]
                return rows[ast.residual_mask(residual, table, rows)]

        # adaptive dispatch route (planning/costmodel.py): "twopass" is the
        # per-query candidate-slot count+gather; "planned" runs the batched
        # block-pair steps with a singleton batch — the SAME compiled
        # executables select_many uses, so both modes share one jit cache
        # (the bench-6 fast path). Observed wall per route feeds the cost
        # table under sel:twopass / sel:planned, and the model's probe
        # schedule keeps the losing route measured so the verdict can flip
        # with hardware (dispatch-RTT-bound links favor stable shapes;
        # local backends favor the tighter candidate gather).
        from geomesa_tpu.obs import devmon
        from geomesa_tpu.planning import costmodel

        route = "twopass"
        if dev.rows_per_shard % JOIN_BLOCK == 0:
            route = costmodel.model().choose_select_route(type_name)
        # access-frequency accounting + dispatch pin: a pinned buffer is
        # never an eviction victim, so the scan below cannot lose its
        # columns mid-flight
        self.pool.touch(type_name, index.name)
        t0 = _time.perf_counter()
        with self.pool.pinned(type_name, index.name), \
                obs.span("dispatch", index=index.name,
                         intervals=len(intervals), route=route):
            if route == "planned":
                positions = self.select_many_positions(
                    dev, index, [extraction], [intervals])[0]
            else:
                positions = self._mesh_select_positions(
                    dev, index, extraction, intervals, plan=plan
                )
        from geomesa_tpu.obs import audit as _obsaudit

        if not _obsaudit.in_shadow():
            # audit-shadow re-executions (the divergence minimizer runs
            # the live path repeatedly) must not train the sel:* route
            # profiles — same hygiene as the _audit-level exclusions
            devmon.costs().observe(
                type_name, f"sel:{route}",
                wall_ms=(_time.perf_counter() - t0) * 1000.0,
                rows=len(positions),
            )
        rows = index.perm[positions]
        if isinstance(residual, ast.Include):
            return rows
        with obs.span("refine", candidates=len(rows)):
            return rows[ast.residual_mask(residual, table, rows)]

    @dispatch_budget(2)
    def select_many_positions(
        self, dev: "_MeshIndexState", index, extractions, intervals_list
    ) -> list[np.ndarray]:
        """Matching sorted-order positions for MANY queries in TWO device
        dispatches total (VERDICT r4 item 2 — the multi-query row-retrieval
        path): a planned pair-count pass sizes the gather EXACTLY, then one
        block gather serves every query. Dispatch RTT amortizes across the
        batch the way the fused count/density steps do, and (query, block)
        pair ids ship host→device in KBs where the per-row candidate slots
        of :meth:`_mesh_select_positions` ship MBs per query.

        Both residency layouts serve: point containment
        (``dev.kind == "points"``) and bbox overlap (``"bboxes"`` — the
        XZ extended-geometry layout); the block grid rides the
        JOIN_BLOCK-aligned residency either way. Counts and gather
        evaluate the same int-domain predicate, so gather overflow is
        impossible.
        """
        import jax.numpy as jnp

        from geomesa_tpu.parallel.mesh import data_shards
        from geomesa_tpu.parallel.query import (
            cached_planned_count_step,
            cached_planned_gather_step,
            intervals_to_block_pairs,
            pad_block_pairs,
        )

        mesh = self._get_mesh()
        nq = len(intervals_list)
        B = JOIN_BLOCK
        if dev.rows_per_shard % B != 0:
            raise ValueError(
                f"residency not block-aligned: {dev.rows_per_shard} % {B}")
        pair_q, pair_blk = intervals_to_block_pairs(intervals_list, B)
        empty = [np.empty(0, dtype=np.int64) for _ in range(nq)]
        if len(pair_q) == 0:
            return empty
        # pairs processed per scan step: larger chunks amortize per-step
        # overhead on accelerators (live gather memory = chunk × JOIN_BLOCK
        # × 24 B); power of two so the padded budget always divides
        chunk = int(os.environ.get("GEOMESA_SELECT_BLOCK_CHUNK", "8"))
        if chunk < 1 or chunk & (chunk - 1):
            raise ValueError(
                f"GEOMESA_SELECT_BLOCK_CHUNK must be a power of two: {chunk}")
        budget = pad_bucket(len(pair_q), minimum=chunk)
        pq, pb = pad_block_pairs(pair_q, pair_blk, budget)
        overlap = dev.kind == "bboxes"
        payloads = [
            self._payload(index.sft, e, overlap=overlap) for e in extractions
        ]
        # bucket the query-batch dimension too: every compile-time shape
        # (nqp, budget, capacity) is a bucket, so naturally varying batch
        # sizes reuse cached executables instead of recompiling per size.
        # Padded query slots are never referenced by any pair. The planned
        # steps split this axis over the mesh query axis, so the bucket must
        # also divide by it (the pad_query_axis contract) — a pure power-of-
        # two bucket fails dispatch on query_parallel=3 etc.
        import math

        from geomesa_tpu.parallel.mesh import QUERY_AXIS

        nqp = math.lcm(pad_bucket(nq, minimum=4), mesh.shape[QUERY_AXIS])
        boxes = np.stack(
            [p[0] for p in payloads]
            + [np.zeros_like(payloads[0][0])] * (nqp - nq)
        )
        times = np.stack(
            [p[1] for p in payloads]
            + [np.zeros_like(payloads[0][1])] * (nqp - nq)
        )
        from geomesa_tpu.obs.jaxmon import count_h2d

        count_h2d(pq, pb, boxes, times)  # per-batch payload staging
        args = (
            *dev.spatial_cols(), jnp.int32(dev.n),
        )
        # pool accounting + pin: the batch's two dispatches read the same
        # resident columns; pinned buffers are never eviction victims
        type_name = getattr(index.sft, "name", "?")
        self.pool.touch(type_name, index.name)
        with self.pool.pinned(type_name, index.name):
            with obs.span("dispatch.count", queries=nq, pairs=len(pair_q)):
                # inter-stage host sync: the pair counts size the gather
                counts = _rtledger.materialize(
                    cached_planned_count_step(mesh, nqp, B, budget, chunk,
                                              overlap=overlap)(
                        *args, jnp.asarray(pq[None]), jnp.asarray(pb[None]),
                        jnp.asarray(boxes[None]), jnp.asarray(times[None]),
                    )
                )[0]
            total = int(counts.sum())
            if total == 0:
                return empty
            capacity = pad_bucket(total, minimum=128)
            with obs.span("dispatch.gather", capacity=capacity):
                buf, hits = cached_planned_gather_step(
                    mesh, B, budget, capacity, chunk, overlap=overlap)(
                    *args, jnp.asarray(pq), jnp.asarray(pb),
                    jnp.asarray(boxes), jnp.asarray(times),
                )
                buf = _rtledger.materialize(buf)
                hits = _rtledger.materialize(hits)
        # per-pair spans: a pair's rows sit in its OWNER shard's buffer,
        # consecutively in pair-index order (the device scan's write order)
        blocks_per_shard = dev.rows_per_shard // B
        out: list[list[np.ndarray]] = [[] for _ in range(nq)]
        off = np.zeros(data_shards(mesh), dtype=np.int64)
        for p in range(budget):
            qid = int(pq[p])
            if qid < 0:
                continue
            d = int(pb[p]) // blocks_per_shard
            h = int(hits[p])
            if h:
                out[qid].append(buf[d, off[d]: off[d] + h])
            off[d] += h
        return [
            np.concatenate(o).astype(np.int64) if o
            else np.empty(0, dtype=np.int64)
            for o in out
        ]

    @dispatch_budget(2)
    def _mesh_select_positions(
        self, dev: _MeshIndexState, index, extraction, intervals, plan=None
    ) -> np.ndarray:
        """Distributed two-pass refine → matching sorted-order positions.

        ``plan``: the owning :class:`~geomesa_tpu.index.api.IndexPlan`,
        when the caller has one — its ``exec_cache`` memoizes the derived
        per-shard interval split and the staged device payloads, so a plan
        served from the store's plan cache dispatches with ZERO host
        re-derivation or re-staging (the dominant host cost of the steady
        per-query select path). The memo key carries the layout shape; a
        reload with a different shape misses instead of mis-pairing.
        """
        import jax.numpy as jnp

        from geomesa_tpu.parallel.mesh import data_shards
        from geomesa_tpu.parallel.query import (
            cached_select_count_step,
            cached_select_count_step_bbox,
            cached_select_gather_step,
            cached_select_gather_step_bbox,
            max_shard_candidates,
            split_intervals_by_shard,
        )

        mesh = self._get_mesh()
        n_shards = data_shards(mesh)
        bbox_mode = dev.kind == "bboxes"
        memo_key = ("twopass", id(mesh), dev.rows_per_shard, dev.kind)
        memo = plan.exec_cache.get(memo_key) if plan is not None else None
        if memo is None:
            mx = max_shard_candidates(intervals, dev.rows_per_shard, n_shards)
            if mx == 0:
                memo = (0, None, None, None, None)
                if plan is not None:
                    plan.exec_cache[memo_key] = memo
                return np.empty(0, dtype=np.int64)
            bucket = pad_bucket(mx)
            idx, counts = split_intervals_by_shard(
                intervals, dev.rows_per_shard, n_shards, bucket
            )
            boxes, times = self._payload(
                index.sft, extraction, overlap=bbox_mode)
            from geomesa_tpu.obs.jaxmon import count_h2d

            count_h2d(idx, counts, boxes, times)  # per-query payload staging
            memo = (
                mx,
                jnp.asarray(idx), jnp.asarray(counts),
                jnp.asarray(boxes), jnp.asarray(times),
            )
            # memoize only payloads under the per-plan slot cap — a wide
            # scan's (n_shards, bucket) split can reach MBs per plan and
            # those re-derive per query (their cost is scan-dominated
            # anyway). Memoized bytes ARE device residency: register them
            # in the ledger under the "planmemo" group with the PLAN as
            # owner, so the footprint shows in the residency gauges /
            # budget headroom and unregisters itself when the plan cache
            # drops the plan (LRU or state swap). Not pool-evictable by
            # design: the per-plan cap bounds each entry and the plan
            # cache's 128-entry LRU bounds the aggregate.
            if (plan is not None
                    and n_shards * bucket <= _EXEC_MEMO_MAX_SLOTS):
                plan.exec_cache[memo_key] = memo
                from geomesa_tpu.obs import devmon

                devmon.ledger().register(
                    getattr(index.sft, "name", "?"), index.name,
                    "planmemo",
                    sum(int(a.nbytes) for a in memo[1:]),
                    owner=plan,
                )
        mx, d_idx, d_counts, d_boxes, d_times = memo
        if mx == 0:
            return np.empty(0, dtype=np.int64)
        c = dev.cols
        if bbox_mode:
            col_args = (
                c["xmin"], c["xmax"], c["ymin"], c["ymax"], c["bins"], c["offs"]
            )
        else:
            col_args = (c["x"], c["y"], c["bins"], c["offs"])
        gather = (cached_select_gather_step_bbox if bbox_mode
                  else cached_select_gather_step)
        # single-dispatch route: the count pass exists only to TIGHTEN the
        # gather capacity (matches <= planner candidates), but each extra
        # dispatch pays a full host->device round trip — ~77 ms over the
        # relay tunnel vs the few ms the tighter transfer saves. When the
        # planner's candidate bound is already small, gather straight at
        # that bound; the two-pass stays for wide scans where an untamed
        # capacity would dominate transfer and pos-buffer memory.
        # compare the PADDED capacity (what the gather actually allocates
        # and transfers), not the raw candidate bound
        if n_shards * pad_bucket(mx, minimum=128) <= _ONE_PASS_MAX_SLOTS:
            capacity = pad_bucket(mx, minimum=128)
        else:
            count_step = (cached_select_count_step_bbox if bbox_mode
                          else cached_select_count_step)(mesh)
            # the inter-stage host sync of the two-pass route: the count
            # result must land on host before the gather capacity exists
            # (ledger.materialize = np.asarray + roundtrip sync accounting)
            per_shard = _rtledger.materialize(
                count_step(*col_args, d_idx, d_counts, d_boxes, d_times)
            )
            top = int(per_shard.max())
            if top == 0:
                return np.empty(0, dtype=np.int64)
            capacity = pad_bucket(top, minimum=128)
        pos, hits = gather(mesh, capacity)(
            *col_args, d_idx, d_counts, d_boxes, d_times
        )
        pos = _rtledger.materialize(pos)
        hits = _rtledger.materialize(hits)
        return np.concatenate(
            [pos[d, : hits[d]] for d in range(n_shards)]
        ).astype(np.int64)
