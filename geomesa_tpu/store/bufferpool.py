"""Shared HBM buffer pool / residency manager (ROADMAP item 1).

The backend's per-type ``max_device_bytes`` budget decides what a single
type may hold (over-budget indexes spill to the host path exactly as
before); the pool layers the CROSS-query, cross-type policy on top:

- **Pinning**: every device-resident (type, index, column-group) buffer
  registers here; the pool holds the strong reference that keeps the
  owning state object (and its device arrays) alive between queries.
- **Eviction**: a process-level HBM budget (``GEOMESA_TPU_HBM`` bytes, or
  the constructor argument) caps TOTAL residency. Admission sums the
  per-entry byte counts recorded at registration — the same values
  handed to the devmon residency ledger, which remains the reporting
  source of truth (agreement is pinned in tests; live ledger sums are
  not used for admission because a mid-rebuild type briefly has old and
  new rows ledgered at once). When a load needs room, the coldest
  unpinned buffers go first, ordered by SLO-weighted
  access frequency: ``(slo_weight, hits, last_used)`` ascending, so a
  type burning its SLO budget keeps its buffers over an idle one. A
  buffer that is **pinned** (a dispatch is reading it right now) is
  never a victim — eviction mid-dispatch is impossible by construction.
- **Donation**: an evicted (or released-for-reload) state object parks in
  a victim stash keyed by its load *fingerprint* (the owning type's
  rebuild epoch). Delta writes don't bump the rebuild epoch — the main
  tier is unchanged — so donated buffers stay reusable across hot-tier
  appends; the next ``load``/``recover`` at the same fingerprint
  re-admits them without re-staging a single byte host→device. The
  stash is the FIRST thing reclaimed when room is needed (it is spare
  capacity, not working set).

Evicted groups land in the ledger's spill report (``type``,
``index:group``) so the ops surface shows what the budget pushed out.

Locking: ONE leaf lock (docs/concurrency.md). Eviction callbacks and
reference drops (which trigger device deallocation + the ledger's
weakref finalizers) always run AFTER the lock is released — no foreign
lock and no blocking call is ever taken under it.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from contextlib import contextmanager

from geomesa_tpu.analysis.contracts import cache_surface

__all__ = ["HBM_ENV", "BufferPool", "register_residency"]

HBM_ENV = "GEOMESA_TPU_HBM"  # process-level pool budget, in bytes


def _env_budget() -> int | None:
    raw = os.environ.get(HBM_ENV)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{HBM_ENV} must be an integer byte count, got {raw!r}"
        ) from None


class _Entry:
    """One pooled residency unit: every column group registered for one
    (type, index) owner object. Access stats live here; the strong
    ``owner`` reference IS the pin that keeps the device arrays alive."""

    __slots__ = ("type_name", "index", "groups", "owner", "on_evict",
                 "fingerprint", "hits", "last_used", "pins")

    def __init__(self, type_name, index, owner, fingerprint, on_evict):
        self.type_name = type_name
        self.index = index
        self.groups: dict[str, int] = {}
        self.owner = owner
        self.on_evict = on_evict
        self.fingerprint = fingerprint
        self.hits = 0
        self.last_used = 0
        self.pins = 0

    @property
    def nbytes(self) -> int:
        return sum(self.groups.values())


@cache_surface(name="buffer-pool", keyed_by="type_name", purge=("purge",))
class BufferPool:
    """See module docstring. One instance per :class:`TpuBackend`."""

    def __init__(self, max_total_bytes: int | None = None):
        if max_total_bytes is None:
            max_total_bytes = _env_budget()
        self.max_total_bytes = max_total_bytes
        self._lock = threading.Lock()  # leaf: entries/stash/stats only
        self._entries: dict[tuple, _Entry] = {}  # (type, index) -> entry
        # victim stash: (type, index, fingerprint) -> _Entry (insertion
        # order = donation order; reclaimed oldest-first)
        self._donated: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._clock = 0
        # SLO weight per type (>= 1.0): higher = keep resident longer.
        # DataStore feeds this from the SLO engine's remaining budget.
        self._weights: dict[str, float] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.donations = 0
        self.reuses = 0
        # optional HBM→RAM→disk demotion ladder (serving/elastic.py);
        # consulted OUTSIDE the pool lock — it does array export and
        # file I/O
        self._tiering = None

    def attach_tiering(self, policy) -> None:
        """Attach a :class:`~geomesa_tpu.serving.elastic.TieringPolicy`:
        evicted/reclaimed entries demote to host RAM (then disk) instead
        of freeing outright, and donation-stash misses consult the lower
        tiers before the caller re-stages from the columnar tier."""
        self._tiering = policy
        policy.bind_pool(self)

    # -- accounting source of truth -------------------------------------------
    @staticmethod
    def _ledger():
        from geomesa_tpu.obs import devmon

        return devmon.ledger()

    # -- registration (the backend's side) ------------------------------------
    def register(self, type_name: str, index: str, group: str, nbytes: int,
                 owner, fingerprint=None, on_evict=None) -> None:
        """Pin one column group. Groups registered for the same
        (type, index) with the same owner merge into one entry (they share
        one lifetime); a different owner replaces the entry (reload)."""
        with self._lock:
            key = (type_name, index)
            e = self._entries.get(key)
            if e is None or e.owner is not owner:
                e = self._entries[key] = _Entry(
                    type_name, index, owner, fingerprint, on_evict)
            e.groups[group] = e.groups.get(group, 0) + int(nbytes)
            if on_evict is not None:
                e.on_evict = on_evict
            if fingerprint is not None:
                e.fingerprint = fingerprint
            self._clock += 1
            e.last_used = self._clock

    def touch(self, type_name: str, index: str) -> bool:
        """Access-frequency accounting: a dispatch is about to read this
        buffer. Returns True (hit) when the buffer is pooled."""
        with self._lock:
            e = self._entries.get((type_name, index))
            if e is None:
                self.misses += 1
                return False
            self._clock += 1
            e.hits += 1
            e.last_used = self._clock
            self.hits += 1
            return True

    def note_miss(self, type_name: str, index: str) -> None:
        """A dispatch wanted resident buffers that are not pooled (host
        fallback)."""
        with self._lock:
            self.misses += 1

    def note_slo(self, type_name: str, budget_remaining: float) -> None:
        """SLO feedback: weight = 2 - remaining budget fraction, so a type
        with an exhausted error budget scores double an untroubled one."""
        w = 2.0 - min(max(float(budget_remaining), 0.0), 1.0)
        with self._lock:
            self._weights[type_name] = max(w, 1.0)

    # -- pinning (dispatch protection) ----------------------------------------
    @contextmanager
    def pinned(self, type_name: str, index: str):
        """Hold while a dispatch reads the buffers of (type, index): a
        pinned entry is never an eviction victim."""
        key = (type_name, index)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.pins += 1
        try:
            yield
        finally:
            with self._lock:
                e2 = self._entries.get(key)
                if e2 is not None and e2 is e:
                    e2.pins = max(e2.pins - 1, 0)

    # -- eviction / room management -------------------------------------------
    def _score(self, e: _Entry) -> tuple:
        """Eviction order key, ascending = colder. SLO-weighted access
        frequency: weight first (protect burning types), then lifetime
        hits, then recency."""
        w = self._weights.get(e.type_name, 1.0)
        return (w, e.hits, e.last_used)

    def _usage(self) -> int:
        """Bytes this pool manages: the per-entry group bytes recorded at
        registration — the SAME values the devmon ledger was handed, so
        the two agree in steady state (pinned in tests/test_bufferpool).
        Summed per entry rather than queried live from the ledger for
        two reasons: foreign allocations (another store's same-named
        type) must not count against this budget, and during a rebuild
        the old state's ledger rows linger until the swap — live ledger
        sums would double-count the type and over-evict mid-load."""
        with self._lock:
            return (
                sum(e.nbytes for e in self._entries.values())
                + sum(e.nbytes for e in self._donated.values())
            )

    def ensure_room(self, need_bytes: int) -> bool:
        """Make ``need_bytes`` of budget headroom, reclaiming the donated
        stash first (it is spare capacity, not working set), then
        evicting the coldest unpinned live entries. Returns False when
        the remaining (pinned) working set cannot fit the request — the
        caller spills to host, exactly as a per-type over-budget load
        does. Reference drops happen OUTSIDE the pool lock: deallocation
        runs the ledger's weakref finalizers."""
        if self.max_total_bytes is None:
            return True

        def _headroom() -> int:
            return self.max_total_bytes - self._usage()

        if _headroom() >= need_bytes:
            return True
        # 1) reclaim the stash, oldest donation first — demoted to the
        #    lower tiers when a tiering policy is attached (outside the
        #    lock: demotion exports arrays host-side)
        while _headroom() < need_bytes:
            with self._lock:
                if not self._donated:
                    break
                _, victim = self._donated.popitem(last=False)
            if self._tiering is not None:
                try:
                    self._tiering.demote_entry(victim)
                except Exception:  # noqa: BLE001 — degrade to a plain drop
                    pass
            victim = None  # noqa: F841 — ref drop IS the reclamation
        if _headroom() >= need_bytes:
            return True
        # 2) evict cold live entries (never pinned ones); room is needed
        #    NOW, so pressure evictions free immediately instead of
        #    parking in the stash
        while True:
            with self._lock:
                candidates = [
                    e for e in self._entries.values() if e.pins == 0
                ]
                victim = (
                    min(candidates, key=self._score) if candidates else None
                )
                if victim is not None:
                    del self._entries[(victim.type_name, victim.index)]
                    self.evictions += 1
            if victim is None:  # only pinned working set left
                return _headroom() >= need_bytes
            self._after_evict(victim)
            victim = None  # the last strong ref: device bytes free here
            if _headroom() >= need_bytes:
                return True

    def _after_evict(self, e: _Entry) -> None:
        """Post-eviction bookkeeping, OUTSIDE the pool lock: demote to
        the lower tiers when attached (the owner survives holding host
        copies), clear the owner's slot (host path serves from now on),
        and record the spill."""
        if self._tiering is not None:
            try:
                self._tiering.demote_entry(e)
            except Exception:  # noqa: BLE001 — degrade to a plain eviction
                pass
        if e.on_evict is not None:
            try:
                e.on_evict()
            except Exception:  # noqa: BLE001 — bookkeeping must not throw
                pass
        ledger = self._ledger()
        for group, nbytes in e.groups.items():
            ledger.record_spill(e.type_name, f"{e.index}:{group}", nbytes)

    # -- release / donation (reload seam) -------------------------------------
    def release(self, type_name: str, keep_fingerprint=None) -> None:
        """A fresh load for ``type_name`` is starting: retire its live
        entries. Entries whose fingerprint matches ``keep_fingerprint``
        (same main tier — e.g. a recover() after a budget eviction, or a
        reload across delta-only writes) move to the donation stash for
        zero-copy re-admission; anything else is dropped (data changed)."""
        drop: list[_Entry] = []
        with self._lock:
            for key in [k for k in self._entries if k[0] == type_name]:
                e = self._entries.pop(key)
                if (keep_fingerprint is not None
                        and e.fingerprint == keep_fingerprint):
                    self.donations += 1
                    self._donated[(e.type_name, e.index, e.fingerprint)] = e
                else:
                    drop.append(e)
            # stale stash entries of this type with a DIFFERENT fingerprint
            # can never be re-admitted — free them now
            for key in [k for k in self._donated
                        if k[0] == type_name and k[2] != keep_fingerprint]:
                drop.append(self._donated.pop(key))
            tier = self._tiering
        del drop  # refs drop outside the lock
        if tier is not None:
            # demoted copies at a superseded fingerprint are unpromotable
            tier.invalidate(type_name, keep_fingerprint)

    def take_donated(self, type_name: str, index: str, fingerprint,
                     on_evict=None):
        """Re-admit a donated buffer set: returns the stashed owner state
        (its ledger entries never unregistered — accounting is
        continuous) or None. ``on_evict`` MUST be the slot-clearer bound
        to the caller's NEW state dict — the stashed closure points at
        the discarded one, and a later eviction through it would free
        nothing while the live slot kept serving."""
        if fingerprint is None:
            return None
        with self._lock:
            e = self._donated.pop((type_name, index, fingerprint), None)
            if e is not None:
                self.reuses += 1
                key = (type_name, index)
                self._entries[key] = e
                if on_evict is not None:
                    e.on_evict = on_evict
                self._clock += 1
                e.last_used = self._clock
                return e.owner
            tier = self._tiering
        if tier is None:
            return None
        # stash miss: the lower tiers may hold a demoted copy. Promotion
        # (disk/host → device staging + ledger re-registration) runs
        # OUTSIDE the pool lock; only the re-admission takes it.
        e = tier.take(type_name, index, fingerprint)
        if e is None:
            return None
        with self._lock:
            self.reuses += 1
            self._entries[(type_name, index)] = e
            if on_evict is not None:
                e.on_evict = on_evict
            self._clock += 1
            e.last_used = self._clock
            return e.owner

    def drop_donated(self, type_name: str, index: str) -> None:
        """Free any stashed donation for one (type, index) — a load whose
        budget refused the index must not leave its old buffers holding
        the very bytes it declined."""
        drop = []
        with self._lock:
            for key in [k for k in self._donated
                        if k[0] == type_name and k[1] == index]:
                drop.append(self._donated.pop(key))
        del drop

    def purge(self, type_name: str) -> None:
        """Drop every live and donated entry of one type (explicit
        ``evict_device`` — operator intent: free the HBM now)."""
        drop = []
        with self._lock:
            for key in [k for k in self._entries if k[0] == type_name]:
                drop.append(self._entries.pop(key))
            for key in [k for k in self._donated if k[0] == type_name]:
                drop.append(self._donated.pop(key))
            tier = self._tiering
        del drop
        if tier is not None:
            tier.invalidate(type_name)  # purge reaches every tier

    # -- read surface ---------------------------------------------------------
    def donated_bytes(self, type_name: str | None = None) -> int:
        with self._lock:
            return sum(
                e.nbytes for e in self._donated.values()
                if type_name is None or e.type_name == type_name
            )

    def snapshot(self) -> dict:
        with self._lock:
            entries = [
                {
                    "type": e.type_name,
                    "index": e.index,
                    "groups": dict(e.groups),
                    "bytes": e.nbytes,
                    "hits": e.hits,
                    "pinned": e.pins > 0,
                }
                for e in self._entries.values()
            ]
            return {
                "budget_bytes": self.max_total_bytes,
                "entries": sorted(
                    entries, key=lambda d: (d["type"], d["index"])),
                "resident_bytes": sum(d["bytes"] for d in entries),
                "donated_bytes": sum(
                    e.nbytes for e in self._donated.values()),
                "donated_count": len(self._donated),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "donations": self.donations,
                "reuses": self.reuses,
                "slo_weights": dict(self._weights),
            }

    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        snap = self.snapshot()
        lines = []
        for name in ("hits", "misses", "evictions"):
            lines.append(f"# TYPE {prefix}_pool_{name} counter")
            lines.append(f"{prefix}_pool_{name} {snap[name]}")
        lines.append(f"# TYPE {prefix}_pool_resident_bytes gauge")
        lines.append(
            f"{prefix}_pool_resident_bytes {snap['resident_bytes']}")
        lines.append(f"# TYPE {prefix}_pool_donated_bytes gauge")
        lines.append(f"{prefix}_pool_donated_bytes {snap['donated_bytes']}")
        return lines


def register_residency(pool: BufferPool, type_name: str, index: str,
                       group: str, nbytes: int, owner,
                       fingerprint=None, on_evict=None) -> None:
    """Register one device allocation with BOTH accounting systems in one
    call — the devmon residency ledger (reporting; unregisters via the
    owner's finalizer) and the buffer pool (budget admission/eviction).
    Every call site that hands the pair identical values by hand is one
    edit away from desynchronizing them: bytes resident in HBM but
    invisible to the budget, or budgeted bytes the ledger never reports."""
    from geomesa_tpu.obs import devmon

    devmon.ledger().register(type_name, index, group, int(nbytes),
                             owner=owner)
    pool.register(type_name, index, group, int(nbytes), owner=owner,
                  fingerprint=fingerprint, on_evict=on_evict)
