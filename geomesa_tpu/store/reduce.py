"""Shared post-scan result pipeline (client-side reduce).

Role parity: the tail of ``QueryPlanner.runQuery`` (``QueryPlanner.scala:
68-98``, SURVEY.md §3.3): after the scan produces candidate rows, every query
runner applies the same steps — record-level visibility, sampling, push-down
aggregation flavors (density/stats/bin), sort, limit, projection, and CRS
reprojection. Both the batch :class:`~geomesa_tpu.store.datastore.DataStore`
and the :class:`~geomesa_tpu.stream.datastore.StreamingDataStore` call
:func:`reduce_result` so the two stores can never drift semantically (the
reference shares this via ``LocalQueryRunner``).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.schema.columnar import FeatureTable, representative_xy
from geomesa_tpu.schema.sft import FeatureType

__all__ = ["reduce_result", "sample_rows", "density_grid", "bin_encode", "sort_limit"]


def sort_limit(table, rows, sort_by, limit):
    """Shared client-side sort + limit tail (``QueryPlanner.scala:75-98``);
    also used by the merged view so ordering semantics cannot drift."""
    if sort_by is not None:
        fld, desc = sort_by
        keys = table.fids if fld == "id" else table.columns[fld].values
        order = np.argsort(keys, kind="stable")
        if desc:
            order = order[::-1]
        table = table.take(order)
        rows = rows[order]
    if limit is not None:
        table = table.take(np.arange(min(limit, len(table))))
        rows = rows[:limit]
    return table, rows


def sample_rows(table, rows, fraction, sample_by):
    """Deterministic every-nth sampling (FeatureSampler/SamplingIterator)."""
    if fraction <= 0 or fraction >= 1 or len(rows) == 0:
        return rows
    nth = int(round(1.0 / fraction))
    if nth <= 1:  # fractions near 1 round to keep-everything
        return rows
    if sample_by is None:
        return rows[::nth]
    keys = table.columns[sample_by].values[rows]
    keep = np.zeros(len(rows), dtype=bool)
    seen: dict = {}
    for i, k in enumerate(keys):
        c = seen.get(k, 0)
        if c % nth == 0:
            keep[i] = True
        seen[k] = c + 1
    return rows[keep]


def density_grid(table, opts) -> np.ndarray:
    """Exact f64 heatmap over the result set (DensityScan role); the sharded
    device path computes the same grid via ops.density + psum."""
    width = int(opts.get("width", 256))
    height = int(opts.get("height", 256))
    xs, ys = representative_xy(table)
    bbox = opts.get("bbox")
    if bbox is None:
        bbox = (-180.0, -90.0, 180.0, 90.0)
    xmin, ymin, xmax, ymax = bbox
    weight = opts.get("weight_by")
    w = None
    if weight:
        w = table.columns[weight].values.astype(np.float64)
    grid, _, _ = np.histogram2d(
        ys, xs, bins=[height, width], range=[[ymin, ymax], [xmin, xmax]], weights=w
    )
    return grid


def bin_encode(table, opts) -> bytes:
    from geomesa_tpu.utils import bin_format

    xs, ys = representative_xy(table)
    track = opts.get("track")
    label = opts.get("label")
    return bin_format.encode(
        xs,
        ys,
        table.dtg_millis(),
        track_values=table.columns[track].values if track else table.fids,
        label_values=table.columns[label].values if label else None,
        sort_by_time=bool(opts.get("sort", False)),
    )


def reduce_result(sft: FeatureType, table: FeatureTable, rows: np.ndarray, q):
    """Apply the shared post-scan pipeline for a query.

    Returns ``(table, rows, density, stats, bin_data)``; exactly one of the
    aggregate slots is non-None when the corresponding hint was set.
    """
    # record-level visibility (geomesa-security role): a schema opting in via
    # user-data ``geomesa.vis.field`` names a String attribute holding the
    # per-record visibility expression; rows the caller's auths can't satisfy
    # are removed before any sampling/aggregation sees them
    vis_field = sft.user_data.get("geomesa.vis.field")
    if vis_field and q.auths is not None:
        from geomesa_tpu.security.visibility import evaluate_column

        visible = evaluate_column(table.columns[vis_field].values, q.auths)
        keep = np.nonzero(visible)[0]
        table = table.take(keep)
        rows = rows[keep]

    # sampling (FeatureSampler / SamplingIterator role): keep ~fraction of
    # matches, optionally per-group (deterministic every-nth)
    sample = q.hints.get("sample")
    if sample:
        keep = sample_rows(
            table, np.arange(len(table)), float(sample), q.hints.get("sample_by")
        )
        table = table.take(keep)
        rows = rows[keep]

    # aggregation hints (density/stats/bin push-down flavors)
    density = stats_out = bin_data = None
    if "density" in q.hints:
        density = density_grid(table, q.hints["density"] or {})
    if "stats" in q.hints:
        from geomesa_tpu.stats.spec import compute_stats

        stats_out = compute_stats(table, q.hints["stats"])
    if "bin" in q.hints:
        bin_data = bin_encode(table, q.hints["bin"] or {})
    if density is not None or stats_out is not None or bin_data is not None:
        return table, rows, density, stats_out, bin_data

    # client-side reduce: sort / limit / reproject / projection
    # (QueryPlanner.scala:75-98); CRS runs before the properties projection
    # so a projection that drops the geometry column can't strand the hint
    table, rows = sort_limit(table, rows, q.sort_by, q.limit)

    crs = q.hints.get("crs")
    if crs:
        from geomesa_tpu.utils.crs import reproject_table

        table = reproject_table(table, crs)

    if q.properties is not None:
        keep = {p: table.columns[p] for p in q.properties}
        table = FeatureTable(table.sft, table.fids, {**keep})

    return table, rows, None, None, None
