"""Shared post-scan result pipeline (client-side reduce).

Role parity: the tail of ``QueryPlanner.runQuery`` (``QueryPlanner.scala:
68-98``, SURVEY.md §3.3): after the scan produces candidate rows, every query
runner applies the same steps — record-level visibility, sampling, push-down
aggregation flavors (density/stats/bin), sort, limit, projection, and CRS
reprojection. Both the batch :class:`~geomesa_tpu.store.datastore.DataStore`
and the :class:`~geomesa_tpu.stream.datastore.StreamingDataStore` call
:func:`reduce_result` so the two stores can never drift semantically (the
reference shares this via ``LocalQueryRunner``).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.schema.columnar import FeatureTable, representative_xy
from geomesa_tpu.schema.sft import FeatureType

__all__ = ["reduce_result", "sample_rows", "density_grid", "bin_encode", "sort_limit"]


def stable_order(values: np.ndarray, desc: bool) -> np.ndarray:
    """THE stable argsort both directions, shared by the store's sort
    pushdown and the SQL engine's post-sort so tie order can never diverge
    between engines: descending keeps tied rows in their ORIGINAL order (a
    plain ``argsort()[::-1]`` would reverse ties)."""
    if not desc:
        return np.argsort(values, kind="stable")
    n = len(values)
    return (n - 1 - np.argsort(values[::-1], kind="stable"))[::-1]


def sort_limit(table, rows, sort_by, limit, start_index=None):
    """Shared client-side sort + paging tail (``QueryPlanner.scala:75-98``;
    ``start_index`` is the OGC ``Query.startIndex`` offset, applied after the
    sort and before ``limit``); also used by the merged view so ordering
    semantics cannot drift."""
    if start_index is not None and start_index < 0:
        raise ValueError(f"start_index must be >= 0: {start_index}")
    if limit is not None and limit < 0:
        raise ValueError(f"limit must be >= 0: {limit}")
    if sort_by is not None:
        fld, desc = sort_by
        keys = table.fids if fld == "id" else table.columns[fld].values
        order = stable_order(keys, desc)
        table = table.take(order)
        rows = rows[order]
    lo = min(int(start_index), len(table)) if start_index else 0
    hi = len(table) if limit is None else min(lo + limit, len(table))
    if lo > 0 or hi < len(table):
        table = table.take(np.arange(lo, hi))
        rows = rows[lo:hi]
    return table, rows


def sample_rows(table, rows, fraction, sample_by):
    """Deterministic every-nth sampling (FeatureSampler/SamplingIterator)."""
    if fraction <= 0 or fraction >= 1 or len(rows) == 0:
        return rows
    nth = int(round(1.0 / fraction))
    if nth <= 1:  # fractions near 1 round to keep-everything
        return rows
    if sample_by is None:
        return rows[::nth]
    keys = table.columns[sample_by].values[rows]
    keep = np.zeros(len(rows), dtype=bool)
    seen: dict = {}
    for i, k in enumerate(keys):
        c = seen.get(k, 0)
        if c % nth == 0:
            keep[i] = True
        seen[k] = c + 1
    return rows[keep]


def _raster_cells(geom, xmin, ymin, px, py, width, height):
    """Grid cells covered by a geometry's footprint (cols, rows) —
    the ``RenderingGrid`` rasterization: lines walk their segments,
    polygons fill cells whose centers they contain."""
    from geomesa_tpu.geometry import predicates as P
    from geomesa_tpu.geometry.types import (
        LineString,
        MultiLineString,
        MultiPolygon,
        Polygon,
    )

    def line_cells(coords):
        cells = set()
        cx = (coords[:, 0] - xmin) / px
        cy = (coords[:, 1] - ymin) / py
        for i in range(len(coords) - 1):
            steps = int(max(abs(cx[i + 1] - cx[i]), abs(cy[i + 1] - cy[i])) * 2) + 2
            t = np.linspace(0.0, 1.0, steps)
            gx = np.floor(cx[i] + (cx[i + 1] - cx[i]) * t).astype(int)
            gy = np.floor(cy[i] + (cy[i + 1] - cy[i]) * t).astype(int)
            ok = (gx >= 0) & (gx < width) & (gy >= 0) & (gy < height)
            cells.update(zip(gx[ok].tolist(), gy[ok].tolist()))
        return cells

    def poly_cells(poly):
        bx1, by1, bx2, by2 = poly.bbox
        jx1 = max(0, int(np.floor((bx1 - xmin) / px)))
        jx2 = min(width, int(np.ceil((bx2 - xmin) / px)))
        jy1 = max(0, int(np.floor((by1 - ymin) / py)))
        jy2 = min(height, int(np.ceil((by2 - ymin) / py)))
        if jx2 <= jx1 or jy2 <= jy1:
            return set()
        gxs = np.arange(jx1, jx2)
        gys = np.arange(jy1, jy2)
        ccx = xmin + (gxs + 0.5) * px
        ccy = ymin + (gys + 0.5) * py
        mx, my = np.meshgrid(ccx, ccy)
        inside = P.points_within_geom(mx.ravel(), my.ravel(), poly)
        gx, gy = np.meshgrid(gxs, gys)
        out = set(zip(gx.ravel()[inside].tolist(), gy.ravel()[inside].tolist()))
        # thin polygons can miss every cell center: fall back to the outline
        return out or line_cells(poly.shell)

    if isinstance(geom, LineString):
        return line_cells(geom.coords)
    if isinstance(geom, MultiLineString):
        out = set()
        for part in geom.parts:
            out |= line_cells(part.coords)
        return out
    if isinstance(geom, Polygon):
        return poly_cells(geom)
    if isinstance(geom, MultiPolygon):
        out = set()
        for part in geom.parts:
            out |= poly_cells(part)
        return out
    return set()


def density_grid(table, opts) -> np.ndarray:
    """Exact f64 heatmap over the result set (DensityScan role); the sharded
    device path computes the same grid via ops.density + psum.

    Point features snap to their cell; extended geometries rasterize their
    footprint (``utils/geotools/RenderingGrid`` role) with the feature's
    weight spread across touched cells, so grid mass per feature stays equal
    to its weight.
    """
    width = int(opts.get("width", 256))
    height = int(opts.get("height", 256))
    bbox = opts.get("bbox")
    if bbox is None:
        bbox = (-180.0, -90.0, 180.0, 90.0)
    xmin, ymin, xmax, ymax = bbox
    weight = opts.get("weight_by")
    w = None
    if weight:
        w = table.columns[weight].values.astype(np.float64)

    gcol = table.geom_column() if table.sft.geom_field else None
    if gcol is None or gcol.x is not None:  # point schema: vectorized snap
        xs, ys = representative_xy(table)
        grid, _, _ = np.histogram2d(
            ys, xs, bins=[height, width], range=[[ymin, ymax], [xmin, xmax]], weights=w
        )
        return grid

    px = (xmax - xmin) / width
    py = (ymax - ymin) / height
    grid = np.zeros((height, width), dtype=np.float64)
    geoms = gcol.geometries()
    valid = gcol.is_valid()
    from geomesa_tpu.geometry.types import Point

    for i in range(len(table)):
        if not valid[i]:
            continue
        g = geoms[i]
        wi = 1.0 if w is None else float(w[i])
        if isinstance(g, Point):
            gx = int(np.floor((g.x - xmin) / px))
            gy = int(np.floor((g.y - ymin) / py))
            if 0 <= gx < width and 0 <= gy < height:
                grid[gy, gx] += wi
            continue
        cells = _raster_cells(g, xmin, ymin, px, py, width, height)
        if cells:
            share = wi / len(cells)
            for gx, gy in cells:
                grid[gy, gx] += share
    return grid


def bin_encode(table, opts) -> bytes:
    from geomesa_tpu.utils import bin_format

    xs, ys = representative_xy(table)
    track = opts.get("track")
    label = opts.get("label")
    return bin_format.encode(
        xs,
        ys,
        table.dtg_millis(),
        track_values=table.columns[track].values if track else table.fids,
        label_values=table.columns[label].values if label else None,
        sort_by_time=bool(opts.get("sort", False)),
    )


def reduce_result(sft: FeatureType, table: FeatureTable, rows: np.ndarray, q):
    """Apply the shared post-scan pipeline for a query.

    Returns ``(table, rows, density, stats, bin_data)``; exactly one of the
    aggregate slots is non-None when the corresponding hint was set.
    """
    # visibility (geomesa-security role): a schema opting in via user-data
    # ``geomesa.vis.field`` names a String attribute holding the per-record
    # visibility expression — OR a comma-separated per-ATTRIBUTE expression
    # list (the reference's SecurityUtils.FEATURE_VISIBILITY convention /
    # KryoVisibilityRowEncoder role): rows with no visible attribute are
    # removed, and individual attributes the caller can't see are redacted
    # to null before any sampling/aggregation sees them
    vis_field = sft.user_data.get("geomesa.vis.field")
    if vis_field and q.auths is not None:
        from geomesa_tpu.security.visibility import apply_visibility

        table, keep = apply_visibility(sft, table, vis_field, q.auths)
        rows = rows[keep]

    # sampling (FeatureSampler / SamplingIterator role): keep ~fraction of
    # matches, optionally per-group (deterministic every-nth)
    sample = q.hints.get("sample")
    if sample:
        keep = sample_rows(
            table, np.arange(len(table)), float(sample), q.hints.get("sample_by")
        )
        table = table.take(keep)
        rows = rows[keep]

    # aggregation hints (density/stats/bin push-down flavors)
    density = stats_out = bin_data = None
    if "density" in q.hints:
        density = density_grid(table, q.hints["density"] or {})
    if "stats" in q.hints:
        from geomesa_tpu.stats.spec import compute_stats

        stats_out = compute_stats(table, q.hints["stats"])
    if "bin" in q.hints:
        bin_data = bin_encode(table, q.hints["bin"] or {})
    if density is not None or stats_out is not None or bin_data is not None:
        return table, rows, density, stats_out, bin_data

    # client-side reduce: sort / limit / reproject / projection
    # (QueryPlanner.scala:75-98); CRS runs before the properties projection
    # so a projection that drops the geometry column can't strand the hint
    table, rows = sort_limit(table, rows, q.sort_by, q.limit, q.start_index)

    crs = q.hints.get("crs")
    if crs:
        from geomesa_tpu.utils.crs import reproject_table

        table = reproject_table(table, crs)

    if q.properties is not None:
        keep = {p: table.columns[p] for p in q.properties}
        # narrow the SFT with the columns: consumers that walk sft.attributes
        # (avro/gml/shp writers) must see a self-consistent schema, not the
        # full one with columns missing (TransformSimpleFeature role)
        from geomesa_tpu.schema.sft import FeatureType

        kept = set(q.properties)
        sft = FeatureType(
            name=table.sft.name,
            attributes=[a for a in table.sft.attributes if a.name in kept],
            default_geom=(
                table.sft.geom_field if table.sft.geom_field in kept else None
            ),
            user_data=table.sft.user_data,
        )
        table = FeatureTable(sft, table.fids, {**keep})

    return table, rows, None, None, None
