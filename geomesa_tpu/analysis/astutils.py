"""Shared AST machinery for tpulint rules.

Everything here is pure ``ast`` — no JAX import, no execution of the
linted code. The two load-bearing pieces:

- :class:`ImportMap` — canonicalizes local names to dotted import paths
  (``jnp.where`` → ``jax.numpy.where``) so rules match semantics, not
  spelling. ``import jax.numpy as jnp``, ``from jax import jit``, and the
  repo's own compat shim (``from geomesa_tpu.utils.jax_compat import
  shard_map``) all resolve to the same canonical names.
- taint propagation — a per-function forward pass marking names that
  (transitively) hold traced/device values, with shape/dtype-style
  accesses shielded because they are static under tracing.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

# Canonical names of the jit entry points (pjit is jit's sharded spelling).
JIT_NAMES = frozenset({
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
})
PARTIAL_NAMES = frozenset({"functools.partial", "partial"})
CACHE_DECORATORS = frozenset({
    "functools.lru_cache",
    "functools.cache",
    "lru_cache",
    "cache",
})
# Attribute accesses on a tracer that yield STATIC (trace-time) values —
# conditioning Python control flow on these is fine.
STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "aval", "sharding", "itemsize",
})
# Builtins whose result over a tracer is static (len) or type-level.
STATIC_CALLS = frozenset({"len", "isinstance", "type", "hasattr"})


# The repo's version-bridging re-exports: symbols imported from here ARE
# the jax API and must canonicalize as such, or taint/jit detection loses
# every module that routes through the shim.
_COMPAT_MODULE = "geomesa_tpu.utils.jax_compat"


class ImportMap:
    """Local name → canonical dotted path, from a module's import statements."""

    def __init__(self, tree: ast.Module):
        self.names: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.names[alias.asname] = alias.name
                    else:
                        # ``import jax.numpy`` binds ``jax``
                        root = alias.name.split(".")[0]
                        self.names[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    module = node.module
                    if module == _COMPAT_MODULE:
                        module = "jax"  # shard_map/enable_x64 re-exports
                    self.names[local] = f"{module}.{alias.name}"

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted path of a Name/Attribute chain, or None."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.names.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def is_jit(self, node: ast.AST) -> bool:
        return self.resolve(node) in JIT_NAMES

    def is_device_namespace(self, dotted: str | None) -> bool:
        """Does this canonical path live in the traced/device value world?"""
        if dotted is None:
            return False
        return dotted == "jax" or dotted.startswith("jax.")


def build_parents(root: ast.AST) -> dict[ast.AST, ast.AST]:
    return {
        child: parent
        for parent in ast.walk(root)
        for child in ast.iter_child_nodes(parent)
    }


@dataclass
class StaticSpec:
    """Static-argument declaration parsed off a jit decoration."""

    names: set[str] = field(default_factory=set)
    nums: set[int] = field(default_factory=set)
    unhashable_nodes: list[ast.AST] = field(default_factory=list)

    def static_params(self, fn: ast.FunctionDef) -> set[str]:
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        out = set(self.names)
        for i in self.nums:
            if 0 <= i < len(params):
                out.add(params[i])
        return out


def _const_strs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def _const_ints(node: ast.AST) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def parse_static_spec(call: ast.Call) -> StaticSpec:
    """Static-arg spec from a ``jax.jit(...)``/``partial(jax.jit, ...)`` call."""
    spec = StaticSpec()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            spec.names.update(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            spec.nums.update(_const_ints(kw.value))
        else:
            continue
        if isinstance(kw.value, (ast.List, ast.Set, ast.Dict,
                                 ast.ListComp, ast.SetComp, ast.DictComp)):
            spec.unhashable_nodes.append(kw.value)
    return spec


def jit_decoration(dec: ast.AST, imports: ImportMap) -> StaticSpec | None:
    """StaticSpec if ``dec`` is a jit decoration (bare, called, or wrapped
    in ``functools.partial``); None otherwise."""
    if imports.is_jit(dec):
        return StaticSpec()
    if isinstance(dec, ast.Call):
        if imports.is_jit(dec.func):
            return parse_static_spec(dec)
        if imports.resolve(dec.func) in PARTIAL_NAMES and dec.args:
            if imports.is_jit(dec.args[0]):
                return parse_static_spec(dec)
    return None


def jitted_functions(
    tree: ast.Module, imports: ImportMap
) -> list[tuple[ast.FunctionDef, StaticSpec]]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            spec = jit_decoration(dec, imports)
            if spec is not None:
                out.append((node, spec))
                break
    return out


def pallas_kernels(tree: ast.Module, imports: ImportMap) -> list[ast.FunctionDef]:
    """FunctionDefs referenced as the kernel of a ``pl.pallas_call``."""
    kernel_names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = imports.resolve(node.func)
        if dotted is None or not dotted.endswith("pallas_call"):
            continue
        cands = list(node.args[:1]) + [
            kw.value for kw in node.keywords if kw.arg == "kernel"
        ]
        for c in cands:
            if isinstance(c, ast.Name):
                kernel_names.add(c.id)
            elif isinstance(c, ast.Call):
                # functools.partial(kernel, ...) — common pallas idiom
                for a in c.args[:1]:
                    if isinstance(a, ast.Name):
                        kernel_names.add(a.id)
    return [
        node for node in ast.walk(tree)
        if isinstance(node, ast.FunctionDef) and node.name in kernel_names
    ]


class _MentionScan(ast.NodeVisitor):
    """Does an expression mention a tainted name (unshielded) or call into
    the jax namespace? Shielded positions: ``x.shape``-style static
    attributes and ``len(x)``-style static builtins."""

    def __init__(self, tainted: set[str], imports: ImportMap):
        self.tainted = tainted
        self.imports = imports
        self.hit: ast.AST | None = None

    def visit_Attribute(self, node: ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return  # x.shape / cols[0].ndim / ... — static under tracing
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in STATIC_CALLS:
            return  # len(x), isinstance(x, T): static results
        dotted = self.imports.resolve(fn)
        if dotted is not None and self.imports.is_device_namespace(dotted):
            if self.hit is None:
                self.hit = node
            return
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        if node.id in self.tainted and self.hit is None:
            self.hit = node

    def visit_Lambda(self, node: ast.Lambda):
        pass  # deferred body — not evaluated here


def mentions_traced(expr: ast.AST, tainted: set[str], imports: ImportMap) -> bool:
    scan = _MentionScan(tainted, imports)
    scan.visit(expr)
    return scan.hit is not None


def _target_names(target: ast.AST) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def iter_body_stmts(body: list[ast.stmt]):
    """All statements in a body, recursing into compound statements but NOT
    into nested function/class definitions (those are separate scopes)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        for attr in ("body", "orelse", "finalbody"):
            yield from iter_body_stmts(getattr(stmt, attr, []))
        for handler in getattr(stmt, "handlers", []):
            yield from iter_body_stmts(handler.body)


def propagate_taint(
    fn: ast.FunctionDef, initial: set[str], imports: ImportMap
) -> set[str]:
    """Forward taint pass over ``fn``'s body: a name assigned from an
    expression that mentions a tainted name (or calls into jax.*) becomes
    tainted. Iterates to a fixpoint so loop-carried taint converges."""
    tainted = set(initial)
    while True:
        before = len(tainted)
        for stmt in iter_body_stmts(fn.body):
            if isinstance(stmt, ast.Assign):
                if mentions_traced(stmt.value, tainted, imports):
                    for t in stmt.targets:
                        tainted.update(_target_names(t))
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if mentions_traced(stmt.value, tainted, imports):
                    tainted.update(_target_names(stmt.target))
            elif isinstance(stmt, ast.AugAssign):
                if mentions_traced(stmt.value, tainted, imports):
                    tainted.update(_target_names(stmt.target))
            elif isinstance(stmt, ast.For):
                if mentions_traced(stmt.iter, tainted, imports):
                    tainted.update(_target_names(stmt.target))
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    if item.optional_vars is not None and mentions_traced(
                        item.context_expr, tainted, imports
                    ):
                        tainted.update(_target_names(item.optional_vars))
        if len(tainted) == before:
            return tainted


def nested_functions(fn: ast.FunctionDef) -> list[ast.FunctionDef]:
    out = []
    for stmt in iter_body_stmts(fn.body):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(stmt)
    return out
