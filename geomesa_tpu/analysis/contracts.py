"""tpuflow contract registry: zero-runtime-cost semantic markers.

The costliest bugs in this repro's history were *semantic contract*
violations invisible to tpulint's local AST rules and tpurace's
locksets: a recreated same-name type serving a dead table's cached
aggregates (ISSUE 7, re-found in ISSUE 15's trajectory cache), audit
shadow traffic training the cost model and burning tenant SLOs
(ISSUE 13), and f64 refinements silently skipped on one of several
routes (ISSUE 8/12). The contracts behind those fixes — "every cache
keyed by a type name dies with the name", "shadow traffic never reaches
a feedback sink", "a cand-band superset post-dominates into an f64
refine" — lived in review checklists. This module turns them into
declarations the live code imports, so the ``--flow`` prong
(:mod:`geomesa_tpu.analysis.flow`) can enforce them on every CI run and
the declarations can never drift from the code they describe.

Every marker is a no-op at runtime: decorators return their argument
unchanged and the module imports nothing but the stdlib, so decorating
a hot-path class costs one function call at import time and zero per
call. The *meaning* is read off the AST by the flow analyzer — these
markers are the vocabulary, ``python -m geomesa_tpu.analysis --flow
--contracts`` is the inventory, and docs/tpulint.md ("Declaring
contracts") is the authoring guide.

Vocabulary:

- :func:`cache_surface` — a derived-data table something else can make
  stale. Declares how entries are keyed and which functions purge it;
  F001 proves every declared mutation path actually reaches a purge,
  that name-keyed caches die on name death (delete/rename), and that
  epoch-keyed caches ride a monotonic epoch.
- :func:`mutation` — a state transition (write/delete/clear/age-off/
  evolve/delete_schema/rename) naming the cache surfaces it must
  invalidate. The F001 reachability source.
- :func:`feedback_sink` — an accumulator that trains or bills off
  observed traffic (cost table, usage meter, SLO burn, workload
  capture, plan-cache store). F002 proves shadow-plane execution cannot
  reach one except through a :func:`shadow_guard` check.
- :func:`shadow_plane` — code whose execution IS audit shadow traffic
  (the auditor, the invariant sweeper, referee execution).
- :func:`shadow_guard` — the recognized discriminators
  (``audit.in_shadow``/``audit.shadow``). A function consulting one is
  shadow-aware: F002 trusts it to gate its own sinks.
- :func:`device_band` — two-band f64 discipline roles: ``certain``
  functions must stay free of f64, ``cand`` results must flow into a
  ``refine`` call (or be returned to a caller that does) — F003.

The dispatch/host-sync budget markers below feed the FOURTH prong,
``--sync`` (:mod:`geomesa_tpu.analysis.sync`), which proves the fusion
work of ROADMAP item 1 statically — worst-case dispatch counts over the
cross-module call graph, host-sync reachability, loop-carried dispatch:

- :func:`dispatch_budget` — an upper bound on device dispatches one
  call may issue (S001), optionally tied to runtime plan signatures so
  ``--sync --reconcile`` can compare the static bound against the
  host-roundtrip ledger's measured counts.
- :func:`host_sync_free` — no host↔device sync is reachable before the
  function returns (S002); intentional awaits retire with a
  ``# tpusync: retire`` comment at the site.
- :func:`choreography_boundary` — the one sanctioned stage-orchestration
  layer: per-item fallback loops inside it are by-design host
  choreography, exempt from S003/S004 and contributing zero dispatch
  cost to callers (budgeted methods of a boundary class opt back in).
"""

from __future__ import annotations

__all__ = [
    "cache_surface", "mutation", "feedback_sink", "shadow_plane",
    "shadow_guard", "device_band", "dispatch_budget", "host_sync_free",
    "choreography_boundary", "MUTATION_KINDS", "DEATH_KINDS",
]

# The mutation taxonomy F001 reasons over. ``DEATH_KINDS`` are the
# name-death transitions: a type NAME stops answering for its old data,
# so everything keyed by the name must be purged (a recreated same-name
# successor restarts the (rebuild epoch, delta version) tuple at equal
# values — epoch stamps alone cannot catch the collision).
MUTATION_KINDS = frozenset({
    "write", "delete", "clear", "age_off", "evolve",
    "delete_schema", "rename",
})
DEATH_KINDS = frozenset({"delete_schema", "rename"})


def cache_surface(*, name, keyed_by, epoch=None, purge=(),
                  immutable=False):
    """Declare a cache surface (stackable; one decorator per surface).

    ``name``: the surface's id — what :func:`mutation` declarations
    reference. ``keyed_by``: what identifies an entry — ``"type_name"``
    (dies with the name: F001 requires a covering DEATH_KINDS mutation),
    ``"epoch"`` (entry validity rides the epoch stamp: ``epoch`` must be
    ``"monotonic"``), or a descriptive key for anything else.
    ``epoch="monotonic"`` asserts the validating stamp can never restart
    at an equal value within the cache's lifetime. ``purge``: functions
    that drop/invalidate entries — bare names resolve to methods of the
    decorated class, ``"Class.method"`` to another class's method,
    ``"pkg.mod:fn"`` to a module-level function. ``immutable=True``
    declares entries are pure functions of their key (compile memos):
    no invalidation contract, inventory only."""

    def deco(obj):
        return obj

    return deco


def mutation(*, kind, invalidates=()):
    """Declare a mutation path: ``kind`` is one of
    :data:`MUTATION_KINDS`; ``invalidates`` names the
    :func:`cache_surface` ids whose purge must be reachable from this
    function through the call graph (F001)."""

    def deco(fn):
        return fn

    return deco


def feedback_sink(fn):
    """Mark an accumulator that trains/bills off observed traffic. F002
    flags any unguarded shadow-plane path into it."""
    return fn


def shadow_plane(obj):
    """Mark a class or function whose execution is audit shadow
    traffic — the F002 taint roots."""
    return obj


def shadow_guard(fn):
    """Mark a recognized shadow discriminator (``in_shadow``/``shadow``).
    A non-root function referencing one is trusted to gate its own
    sinks, so F002 traversal stops there."""
    return fn


def device_band(*, certain=False, cand=False, refine=False):
    """Declare a function's role in the two-band f64 discipline.

    ``certain=True``: produces certain-band device decisions — F003
    flags f64 construction (and refine-band calls) inside it.
    ``cand=True``: produces a candidate-band superset — every call site
    must flow the result into a ``refine`` function or return it to a
    caller that does. ``refine=True``: the exact f64 re-check that
    retires a cand band."""

    def deco(fn):
        return fn

    return deco


def dispatch_budget(n, *, signatures=()):
    """Declare that one call of this function issues at most ``n`` device
    dispatches, worst case, through the whole cross-module call graph
    (a dispatch = one invocation of a ``cached_*_step`` step or a
    jit-compiled ``parallel/query`` callable). ``n`` must be a literal
    int — the ``--sync`` prong computes the structural worst case
    (branches take the max arm, constant-trip loops multiply, a
    non-constant loop around a dispatch is unbounded) and S001 fires
    with the witness chain when it exceeds ``n``.

    ``signatures``: optional :func:`fnmatch.fnmatch` globs over runtime
    plan signatures (``geomesa_tpu.obs.devmon.plan_signature`` — e.g.
    ``"z2:iv16:rows"``; ``"*:rows"`` covers every row-select plan).
    ``--sync --reconcile ledger.json`` matches exported ledger rows
    against these globs and flags any signature whose MEASURED
    dispatches-per-query exceed the declared bound — a divergence means
    a boundary op the static model missed, or a wrong contract."""

    def deco(fn):
        return fn

    return deco


def host_sync_free(fn):
    """Declare that no host↔device synchronization — ``block_until_ready``,
    ``.item()``, ``np.asarray`` of a device value, an implicit
    ``bool()``/``float()`` coercion, ``obs.ledger.materialize`` — is
    reachable through the call graph before this function returns
    (S002). The intentional await that ends a device pipeline retires
    with ``# tpusync: retire`` on the site's line (mirroring F003's
    refine-merge retirement)."""
    return fn


def choreography_boundary(obj):
    """Mark a class or function as the sanctioned stage-orchestration
    layer (the datastore facade): its per-query fallback loops and
    routing are host choreography BY DESIGN. The ``--sync`` prong skips
    S003/S004 inside it and charges callers zero dispatch cost for
    calling into it, so staged paths don't drown the report. A method
    carrying its own :func:`dispatch_budget` opts back into S001."""
    return obj
