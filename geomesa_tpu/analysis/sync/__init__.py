"""tpusync — the static dispatch/host-sync budget prong.

``python -m geomesa_tpu.analysis --sync`` is the CLI spelling (add
``--reconcile ledger.json`` to check static budgets against a
live-exported host-roundtrip ledger);
:mod:`geomesa_tpu.analysis.contracts` holds the ``dispatch_budget`` /
``host_sync_free`` / ``choreography_boundary`` vocabulary;
:mod:`geomesa_tpu.analysis.sync.rules` documents the S001-S004 rule
families."""

from geomesa_tpu.analysis.sync.rules import (
    LEDGER_EXPORT_KIND,
    SYNC_RULE_IDS,
    active_sync_rules,
    analyze_sync_modules,
    analyze_sync_paths,
    load_ledger_export,
)

__all__ = [
    "LEDGER_EXPORT_KIND", "SYNC_RULE_IDS", "active_sync_rules",
    "analyze_sync_modules", "analyze_sync_paths", "load_ledger_export",
]
