"""Registry descriptors for the tpusync rules.

S001-S004 are WHOLE-PROGRAM rules (``project = True``): their findings
come from :func:`geomesa_tpu.analysis.sync.rules.analyze_sync_paths`
(the ``--sync`` CLI mode), not the per-module ``check`` pass — the
``check`` here is a no-op so the ids still resolve for ``--list-rules``,
``--rules`` filtering, waivers, baselines, and SARIF rule metadata
(same pattern as the tpurace/tpuflow descriptors)."""

from __future__ import annotations

from geomesa_tpu.analysis.rules import register


@register
class DispatchBudgetExceeded:
    id = "S001"
    title = "worst-case (or ledger-measured) dispatches above the budget"
    project = True

    def check(self, mod, config):
        return ()


@register
class HostSyncReachable:
    id = "S002"
    title = "host sync reachable inside a host_sync_free/device_band region"
    project = True

    def check(self, mod, config):
        return ()


@register
class LoopCarriedDispatch:
    id = "S003"
    title = "dispatch inside a loop with a non-constant trip count"
    project = True

    def check(self, mod, config):
        return ()


@register
class UnmodeledBoundary:
    id = "S004"
    title = "raw jax.jit/pmap call bypassing the cached_* step factories"
    project = True

    def check(self, mod, config):
        return ()
