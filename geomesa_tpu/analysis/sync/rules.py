"""tpusync: static dispatch/host-sync budget rules (S001-S004).

The fourth analysis prong. ROADMAP item 1 (whole-plan device
compilation — ONE dispatch per query) has a *measured* work list in the
host-roundtrip ledger (``obs fusion-report``); this prong is the
*proof* side: it classifies device-boundary operations off the AST,
propagates worst-case dispatch counts over the same cross-module
call-graph machinery as tpurace/tpuflow, and checks them against the
budgets the live code declares through
:mod:`geomesa_tpu.analysis.contracts`:

- **S001 dispatch budget exceeded** — a ``@dispatch_budget(n)``
  function whose structural worst case (branches take the max arm,
  constant-trip loops multiply, calls add the callee's fixpoint cost)
  exceeds ``n``, reported with the witness call chain. Malformed sync
  contract declarations land here too, as do ``--reconcile``
  divergences (a ledger-measured dispatch rate above the static bound).
- **S002 host sync reachable in a sync-free region** — a
  ``block_until_ready`` / ``.item()`` / ``np.asarray``-of-device-value /
  implicit coercion / ``obs.ledger.materialize`` site reachable through
  the call graph from a ``@host_sync_free`` or
  ``@device_band(certain=True)`` function. The intentional await that
  ends a pipeline retires with ``# tpusync: retire`` on its line
  (``retire-next-line`` from the line above), read through the shared
  tokenizer so docstring mentions stay inert.
- **S003 loop-carried dispatch** — a dispatch site (or a call chain
  with positive dispatch cost) inside a Python loop whose trip count is
  not a compile-time constant: the per-iteration host-roundtrip
  serialization the batched paths exist to eliminate.
- **S004 unmodeled boundary** — a raw ``jax.jit``/``jax.pmap`` CALL
  expression (decorator uses are fine) outside the ``cached_*`` factory
  discipline: invisible to the roundtrip ledger and to this analysis,
  so nothing can budget it.

What counts as a *dispatch site*: invoking a step built by the
``cached_*_step``/``make_*_step`` factory family (``parallel/query.py``
and fixtures alike — recognized by name through the ImportMap, including
the ``gather = (f_bbox if bbox else f)`` aliasing idiom), and calling a
project function decorated ``@jax.jit``/``@partial(jax.jit, ...)``.
``@choreography_boundary`` functions are the sanctioned orchestration
layer: exempt from S003/S004 and zero-cost to callers (a budgeted
method of a boundary class opts back into S001).

Heuristics, not proofs: the expected answer for a reviewed intentional
site is a ``# tpusync: disable=Sxxx`` waiver with a justification.
"""

from __future__ import annotations

import ast
import json
import re
from collections import defaultdict
from dataclasses import dataclass
from fnmatch import fnmatch

from geomesa_tpu.analysis.core import (
    LintConfig,
    Module,
    Violation,
    _comment_texts,
    finalize_module_violations,
)
from geomesa_tpu.analysis.race.lockset import (
    _FnScan,
    _FnSummary,
    _Project,
    _module_id,
    load_modules,
)
from geomesa_tpu.analysis.sync.contracts_scan import (
    SyncContracts,
    scan_sync_contracts,
)

__all__ = [
    "SYNC_RULE_IDS", "LEDGER_EXPORT_KIND", "active_sync_rules",
    "analyze_sync_modules", "analyze_sync_paths", "load_ledger_export",
]

SYNC_RULE_IDS = ("S001", "S002", "S003", "S004")

#: A worst case at or above this is reported as "unbounded" (a dispatch
#: under a non-constant loop, or recursion through a dispatch site).
INF = 10 ** 9

#: The export contract shared with ``obs/ledger.py`` — ``--reconcile``
#: refuses anything else (a silent schema drift would fake a clean
#: reconciliation).
LEDGER_EXPORT_KIND = "geomesa-tpu-roundtrip-ledger"
LEDGER_EXPORT_SCHEMA_VERSION = 1

_JIT = frozenset({
    "jax.jit", "jax.pmap", "jax.pjit", "jax.experimental.pjit.pjit",
})

_RETIRE = re.compile(r"#\s*tpusync:\s*retire(?P<next>-next-line)?\b")


def active_sync_rules(config: LintConfig) -> set[str]:
    if config.rules is None:
        return set(SYNC_RULE_IDS)
    return set(config.rules) & set(SYNC_RULE_IDS)


def _factory_name(name: str) -> bool:
    """The step-factory naming discipline: ``cached_*_step*`` /
    ``make_*_step*`` (``parallel/query.py``'s J003 idiom)."""
    seg = name.rsplit(".", 1)[-1]
    return "_step" in seg and seg.lstrip("_").startswith(
        ("cached_", "make_"))


def _key_label(key: tuple) -> str:
    return (f"{key[1]}.{key[2]}" if key[0] == "method"
            else f"{key[1]}:{key[2]}")


def _has_jit_decorator(fn: ast.AST, imports) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if imports.resolve(target) in _JIT:
            return True
        if isinstance(dec, ast.Call) \
                and imports.resolve(dec.func) == "functools.partial" \
                and dec.args and imports.resolve(dec.args[0]) in _JIT:
            return True
    return False


def _jit_decorated_keys(project: _Project) -> set[tuple]:
    """Project callables that ARE one dispatch per call: top-level
    functions / methods decorated ``@jax.jit`` (or
    ``@partial(jax.jit, ...)``). Calling one is a modeled boundary op,
    not an S004 escape."""
    out: set[tuple] = set()
    for mod in project.modules:
        imports = project.imports[mod.relpath]
        mid = _module_id(mod.relpath)
        for name, fn in project.functions[mid].items():
            if _has_jit_decorator(fn, imports):
                out.add(("fn", mid, name))
    for cname, info in project.classes.items():
        imports = project.imports[info.module.relpath]
        for mname, m in info.methods.items():
            if _has_jit_decorator(m, imports):
                out.add(("method", cname, mname))
    return out


def _retired_lines(mod: Module) -> set[int]:
    """Lines whose sync sites a ``# tpusync: retire`` comment blesses."""
    out: set[int] = set()
    for i, text in _comment_texts(mod.lines):
        for m in _RETIRE.finditer(text):
            out.add(i + 1 if m.group("next") else i)
    return out


def _const_trips(it: ast.AST):
    """Compile-time-constant trip count of a ``for`` iterable, or None."""
    if isinstance(it, (ast.Tuple, ast.List, ast.Set)):
        return len(it.elts)
    if isinstance(it, ast.Constant) and isinstance(it.value, (str, bytes)):
        return len(it.value)
    if isinstance(it, ast.Call) and isinstance(it.func, ast.Name):
        if it.func.id == "range" and it.args and not it.keywords:
            vals = []
            for a in it.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, int):
                    vals.append(a.value)
                elif (isinstance(a, ast.UnaryOp)
                        and isinstance(a.op, ast.USub)
                        and isinstance(a.operand, ast.Constant)
                        and isinstance(a.operand.value, int)):
                    vals.append(-a.operand.value)
                else:
                    return None
            try:
                return len(range(*vals))
            except (TypeError, ValueError):
                return None
        if it.func.id in ("enumerate", "reversed", "sorted", "tuple",
                          "list") and it.args:
            return _const_trips(it.args[0])
        if it.func.id == "zip" and it.args:
            ts = [_const_trips(a) for a in it.args]
            if ts and all(t is not None for t in ts):
                return min(ts)
    return None


# ---------------------------------------------------------------------------
# per-function boundary scan → cost IR + sync/S004 sites
# ---------------------------------------------------------------------------
#
# The IR is a tiny worst-case-cost tree built structurally from the
# statement list (so the S001 evaluation and the S003 loop walk share
# one shape):
#
#   ("seq",  [items])            cost = sum
#   ("max",  [items])            cost = max (if/elif/else arms, try paths)
#   ("loop", trips|None, body, line)
#                                cost = trips × body; None trips with a
#                                positive body cost = INF (and S003)
#   ("site", line, label)        one dispatch
#   ("call", key, line)          the callee's fixpoint cost


@dataclass
class _SyncSite:
    line: int
    col: int
    what: str
    retired: bool = False


@dataclass
class _FnSync:
    key: tuple
    label: str
    module: Module
    ir: tuple
    calls: list[tuple]              # callee keys (S002 adjacency)
    sync_sites: list[_SyncSite]
    s004: list[tuple]               # (line, col, dotted)


class _SyncScan(_FnScan):
    """Boundary-op classifier: rides _FnScan's ImportMap/typing and
    cross-module callee resolution, but drives statements structurally
    (building the cost IR) instead of via generic traversal."""

    def __init__(self, project, summary, fn, jit_fns: set[tuple]):
        super().__init__(project, summary, fn, cross_module=True)
        self.jit_fns = jit_fns
        self.events: list[tuple] = []       # ("site", ...) | ("call", ...)
        self.sync_sites: list[_SyncSite] = []
        self.s004: list[tuple] = []
        self.tainted: set[str] = set()      # device-resident locals
        self.step_vars: set[str] = set()    # locals holding a built step
        self.factory_vars: set[str] = set()  # locals aliasing a factory
        self._device_calls: set[int] = set()  # id(Call) → device value
        self._step_calls: set[int] = set()    # id(Call) → step callable

    # -- structural statement driver ----------------------------------------
    def scan(self, fn: ast.AST) -> tuple:
        return ("seq", self._eval_block(fn.body))

    def _eval_block(self, stmts) -> list:
        items: list = []
        for st in stmts:
            items.extend(self._eval_stmt(st))
        return items

    def _eval_stmt(self, st: ast.stmt) -> list:
        if isinstance(st, ast.If):
            items = self._leaf(st.test)
            self._implicit_bool(st.test)
            arms = [("seq", self._eval_block(st.body)),
                    ("seq", self._eval_block(st.orelse))]
            return items + [("max", arms)]
        if isinstance(st, (ast.For, ast.AsyncFor)):
            items = self._leaf(st.iter)
            trips = _const_trips(st.iter)
            body = ("seq", self._eval_block(st.body)
                    + self._eval_block(st.orelse))
            return items + [("loop", trips, body, st.lineno)]
        if isinstance(st, ast.While):
            items = self._leaf(st.test)
            self._implicit_bool(st.test)
            body = ("seq", self._eval_block(st.body)
                    + self._eval_block(st.orelse))
            # a while's trip count is never a static constant
            return items + [("loop", None, body, st.lineno)]
        if isinstance(st, (ast.With, ast.AsyncWith)):
            items = []
            for it in st.items:
                items += self._leaf(it.context_expr)
            return items + self._eval_block(st.body)
        if isinstance(st, ast.Try):
            main = ("seq", self._eval_block(st.body)
                    + self._eval_block(st.orelse))
            arms = [main] + [("seq", self._eval_block(h.body))
                             for h in st.handlers]
            return [("max", arms)] + self._eval_block(st.finalbody)
        if isinstance(st, getattr(ast, "Match", ())):
            items = self._leaf(st.subject)
            arms = [("seq", self._eval_block(c.body)) for c in st.cases]
            return items + [("max", arms)]
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            # nested defs run who-knows-when; same rule as _FnScan
            return []
        return self._leaf(st)

    def _leaf(self, node: ast.AST) -> list:
        """Visit one leaf statement/expression; the boundary events it
        produced become IR items in source order."""
        mark = len(self.events)
        self.visit(node)
        items = self.events[mark:]
        del self.events[mark:]
        return items

    def _implicit_bool(self, test: ast.AST) -> None:
        nm = None
        if isinstance(test, ast.Name):
            nm = test.id
        elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            nm = test.operand.id
        if nm is not None and nm in self.tainted:
            self.sync_sites.append(_SyncSite(
                test.lineno, test.col_offset,
                f"implicit bool() of device value {nm!r} in a branch test"))

    # -- classification ------------------------------------------------------
    def _ref_name(self, f: ast.AST) -> str | None:
        dotted = self.imports.resolve(f)
        if dotted is not None:
            return dotted.rsplit(".", 1)[-1]
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute):
            return f.attr
        return None

    def _is_factory_ref(self, f: ast.AST) -> bool:
        if isinstance(f, ast.IfExp):
            # the (f_bbox if bbox_mode else f)(mesh) selection idiom
            return (self._is_factory_ref(f.body)
                    and self._is_factory_ref(f.orelse))
        if isinstance(f, ast.Name) and f.id in self.factory_vars:
            return True
        name = self._ref_name(f)
        return name is not None and _factory_name(name)

    def _yields_step(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and self._is_factory_ref(node.func)

    def _arg_tainted(self, a: ast.AST) -> bool:
        if isinstance(a, ast.Name):
            return a.id in self.tainted
        if isinstance(a, ast.Call):
            return id(a) in self._device_calls
        if isinstance(a, (ast.Subscript, ast.Attribute)):
            return self._arg_tainted(a.value)
        return False

    def _visit_args(self, node: ast.Call) -> None:
        for a in node.args:
            self.visit(a)
        for k in node.keywords:
            self.visit(k.value)

    def visit_Call(self, node: ast.Call):  # noqa: C901 — one classifier
        f = node.func
        dotted = self.imports.resolve(f)

        # dispatch sites: invoking a built step (inline or via a local),
        # or calling a @jax.jit-decorated project function
        site = None
        if isinstance(f, ast.Call) and self._yields_step(f):
            site = f"{self._ref_name(f.func) or 'step'}(...)(...)"
            self._step_calls.add(id(f))
            self._visit_args(f)
        elif isinstance(f, ast.Name) and f.id in self.step_vars:
            site = f"{f.id}(...)"
        else:
            callee = self._callee_key(f)
            if callee is not None and callee in self.jit_fns:
                site = f"{self._ref_name(f)}(...) [@jax.jit]"
        if site is not None:
            self.events.append(("site", node.lineno, site))
            self._device_calls.add(id(node))
            self._visit_args(node)
            if isinstance(f, ast.Attribute):
                self.visit(f.value)
            return

        # a bare factory call builds a step (compile-cached: zero cost)
        if self._yields_step(node):
            self._step_calls.add(id(node))
            self._visit_args(node)
            return

        # sync sites
        sync = None
        if isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
            sync = ".block_until_ready()"
        elif isinstance(f, ast.Attribute) and f.attr in ("item", "tolist") \
                and self._arg_tainted(f.value):
            sync = f".{f.attr}() on a device value"
        elif dotted in ("numpy.asarray", "numpy.array") and node.args \
                and self._arg_tainted(node.args[0]):
            sync = f"{dotted} of a device value"
        elif dotted == "jax.device_get":
            sync = "jax.device_get"
        elif dotted == "geomesa_tpu.obs.ledger.materialize":
            sync = "obs.ledger.materialize (device→host readback)"
        elif isinstance(f, ast.Name) and f.id in ("bool", "float", "int") \
                and node.args and self._arg_tainted(node.args[0]):
            sync = f"{f.id}() coercion of a device value"
        if sync is not None:
            self.sync_sites.append(_SyncSite(
                node.lineno, node.col_offset, sync))
            self._visit_args(node)
            if isinstance(f, ast.Attribute):
                self.visit(f.value)
            return

        # transfers: the result lives on device
        if dotted in ("jax.device_put", "jax.numpy.asarray",
                      "jax.numpy.array"):
            self._device_calls.add(id(node))
            self._visit_args(node)
            return

        # S004: a raw jit wrapper built outside the factory discipline
        if dotted in _JIT:
            self.s004.append((node.lineno, node.col_offset, dotted))
            self._visit_args(node)
            return

        # ordinary call → call-graph edge
        callee = self._callee_key(f)
        if callee is not None:
            self.events.append(("call", callee, node.lineno))
        self._visit_args(node)
        if isinstance(f, ast.Attribute):
            self.visit(f.value)
        elif not isinstance(f, ast.Name):
            self.visit(f)

    # a comprehension is a loop with a non-constant trip count: boundary
    # events inside it wrap into an unbounded-loop IR node so S001/S003
    # see ``[step(c) for c in chunks]`` for what it is
    def _comprehension(self, node):
        mark = len(self.events)
        self.generic_visit(node)
        items = self.events[mark:]
        del self.events[mark:]
        if items:
            self.events.append(
                ("loop", None, ("seq", items), node.lineno))

    visit_ListComp = _comprehension
    visit_SetComp = _comprehension
    visit_DictComp = _comprehension
    visit_GeneratorExp = _comprehension

    # -- taint/step binding --------------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        super().visit_Assign(node)
        self._bind_targets(node.targets, node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        super().visit_AnnAssign(node)
        if node.value is not None:
            self._bind_targets([node.target], node.value)

    def _value_kind(self, v: ast.AST) -> str | None:
        if isinstance(v, ast.Call):
            if id(v) in self._device_calls:
                return "device"
            if id(v) in self._step_calls:
                return "step"
            return None
        if isinstance(v, ast.Name):
            if v.id in self.tainted:
                return "device"
            if v.id in self.step_vars:
                return "step"
            if v.id in self.factory_vars:
                return "factory"
            return "factory" if self._is_factory_ref(v) else None
        if isinstance(v, ast.Attribute):
            if self._is_factory_ref(v):
                return "factory"
            return self._value_kind(v.value) if isinstance(
                v.value, ast.Name) and v.value.id in self.tainted else None
        if isinstance(v, ast.Subscript):
            base = v.value
            if isinstance(base, ast.Name) and base.id in self.tainted:
                return "device"
            return None
        if isinstance(v, ast.IfExp):
            a, b = self._value_kind(v.body), self._value_kind(v.orelse)
            if a == b:
                return a
            return "device" if "device" in (a, b) else None
        return None

    def _bind_targets(self, targets, value) -> None:
        kind = self._value_kind(value)
        for t in targets:
            for el in _iter_names(t):
                self.tainted.discard(el)
                self.step_vars.discard(el)
                self.factory_vars.discard(el)
                if kind == "device":
                    self.tainted.add(el)
                elif kind == "step":
                    self.step_vars.add(el)
                elif kind == "factory":
                    self.factory_vars.add(el)


def _iter_names(t: ast.AST):
    if isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            yield from _iter_names(el)
    elif isinstance(t, ast.Starred):
        yield from _iter_names(t.value)
    elif isinstance(t, ast.Name):
        yield t.id


def _scan_functions(project: _Project,
                    jit_fns: set[tuple]) -> dict[tuple, _FnSync]:
    out: dict[tuple, _FnSync] = {}

    def one(key, name, cls, mod, fn):
        s = _FnSummary(key=key, name=name, cls=cls, module=mod)
        scan = _SyncScan(project, s, fn, jit_fns)
        ir = scan.scan(fn)
        calls = [e[1] for e in _iter_ir_events(ir) if e[0] == "call"]
        out[key] = _FnSync(
            key=key, label=_key_label(key), module=mod, ir=ir,
            calls=calls, sync_sites=scan.sync_sites, s004=scan.s004)

    for mod in project.modules:
        mid = _module_id(mod.relpath)
        for name, fn in project.functions[mid].items():
            one(("fn", mid, name), name, None, mod, fn)
        for cname, info in project.classes.items():
            if info.module is not mod:
                continue
            for mname, m in info.methods.items():
                one(("method", cname, mname), mname, info, mod, m)
    return out


def _iter_ir_events(ir: tuple):
    kind = ir[0]
    if kind in ("seq", "max"):
        for it in ir[1]:
            yield from _iter_ir_events(it)
    elif kind == "loop":
        yield from _iter_ir_events(ir[2])
    else:
        yield ir


# ---------------------------------------------------------------------------
# worst-case dispatch cost: IR evaluation + call-graph fixpoint
# ---------------------------------------------------------------------------

def _cost_eval(ir: tuple, costs: dict, choreo: set[tuple],
               group: frozenset = frozenset()) -> int:
    """Worst case of one IR tree. ``group``: the evaluating function's
    own choreography-boundary keys — absorption applies only at edges
    crossing INTO a boundary from outside it, so a budgeted method of a
    boundary class still sees the real cost of its intra-class callees
    (the opt-back-into-S001 semantics)."""
    kind = ir[0]
    if kind == "seq":
        return min(INF, sum(_cost_eval(i, costs, choreo, group)
                            for i in ir[1]))
    if kind == "max":
        return max((_cost_eval(i, costs, choreo, group) for i in ir[1]),
                   default=0)
    if kind == "loop":
        body = _cost_eval(ir[2], costs, choreo, group)
        if body == 0:
            return 0
        if ir[1] is None:
            return INF
        return min(INF, ir[1] * body)
    if kind == "site":
        return 1
    # ("call", key, line)
    if ir[1] in choreo and ir[1] not in group:
        return 0
    return costs.get(ir[1], 0)


def _choreo_groups(contracts: SyncContracts) -> dict[tuple, frozenset]:
    """key → every key sharing a choreography declaration with it (a
    class declaration groups all its methods)."""
    out: dict[tuple, frozenset] = {}
    for c in contracts.choreo:
        ks = frozenset(c.keys)
        for k in c.keys:
            out[k] = out.get(k, frozenset()) | ks
    return out


def _fixpoint_costs(fns: dict[tuple, _FnSync], choreo: set[tuple],
                    groups: dict[tuple, frozenset]) -> dict[tuple, int]:
    costs = {k: 0 for k in fns}
    rounds = min(len(fns) + 2, 200)
    for _ in range(rounds):
        changed = False
        for k, fs in fns.items():
            c = _cost_eval(fs.ir, costs, choreo, groups.get(k, frozenset()))
            if c != costs[k]:
                costs[k] = c
                changed = True
        if not changed:
            return costs
    # still moving after the cap: recursion through a dispatch site —
    # the worst case is unbounded
    for k, fs in fns.items():
        if _cost_eval(fs.ir, costs, choreo,
                      groups.get(k, frozenset())) != costs[k]:
            costs[k] = INF
    return costs


def _cost_str(c: int) -> str:
    return "unbounded" if c >= INF else str(c)


def _mult_str(m: int) -> str:
    if m <= 1:
        return ""
    return " ×unbounded-loop" if m >= INF else f" ×{m} (loop)"


def _witness(key: tuple, fns: dict[tuple, _FnSync], costs: dict,
             choreo: set[tuple], groups: dict[tuple, frozenset],
             depth: int = 0) -> list[str]:
    """The worst-case path, human-readable: direct contributors of
    *key*'s IR, then the costliest callee expanded (bounded depth)."""
    fs = fns.get(key)
    if fs is None or depth > 3:
        return []
    group = groups.get(key, frozenset())
    parts: list[tuple[int, str, int, tuple | None]] = []

    def walk(node: tuple, mult: int) -> None:
        kind = node[0]
        if kind == "seq":
            for it in node[1]:
                walk(it, mult)
        elif kind == "max":
            best, bc = None, 0
            for it in node[1]:
                c = _cost_eval(it, costs, choreo, group)
                if c > bc:
                    best, bc = it, c
            if best is not None:
                walk(best, mult)
        elif kind == "loop":
            if _cost_eval(node[2], costs, choreo, group) > 0:
                trips = node[1] if node[1] is not None else INF
                walk(node[2], min(INF, mult * trips))
        elif kind == "site":
            parts.append((node[1], node[2], mult, None))
        else:  # call
            c = 0 if (node[1] in choreo and node[1] not in group) \
                else costs.get(node[1], 0)
            if c > 0:
                parts.append((node[2], _key_label(node[1]), mult, node[1]))

    walk(fs.ir, 1)
    lines = []
    deepest: tuple | None = None
    deepest_cost = 0
    for line, what, mult, callee in parts[:6]:
        if callee is None:
            lines.append(f"line {line}: {what} dispatch{_mult_str(mult)}")
        else:
            c = costs.get(callee, 0)
            lines.append(
                f"line {line}: call {what} "
                f"[{_cost_str(c)}]{_mult_str(mult)}")
            if c > deepest_cost:
                deepest, deepest_cost = callee, c
    if deepest is not None:
        sub = _witness(deepest, fns, costs, choreo, groups, depth + 1)
        if sub:
            lines.append(f"→ inside {_key_label(deepest)}: "
                         + "; ".join(sub[:3]))
    return lines


# ---------------------------------------------------------------------------
# S001: declared budget vs structural worst case (+ reconcile)
# ---------------------------------------------------------------------------

def _check_s001(fns, costs, choreo, groups, contracts: SyncContracts):
    out: list[Violation] = []
    for b in contracts.budgets:
        if b.key not in fns:
            continue
        worst = costs.get(b.key, 0)
        if worst <= b.n:
            continue
        chain = "; ".join(_witness(b.key, fns, costs, choreo, groups)) \
            or "no direct witness (cost carried by callees)"
        out.append(Violation(
            rule="S001", path=b.module.path, line=b.line, col=0,
            message=(
                f"@dispatch_budget({b.n}) exceeded on {b.label}: "
                f"worst case is {_cost_str(worst)} dispatch(es) — "
                f"{chain}")))
    return out


def load_ledger_export(path: str) -> list[dict]:
    """Parse + validate an ``obs ledger-export`` snapshot. A wrong kind
    or schema version is a usage error (CLI exit 2), not a finding."""
    with open(path, encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"--reconcile {path}: not JSON ({e})") from e
    if not isinstance(doc, dict) or doc.get("kind") != LEDGER_EXPORT_KIND:
        raise ValueError(
            f"--reconcile {path}: not a roundtrip-ledger export "
            f"(expected kind={LEDGER_EXPORT_KIND!r}, "
            f"got {doc.get('kind') if isinstance(doc, dict) else doc!r})")
    if doc.get("schema_version") != LEDGER_EXPORT_SCHEMA_VERSION:
        raise ValueError(
            f"--reconcile {path}: unsupported schema_version "
            f"{doc.get('schema_version')!r} (this analyzer speaks "
            f"{LEDGER_EXPORT_SCHEMA_VERSION})")
    entries = doc.get("entries")
    if not isinstance(entries, list) or not all(
            isinstance(e, dict) for e in entries):
        raise ValueError(f"--reconcile {path}: entries must be a list "
                         f"of objects")
    return entries


def _check_reconcile(contracts: SyncContracts, entries: list[dict]):
    """Measured dispatches/query above the static bound for any plan
    signature a budget claims — either a boundary op the model missed
    or a wrong contract; both are S001 findings at the declaration."""
    out: list[Violation] = []
    sig_budgets = [b for b in contracts.budgets if b.signatures]
    for e in entries:
        sig = e.get("signature")
        queries = e.get("queries") or 0
        dispatches = e.get("dispatches") or 0
        if not isinstance(sig, str) or not queries:
            continue
        matching = [b for b in sig_budgets
                    if any(fnmatch(sig, g) for g in b.signatures)]
        if not matching:
            continue
        decl = max(matching, key=lambda b: b.n)
        measured = dispatches / queries
        if measured <= decl.n + 1e-9:
            continue
        out.append(Violation(
            rule="S001", path=decl.module.path, line=decl.line, col=0,
            message=(
                f"ledger reconcile: signature {sig!r} measured "
                f"{dispatches} dispatches over {queries} query(ies) "
                f"({measured:.2f}/query) — above the declared "
                f"@dispatch_budget({decl.n}) on {decl.label}; either a "
                f"boundary op this analysis cannot see or a wrong "
                f"contract (both are findings)")))
    return out


# ---------------------------------------------------------------------------
# S002: host sync reachable from a sync-free region
# ---------------------------------------------------------------------------

def _check_s002(fns, contracts: SyncContracts, bands):
    roots: list[tuple[tuple, str]] = [
        (d.key, f"@host_sync_free {d.label}") for d in contracts.sync_free
    ] + [
        (b.key, f"@device_band(certain=True) {b.label}")
        for b in bands if b.certain
    ]
    adj = {k: fs.calls for k, fs in fns.items()}
    out: list[Violation] = []
    seen: set[tuple] = set()
    retired_cache: dict[str, set[int]] = {}
    for root, root_label in roots:
        if root not in fns:
            continue
        parent: dict[tuple, tuple | None] = {root: None}
        stack = [root]
        while stack:
            k = stack.pop()
            for nxt in adj.get(k, ()):
                if nxt in fns and nxt not in parent:
                    parent[nxt] = k
                    stack.append(nxt)
        for key in parent:
            fs = fns[key]
            rel = fs.module.relpath
            if rel not in retired_cache:
                retired_cache[rel] = _retired_lines(fs.module)
            retired = retired_cache[rel]
            for site in fs.sync_sites:
                if site.line in retired:
                    continue
                dedup = (fs.module.path, site.line, site.what)
                if dedup in seen:
                    continue
                seen.add(dedup)
                chain_keys: list[tuple] = []
                k: tuple | None = key
                while k is not None:
                    chain_keys.append(k)
                    k = parent[k]
                chain = " → ".join(
                    _key_label(c) for c in reversed(chain_keys))
                out.append(Violation(
                    rule="S002", path=fs.module.path, line=site.line,
                    col=site.col,
                    message=(
                        f"host sync ({site.what}) reachable from "
                        f"{root_label} via {chain} — move the await past "
                        f"the sync-free region, or mark the intentional "
                        f"pipeline end with `# tpusync: retire`")))
    return out


# ---------------------------------------------------------------------------
# S003: loop-carried dispatch
# ---------------------------------------------------------------------------

def _check_s003(fns, costs, choreo, groups):
    out: list[Violation] = []
    for key, fs in fns.items():
        if key in choreo:
            continue
        group = groups.get(key, frozenset())
        reported: set[tuple] = set()

        def walk(node: tuple, loop_line: int | None) -> None:
            kind = node[0]
            if kind in ("seq", "max"):
                for it in node[1]:
                    walk(it, loop_line)
            elif kind == "loop":
                walk(node[2], node[3] if node[1] is None else loop_line)
            elif kind == "site" and loop_line is not None:
                mark = (node[1], node[2])
                if mark not in reported:
                    reported.add(mark)
                    out.append(Violation(
                        rule="S003", path=fs.module.path, line=node[1],
                        col=0,
                        message=(
                            f"loop-carried dispatch in {fs.label}: "
                            f"{node[2]} runs inside the loop at line "
                            f"{loop_line} whose trip count is not a "
                            f"compile-time constant — one host roundtrip "
                            f"per iteration; batch the work into one "
                            f"dispatch or bound the loop statically")))
            elif kind == "call" and loop_line is not None:
                c = 0 if (node[1] in choreo and node[1] not in group) \
                    else costs.get(node[1], 0)
                if c > 0:
                    mark = (node[2], node[1])
                    if mark not in reported:
                        reported.add(mark)
                        out.append(Violation(
                            rule="S003", path=fs.module.path, line=node[2],
                            col=0,
                            message=(
                                f"loop-carried dispatch in {fs.label}: "
                                f"call to {_key_label(node[1])} "
                                f"({_cost_str(c)} dispatch(es)) inside "
                                f"the non-constant loop at line "
                                f"{loop_line} — one host roundtrip per "
                                f"iteration; batch the work into one "
                                f"dispatch or bound the loop statically")))

        walk(fs.ir, None)
    return out


# ---------------------------------------------------------------------------
# S004: unmodeled boundary
# ---------------------------------------------------------------------------

def _check_s004(fns, choreo):
    out: list[Violation] = []
    for key, fs in fns.items():
        if not fs.s004 or key in choreo:
            continue
        if _factory_name(key[2]):
            continue  # the sanctioned jit-wrapper construction layer
        mid = key[1] if key[0] == "fn" else _module_id(
            fs.module.relpath)
        if mid.endswith("parallel.query"):
            continue
        for line, col, dotted in fs.s004:
            out.append(Violation(
                rule="S004", path=fs.module.path, line=line, col=col,
                message=(
                    f"unmodeled device boundary in {fs.label}: raw "
                    f"{dotted}(...) call bypasses the cached_*_step "
                    f"factory family — invisible to the roundtrip "
                    f"ledger and to dispatch budgets; route it through "
                    f"a cached_* factory in parallel/query.py (or mark "
                    f"the layer @choreography_boundary)")))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze_sync_modules(modules: list[Module],
                         config: LintConfig | None = None,
                         reconcile: list[dict] | None = None):
    """Run S001-S004 over a parsed module set (waivers/baseline are the
    caller's passes, same contract as ``analyze_modules``)."""
    from geomesa_tpu.analysis.flow.contracts_scan import scan_contracts

    config = config or LintConfig()
    active = active_sync_rules(config)
    project = _Project(modules)
    jit_fns = _jit_decorated_keys(project)
    fns = _scan_functions(project, jit_fns)
    contracts = scan_sync_contracts(project, modules)
    # device_band(certain) regions are sync-free by the same contract —
    # reuse the flow prong's declarations (its errors are its findings)
    bands = scan_contracts(project, modules).bands
    choreo = contracts.choreo_keys()
    groups = _choreo_groups(contracts)
    costs = _fixpoint_costs(fns, choreo, groups)

    violations: list[Violation] = list(contracts.errors)
    if "S001" in active:
        violations.extend(_check_s001(fns, costs, choreo, groups, contracts))
        if reconcile is not None:
            violations.extend(_check_reconcile(contracts, reconcile))
    if "S002" in active:
        violations.extend(_check_s002(fns, contracts, bands))
    if "S003" in active:
        violations.extend(_check_s003(fns, costs, choreo, groups))
    if "S004" in active:
        violations.extend(_check_s004(fns, choreo))
    violations = [v for v in violations if v.rule in active]
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations


def analyze_sync_paths(paths: list[str],
                       config: LintConfig | None = None,
                       reconcile: list[dict] | None = None):
    """The ``--sync`` entry point: parse every file, run the budget
    analysis, and apply the shared waiver/staleness passes."""
    from geomesa_tpu.analysis.rules import all_rules

    config = config or LintConfig()
    if config.rules is not None:
        unknown = set(config.rules) - set(all_rules())
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    modules, violations = load_modules(paths)
    violations = list(violations)
    violations.extend(analyze_sync_modules(modules, config,
                                           reconcile=reconcile))
    by_path: dict[str, list[Violation]] = defaultdict(list)
    for v in violations:
        by_path[v.path].append(v)
    judged = active_sync_rules(config)
    emit_w001 = config.rules is None or "W001" in config.rules
    for mod in modules:
        vs = by_path.get(mod.path, [])
        violations.extend(finalize_module_violations(
            mod, vs, judged, emit_w001=emit_w001))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations
