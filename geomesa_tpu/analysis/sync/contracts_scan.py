"""AST scan for the tpusync contract vocabulary.

Reads ``@dispatch_budget`` / ``@host_sync_free`` /
``@choreography_boundary`` declarations off the parsed tree (same
resolve-the-decorator-through-the-ImportMap discipline as the flow
prong's :mod:`~geomesa_tpu.analysis.flow.contracts_scan`, and the same
malformed-declaration rule: a contract the scanner cannot read
statically is itself a finding — S001 here, since every sync contract
ultimately bounds dispatch work).

The flow scanner silently ignores these markers (unknown names fall
through its dispatch) and this one ignores the flow vocabulary, so the
two namespaces coexist on one decorated definition.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from geomesa_tpu.analysis.core import Module, Violation
from geomesa_tpu.analysis.race.lockset import _module_id

_NS = "geomesa_tpu.analysis.contracts."


@dataclass
class BudgetDecl:
    """One ``@dispatch_budget(n, signatures=...)`` declaration."""

    key: tuple                  # summary key of the decorated function
    n: int
    signatures: tuple[str, ...]
    label: str
    module: Module
    line: int


@dataclass
class SyncFreeDecl:
    key: tuple
    label: str
    module: Module
    line: int


@dataclass
class ChoreoDecl:
    keys: tuple                 # every entry key (all methods, for a class)
    label: str
    module: Module
    line: int


@dataclass
class SyncContracts:
    budgets: list[BudgetDecl] = field(default_factory=list)
    sync_free: list[SyncFreeDecl] = field(default_factory=list)
    choreo: list[ChoreoDecl] = field(default_factory=list)
    # malformed declarations — S001 (an unreadable budget bounds nothing)
    errors: list[Violation] = field(default_factory=list)

    def choreo_keys(self) -> set[tuple]:
        out: set[tuple] = set()
        for c in self.choreo:
            out.update(c.keys)
        return out


def _decl_error(module: Module, node: ast.AST, msg: str) -> Violation:
    return Violation(
        rule="S001", path=module.path, line=node.lineno, col=node.col_offset,
        message=f"malformed sync contract declaration: {msg}")


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return _BAD


_BAD = object()


class _Scanner:
    def __init__(self, project, contracts: SyncContracts):
        self.project = project
        self.out = contracts
        self.node_class = {
            id(info.node): keyed for keyed, info in project.classes.items()
        }

    def scan(self, module: Module) -> None:
        imports = self.project.imports[module.relpath]
        mid = _module_id(module.relpath)
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                keyed = self.node_class.get(id(node), node.name)
                self._decorators(module, imports, node, cls=keyed)
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._decorators(module, imports, m, cls=keyed,
                                         method=m.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._decorators(module, imports, node,
                                 fn_key=("fn", mid, node.name))

    def _decorators(self, module, imports, node, cls=None, method=None,
                    fn_key=None) -> None:
        if method is not None:
            fn_key = ("method", cls, method)
            label = f"{cls}.{method}"
        elif fn_key is not None:
            label = f"{fn_key[1]}:{fn_key[2]}"
        else:
            label = cls
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            dotted = imports.resolve(target)
            if dotted is None or not dotted.startswith(_NS):
                continue
            marker = dotted[len(_NS):]
            if marker == "dispatch_budget":
                self._budget(module, dec, label, fn_key)
            elif marker == "host_sync_free":
                self._sync_free(module, dec, label, fn_key)
            elif marker == "choreography_boundary":
                self._choreo(module, dec, label, cls, method, fn_key)
            # flow vocabulary (cache_surface, device_band, ...) falls
            # through — the flow scanner owns it

    def _budget(self, module, dec, label, fn_key) -> None:
        if fn_key is None:
            self.out.errors.append(_decl_error(
                module, dec, "@dispatch_budget applies to "
                "functions/methods, not classes"))
            return
        if not isinstance(dec, ast.Call) or not dec.args:
            self.out.errors.append(_decl_error(
                module, dec, "@dispatch_budget requires a literal int "
                "bound: @dispatch_budget(n)"))
            return
        n = _literal(dec.args[0])
        if n is _BAD or not isinstance(n, int) or isinstance(n, bool) \
                or n < 0 or len(dec.args) > 1:
            self.out.errors.append(_decl_error(
                module, dec, "@dispatch_budget bound must be one literal "
                "non-negative int (a computed budget cannot be checked "
                "statically)"))
            return
        sigs: tuple[str, ...] = ()
        for k in dec.keywords:
            if k.arg != "signatures":
                self.out.errors.append(_decl_error(
                    module, dec,
                    f"unknown @dispatch_budget argument {k.arg!r}"))
                return
            v = _literal(k.value)
            if isinstance(v, str):
                v = (v,)
            if v is _BAD or not isinstance(v, (tuple, list)) \
                    or not all(isinstance(s, str) for s in v):
                self.out.errors.append(_decl_error(
                    module, dec, "signatures= must be a literal str or "
                    "tuple of plan-signature globs"))
                return
            sigs = tuple(v)
        self.out.budgets.append(BudgetDecl(
            key=fn_key, n=n, signatures=sigs, label=label,
            module=module, line=dec.lineno))

    def _sync_free(self, module, dec, label, fn_key) -> None:
        if fn_key is None:
            self.out.errors.append(_decl_error(
                module, dec, "@host_sync_free applies to "
                "functions/methods, not classes"))
            return
        if isinstance(dec, ast.Call):
            self.out.errors.append(_decl_error(
                module, dec, "@host_sync_free takes no arguments"))
            return
        self.out.sync_free.append(SyncFreeDecl(
            key=fn_key, label=label, module=module, line=dec.lineno))

    def _choreo(self, module, dec, label, cls, method, fn_key) -> None:
        if isinstance(dec, ast.Call):
            self.out.errors.append(_decl_error(
                module, dec, "@choreography_boundary takes no arguments"))
            return
        if fn_key is not None:
            keys = (fn_key,)
        else:
            info = self.project.classes.get(cls)
            keys = tuple(
                ("method", cls, m) for m in (info.methods if info else ())
            )
        self.out.choreo.append(ChoreoDecl(
            keys=keys, label=label, module=module, line=dec.lineno))


def scan_sync_contracts(project, modules: list[Module]) -> SyncContracts:
    out = SyncContracts()
    scanner = _Scanner(project, out)
    for mod in modules:
        scanner.scan(mod)
    return out
