"""C001: shared-state heuristics for the stream layer.

Two checks per class in the scoped modules (stream/*, utils/*):

- an instance attribute mutated BOTH inside and outside ``with
  self._lock`` blocks (``__init__`` excluded — construction is
  single-threaded; methods named ``*_locked`` are treated as
  caller-holds-lock, the repo's convention for lock-internal helpers);
- two locks of one class acquired in opposite nesting orders anywhere in
  the module (the classic AB/BA deadlock shape).

Heuristics, not proofs: a waiver with a one-line justification is the
expected answer for intentional lock-free publication (e.g. a monotonic
counter), and the rule text says so.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from geomesa_tpu.analysis.astutils import ImportMap
from geomesa_tpu.analysis.core import Module, Violation
from geomesa_tpu.analysis.rules import register

LOCK_FACTORIES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
})
MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "appendleft", "popleft",
})


def _self_attr(node: ast.AST, self_name: str) -> str | None:
    """``self.X`` (or deeper: ``self.X[i]``, ``self.X.y``) → ``X``."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == self_name
        ):
            return node.attr
        node = node.value
    return None


@dataclass
class _Mutation:
    attr: str
    line: int
    col: int
    locked: bool
    what: str


@dataclass
class _ClassReport:
    lock_attrs: set[str] = field(default_factory=set)
    mutations: list[_Mutation] = field(default_factory=list)
    # (outer lock, inner lock) -> first line observed
    lock_orders: dict[tuple[str, str], int] = field(default_factory=dict)


class _MethodScan(ast.NodeVisitor):
    def __init__(self, report: _ClassReport, self_name: str,
                 held: bool):
        self.report = report
        self.self_name = self_name
        self.lock_stack: list[str] = []
        self.base_held = held

    @property
    def locked(self) -> bool:
        return self.base_held or bool(self.lock_stack)

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            attr = _self_attr(item.context_expr, self.self_name)
            if attr is not None and attr in self.report.lock_attrs:
                acquired.append(attr)
        for attr in acquired:
            for outer in self.lock_stack:
                if outer != attr:
                    pair = (outer, attr)
                    self.report.lock_orders.setdefault(pair, node.lineno)
            self.lock_stack.append(attr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.lock_stack.pop()

    def _record(self, target: ast.AST, node: ast.AST, what: str):
        attr = _self_attr(target, self.self_name)
        if attr is None or attr in self.report.lock_attrs:
            return
        self.report.mutations.append(_Mutation(
            attr=attr, line=node.lineno, col=node.col_offset,
            locked=self.locked, what=what,
        ))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._record(t, node, "assignment")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record(node.target, node, "augmented assignment")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._record(node.target, node, "assignment")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
            attr = _self_attr(f.value, self.self_name)
            if attr is not None and attr not in self.report.lock_attrs:
                self.report.mutations.append(_Mutation(
                    attr=attr, line=node.lineno, col=node.col_offset,
                    locked=self.locked, what=f".{f.attr}()",
                ))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        # nested defs (callbacks) run who-knows-where; don't classify
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        pass


@register
class SharedStateHeuristics:
    id = "C001"
    title = "attributes mutated with and without the instance lock"

    def check(self, mod: Module, config):
        if not config.in_scope(mod.relpath, config.c001_paths):
            return
        imports = ImportMap(mod.tree)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            report = _ClassReport()
            methods = [
                n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            # pass 1: which attributes are locks?
            for m in methods:
                for node in ast.walk(m):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not (isinstance(node.value, ast.Call)
                            and imports.resolve(node.value.func)
                            in LOCK_FACTORIES):
                        continue
                    for t in node.targets:
                        attr = _self_attr(t, self_name(m))
                        if attr is not None:
                            report.lock_attrs.add(attr)
            if not report.lock_attrs:
                continue
            # pass 2: classify every self.<attr> mutation
            for m in methods:
                if m.name == "__init__":
                    continue
                scan = _MethodScan(
                    report, self_name(m), held=m.name.endswith("_locked"))
                for stmt in m.body:
                    scan.visit(stmt)
            by_attr: dict[str, list[_Mutation]] = {}
            for mut in report.mutations:
                by_attr.setdefault(mut.attr, []).append(mut)
            for attr, muts in sorted(by_attr.items()):
                locked = [m for m in muts if m.locked]
                unlocked = [m for m in muts if not m.locked]
                if not (locked and unlocked):
                    continue
                locks = "/".join(sorted(report.lock_attrs))
                for mut in unlocked:
                    yield Violation(
                        rule=self.id, path=mod.path, line=mut.line,
                        col=mut.col, message=(
                            f"{cls.name}.{attr} is mutated here "
                            f"({mut.what}) without holding self.{locks}, "
                            f"but under the lock elsewhere (e.g. line "
                            f"{locked[0].line}) — move this mutation under "
                            f"the lock, or waive with a justification if "
                            f"the publication is intentionally lock-free"),
                    )
            # AB/BA ordering
            for (a, b), line in sorted(report.lock_orders.items(),
                                       key=lambda kv: kv[1]):
                if (b, a) in report.lock_orders \
                        and report.lock_orders[(b, a)] < line:
                    yield Violation(
                        rule=self.id, path=mod.path, line=line, col=0,
                        message=(
                            f"locks self.{a} -> self.{b} acquired here in "
                            f"the opposite order of line "
                            f"{report.lock_orders[(b, a)]} "
                            f"(self.{b} -> self.{a}): AB/BA deadlock shape "
                            f"— pick one global order"),
                    )


def self_name(method: ast.FunctionDef) -> str:
    args = method.args.posonlyargs + method.args.args
    return args[0].arg if args else "self"
