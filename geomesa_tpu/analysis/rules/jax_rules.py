"""JAX/Pallas-aware rules: J001 tracer control flow, J002 host syncs in
hot paths, J003 recompilation hazards, J004 the TPU dtype contract.

All four share one per-module traced-context index: which functions trace
(jit-decorated, pallas kernels, and functions nested inside those) and
which local names hold traced values (taint). The index is computed once
per file and cached on the Module object.
"""

from __future__ import annotations

import ast

from geomesa_tpu.analysis.astutils import (
    CACHE_DECORATORS,
    ImportMap,
    build_parents,
    iter_body_stmts,
    jitted_functions,
    mentions_traced,
    nested_functions,
    pallas_kernels,
    parse_static_spec,
    propagate_taint,
)
from geomesa_tpu.analysis.core import Module, Violation
from geomesa_tpu.analysis.rules import register


SYNC_FUNCS = {
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "jax.device_get": "jax.device_get",
}
SYNC_METHODS = frozenset({"item", "tolist"})
SYNC_BUILTINS = frozenset({"float", "int", "bool"})

JNP_64 = frozenset({
    "jax.numpy.int64", "jax.numpy.float64", "jax.numpy.uint64",
})
NP_64 = frozenset({"numpy.int64", "numpy.float64", "numpy.uint64"})
STR_64 = frozenset({"int64", "float64", "uint64"})


class _TracedIndex:
    """Traced functions of a module with their taint sets."""

    def __init__(self, mod: Module):
        self.imports = ImportMap(mod.tree)
        self.parents = build_parents(mod.tree)
        # decorator expression nodes (J003 treats those specially)
        self.deco_nodes: set[ast.AST] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    self.deco_nodes.update(ast.walk(dec))
        # (fn, tainted names, context label); nested defs inherit taint
        self.traced: list[tuple[ast.FunctionDef, set[str], str]] = []
        seen: set[ast.AST] = set()

        def params(fn: ast.FunctionDef) -> set[str]:
            a = fn.args
            out = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
            for star in (a.vararg, a.kwarg):
                if star is not None:
                    out.add(star.arg)
            return out

        def collect(fn, initial, label):
            if fn in seen:
                return
            seen.add(fn)
            tainted = propagate_taint(fn, initial, self.imports)
            self.traced.append((fn, tainted, label))
            for nf in nested_functions(fn):
                collect(nf, params(nf) | tainted, label)

        for fn, spec in jitted_functions(mod.tree, self.imports):
            collect(fn, params(fn) - spec.static_params(fn),
                    f"jit-traced function {fn.name!r}")
        for fn in pallas_kernels(mod.tree, self.imports):
            collect(fn, params(fn), f"pallas kernel {fn.name!r}")
        self.traced_fns = {fn for fn, _, _ in self.traced}


def traced_index(mod: Module) -> _TracedIndex:
    idx = mod.__dict__.get("_traced_index")
    if idx is None:
        idx = _TracedIndex(mod)
        mod.__dict__["_traced_index"] = idx
    return idx


def _stmt_exprs(stmt: ast.stmt) -> list[ast.AST]:
    """Expression roots owned by this statement alone (child statements are
    visited separately by iter_body_stmts, so compound statements only
    contribute their headers)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, ast.For):
        return [stmt.iter]
    if isinstance(stmt, ast.With):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Try)):
        return []
    return [stmt]


def _walk_no_lambda(expr: ast.AST):
    """Walk an expression without descending into lambdas (deferred bodies
    are traced at their call site, not here)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _sync_calls(expr: ast.AST, tainted: set[str], imports: ImportMap):
    """(call node, spelling) for host-sync calls on traced values."""
    for node in _walk_no_lambda(expr):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        dotted = imports.resolve(f)
        if dotted in SYNC_FUNCS:
            if node.args and mentions_traced(node.args[0], tainted, imports):
                yield node, SYNC_FUNCS[dotted]
        elif (
            isinstance(f, ast.Attribute)
            and f.attr in SYNC_METHODS
            and not node.args
            and mentions_traced(f.value, tainted, imports)
        ):
            yield node, f".{f.attr}()"
        elif (
            isinstance(f, ast.Name)
            and f.id in SYNC_BUILTINS
            and len(node.args) == 1
            and mentions_traced(node.args[0], tainted, imports)
        ):
            yield node, f"{f.id}()"


@register
class TracerControlFlow:
    id = "J001"
    title = ("Python if/while/assert on traced values inside jit/pallas "
             "functions")

    def check(self, mod: Module, config):
        idx = traced_index(mod)
        for fn, tainted, label in idx.traced:
            for stmt in iter_body_stmts(fn.body):
                if isinstance(stmt, (ast.If, ast.While)):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    if mentions_traced(stmt.test, tainted, idx.imports):
                        yield Violation(
                            rule=self.id, path=mod.path, line=stmt.lineno,
                            col=stmt.col_offset,
                            message=(
                                f"Python `{kind}` on a traced value inside "
                                f"{label}: the branch is taken once at trace "
                                f"time, not per element — use jnp.where / "
                                f"lax.cond / lax.while_loop (or mark the "
                                f"argument static)"),
                        )
                elif isinstance(stmt, ast.Assert):
                    if mentions_traced(stmt.test, tainted, idx.imports):
                        yield Violation(
                            rule=self.id, path=mod.path, line=stmt.lineno,
                            col=stmt.col_offset,
                            message=(
                                f"`assert` on a traced value inside {label}: "
                                f"it evaluates the tracer at trace time — "
                                f"use checkify or debug-mode host asserts"),
                        )


@register
class HostSyncInHotPath:
    id = "J002"
    title = "host<->device syncs in ops/ and parallel/ hot paths"

    def check(self, mod: Module, config):
        idx = traced_index(mod)
        # Inside traced code a "sync" is a trace-time conversion of a
        # tracer — always wrong, flagged everywhere in the package.
        for fn, tainted, label in idx.traced:
            for stmt in iter_body_stmts(fn.body):
                for expr in _stmt_exprs(stmt):
                    for call, spelling in _sync_calls(
                        expr, tainted, idx.imports
                    ):
                        yield Violation(
                            rule=self.id, path=mod.path, line=call.lineno,
                            col=call.col_offset,
                            message=(
                                f"{spelling} on a traced value inside "
                                f"{label}: forces a trace-time host "
                                f"conversion — keep the value on device "
                                f"(jnp ops) or hoist the conversion out of "
                                f"the traced function"),
                        )
        # In hot-path modules, a per-iteration device->host readback inside
        # a Python loop serializes the pipeline (one dispatch RTT per
        # element). Single post-loop readbacks are the sanctioned seam.
        if not config.in_scope(mod.relpath, config.j002_paths):
            return
        seen: set[ast.AST] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node in idx.traced_fns:
                continue
            host_tainted = propagate_taint(node, set(), idx.imports)
            if not host_tainted:
                continue
            for stmt in iter_body_stmts(node.body):
                if not isinstance(stmt, (ast.For, ast.While)):
                    continue
                for inner in iter_body_stmts(stmt.body):
                    for expr in _stmt_exprs(inner):
                        for call, spelling in _sync_calls(
                            expr, host_tainted, idx.imports
                        ):
                            if call in seen:
                                continue
                            seen.add(call)
                            yield Violation(
                                rule=self.id, path=mod.path,
                                line=call.lineno, col=call.col_offset,
                                message=(
                                    f"{spelling} on a device value inside a "
                                    f"Python loop in a hot path: each "
                                    f"iteration blocks on a device->host "
                                    f"transfer — batch the readback once "
                                    f"outside the loop"),
                            )


def _has_cache_decorator(fn: ast.FunctionDef, imports: ImportMap) -> bool:
    return any(
        imports.resolve(d if not isinstance(d, ast.Call) else d.func)
        in CACHE_DECORATORS
        for d in fn.decorator_list
    )


def _cache_covered(tree: ast.Module, imports: ImportMap) -> set[str]:
    """Names of module-level functions reachable from a memoized
    (lru_cache/cache-decorated) function — the repo's two-layer factory
    idiom (``cached_select_count_step`` → ``make_select_count_step`` →
    ``_make_count_step``) memoizes the OUTER layer, so every factory on
    that chain builds its jit wrapper a bounded number of times."""
    fns = {
        n.name: n for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    edges: dict[str, set[str]] = {}
    for name, fn in fns.items():
        refs = {
            node.id for node in ast.walk(fn)
            if isinstance(node, ast.Name) and node.id in fns
        } - {name}
        edges[name] = refs
    covered: set[str] = {
        name for name, fn in fns.items()
        if _has_cache_decorator(fn, imports)
    }
    frontier = list(covered)
    while frontier:
        cur = frontier.pop()
        for ref in edges.get(cur, ()):
            if ref not in covered:
                covered.add(ref)
                frontier.append(ref)
    return covered


def _enclosing_function(node, parents, *, through_decorators=False):
    prev, cur = node, parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if through_decorators and prev in cur.decorator_list:
                pass  # arrived via @decorator: keep walking outward
            else:
                return cur
        prev, cur = cur, parents.get(cur)
    return None


@register
class RecompilationHazard:
    id = "J003"
    title = "jax.jit wrappers created per call / unhashable static specs"

    def check(self, mod: Module, config):
        idx = traced_index(mod)
        imports, parents = idx.imports, idx.parents
        covered = _cache_covered(mod.tree, imports)
        for node in ast.walk(mod.tree):
            # (a) jax.jit(f)(...) — a fresh wrapper (and compile-cache
            # entry) per invocation
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Call) \
                    and imports.is_jit(node.func.func):
                yield Violation(
                    rule=self.id, path=mod.path, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        "jax.jit(f)(...) builds and discards the jit "
                        "wrapper per call, defeating the compile cache — "
                        "bind the jitted function once (module level or a "
                        "cached factory)"),
                )
            # (d) unhashable static_argnums/static_argnames spec
            if isinstance(node, ast.Call):
                is_jit_call = imports.is_jit(node.func)
                is_partial_jit = (
                    imports.resolve(node.func) in {"functools.partial", "partial"}
                    and node.args and imports.is_jit(node.args[0])
                )
                if is_jit_call or is_partial_jit:
                    for bad in parse_static_spec(node).unhashable_nodes:
                        yield Violation(
                            rule=self.id, path=mod.path, line=bad.lineno,
                            col=bad.col_offset,
                            message=(
                                "static_argnums/static_argnames given a "
                                "mutable (unhashable) literal — use a tuple "
                                "so the spec (and the jit cache key) stays "
                                "hashable"),
                        )
            # (b)/(c): every jit reference, by context
            if not ((isinstance(node, (ast.Name, ast.Attribute))
                     and imports.is_jit(node))):
                continue
            # already reported by (a): jax.jit(f)(...) — the reference is
            # the func of a call that is itself immediately invoked
            wrap = parents.get(node)
            if (
                isinstance(wrap, ast.Call) and wrap.func is node
                and isinstance(parents.get(wrap), ast.Call)
                and parents[wrap].func is wrap
            ):
                continue
            # inside a loop (crossing function boundaries only via
            # decorators): a new wrapper per iteration
            prev, cur = node, parents.get(node)
            in_loop = False
            while cur is not None:
                if isinstance(cur, (ast.For, ast.While)):
                    in_loop = True
                    break
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and prev not in cur.decorator_list:
                    break
                prev, cur = cur, parents.get(cur)
            if in_loop:
                yield Violation(
                    rule=self.id, path=mod.path, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        "jax.jit inside a loop: a fresh wrapper (and "
                        "recompile) every iteration — hoist the jitted "
                        "function out of the loop"),
                )
                continue
            # nested jit without a memoized factory around it
            host = _enclosing_function(node, parents, through_decorators=True)
            if host is None:
                continue
            if not (_has_cache_decorator(host, imports)
                    or host.name in covered):
                yield Violation(
                    rule=self.id, path=mod.path, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"jax.jit inside {host.name!r}, which is neither "
                        f"memoized nor reachable from a memoized factory: "
                        f"the wrapper (and its compile cache) is rebuilt "
                        f"per call — decorate the factory with "
                        f"functools.lru_cache or move the jit to module "
                        f"level (repo idiom: cached_*/make_* layers)"),
                )


@register
class TpuDtypeContract:
    id = "J004"
    title = "64-bit dtypes on the device path (int32/f32/bf16 contract)"

    _IDIOM = ("the device layers are int32/f32/bf16 only; 64-bit keys use "
              "the emulated uint32-pair idiom (ops/pallas_kernels.py)")

    def check(self, mod: Module, config):
        if not config.in_scope(mod.relpath, config.j004_paths):
            return
        idx = traced_index(mod)
        imports = idx.imports
        traced_nodes: set[ast.AST] = set()
        for fn, _, _ in idx.traced:
            traced_nodes.update(ast.walk(fn))
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                dotted = imports.resolve(node)
                if dotted in JNP_64:
                    yield Violation(
                        rule=self.id, path=mod.path, line=node.lineno,
                        col=node.col_offset,
                        message=(f"{dotted.replace('jax.numpy', 'jnp')} is a "
                                 f"64-bit device dtype: {self._IDIOM}"),
                    )
                elif dotted in NP_64 and node in traced_nodes:
                    yield Violation(
                        rule=self.id, path=mod.path, line=node.lineno,
                        col=node.col_offset,
                        message=(f"{dotted.replace('numpy', 'np')} inside a "
                                 f"traced function: {self._IDIOM}"),
                    )
            elif isinstance(node, ast.Call):
                in_traced = node in traced_nodes
                dotted = imports.resolve(node.func)
                device_call = dotted is not None and (
                    dotted == "jax" or dotted.startswith("jax."))
                astype_call = (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                )
                for val in (
                    [kw.value for kw in node.keywords if kw.arg == "dtype"]
                    + (node.args[:1] if astype_call else [])
                ):
                    if (
                        isinstance(val, ast.Constant)
                        and val.value in STR_64
                        and (device_call or in_traced)
                    ):
                        yield Violation(
                            rule=self.id, path=mod.path, line=val.lineno,
                            col=val.col_offset,
                            message=(f'dtype "{val.value}" on the device '
                                     f'path: {self._IDIOM}'),
                        )
