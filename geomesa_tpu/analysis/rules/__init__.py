"""tpulint rule registry.

A rule is a class with a unique ``id``, a one-line ``title``, and a
``check(module, config) -> Iterable[Violation]`` method. Registering is
one decorator:

    from geomesa_tpu.analysis.rules import register

    @register
    class MyRule:
        id = "X001"
        title = "what it catches"
        def check(self, module, config): ...

Rule modules listed in ``_RULE_MODULES`` are imported on first use; a new
rule file only needs to be added there (see docs/tpulint.md "Adding a
rule").
"""

from __future__ import annotations

from importlib import import_module

RULES: dict[str, object] = {}

_RULE_MODULES = (
    "geomesa_tpu.analysis.rules.jax_rules",
    "geomesa_tpu.analysis.rules.concurrency",
    "geomesa_tpu.analysis.race.rules",
    "geomesa_tpu.analysis.flow.registry",
    "geomesa_tpu.analysis.sync.registry",
)


def register(cls):
    inst = cls()
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    RULES[inst.id] = inst
    return cls


def all_rules() -> dict[str, object]:
    for mod in _RULE_MODULES:
        import_module(mod)
    return RULES


def active_rules(config) -> list[object]:
    rules = all_rules()
    if config.rules is None:
        return [rules[k] for k in sorted(rules)]
    unknown = set(config.rules) - set(rules)
    if unknown:
        raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    return [rules[k] for k in sorted(config.rules)]
