"""tpuflow: contract-driven whole-program dataflow rules (F001-F003).

The third analysis prong. It rides the same project index and
per-function summaries as tpurace (:func:`build_flow_graph` — the scan
with CROSS-module call edges enabled), and checks the semantic contracts
declared through :mod:`geomesa_tpu.analysis.contracts`:

- **F001 epoch/invalidation coherence** — every declared mutation path
  must REACH (through the call graph) a declared purge of every cache
  surface it invalidates; name-keyed surfaces must die on name death
  (delete/delete_schema/rename — the ISSUE-7 recreate collision);
  epoch-keyed surfaces must declare a monotonic epoch; a non-immutable
  surface no mutation invalidates (and no monotonic epoch validates) is
  an undead cache.
- **F002 shadow-plane taint** — code reachable from a ``@shadow_plane``
  root (auditor, sweeper, referee execution) must not reach a
  ``@feedback_sink`` except through a function that consults a
  ``@shadow_guard`` (``audit.in_shadow``/``audit.shadow``). A non-root
  function referencing a guard is shadow-aware and trusted to gate its
  own sinks; a ROOT referencing a guard is not a barrier (otherwise the
  auditor's own ``with shadow():`` wrapper would vacuously bless every
  path below it).
- **F003 two-band f64 discipline** — ``certain``-band functions must be
  free of f64 (dtype references, astype, refine calls); every
  ``cand``-band superset must flow into a ``refine`` call or be returned
  to a caller (which then inherits the obligation, to a fixpoint).

Heuristics, not proofs: the expected answer for a reviewed intentional
site is a ``# tpuflow: disable=Fxxx`` waiver with a justification.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from dataclasses import dataclass

from geomesa_tpu.analysis.core import (
    LintConfig,
    Module,
    Violation,
    finalize_module_violations,
)
from geomesa_tpu.analysis.race.lockset import (
    _FnScan,
    _FnSummary,
    build_flow_graph,
    load_modules,
)
from geomesa_tpu.analysis.flow.contracts_scan import (
    DEATH_KINDS,
    Contracts,
    resolve_purge_specs,
    scan_contracts,
)

__all__ = [
    "FLOW_RULE_IDS", "analyze_flow_modules", "analyze_flow_paths",
    "contract_inventory", "active_flow_rules",
]

FLOW_RULE_IDS = ("F001", "F002", "F003")


def active_flow_rules(config: LintConfig) -> set[str]:
    if config.rules is None:
        return set(FLOW_RULE_IDS)
    return set(config.rules) & set(FLOW_RULE_IDS)


# ---------------------------------------------------------------------------
# call-graph helpers
# ---------------------------------------------------------------------------

def _adjacency(summaries) -> dict[tuple, list[tuple]]:
    return {k: [c.callee for c in s.calls] for k, s in summaries.items()}


def _reachable(adj: dict[tuple, list[tuple]], start: tuple) -> set[tuple]:
    seen = {start}
    stack = [start]
    while stack:
        k = stack.pop()
        for nxt in adj.get(k, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return seen


def _fn_node(project, key):
    kind, a, b = key
    if kind == "fn":
        return project.functions.get(a, {}).get(b)
    info = project.classes.get(a)
    return info.methods.get(b) if info is not None else None


# ---------------------------------------------------------------------------
# F001: epoch/invalidation coherence
# ---------------------------------------------------------------------------

def _check_f001(project, summaries, contracts: Contracts) -> list[Violation]:
    out: list[Violation] = []
    adj = _adjacency(summaries)
    by_name: dict[str, object] = {}
    for s in contracts.surfaces:
        if s.name in by_name:
            out.append(Violation(
                rule="F001", path=s.module.path, line=s.line, col=0,
                message=(f"duplicate cache surface name '{s.name}' "
                         f"(first declared by {by_name[s.name].owner})")))
            continue
        by_name[s.name] = s

    invalidated_by: dict[str, list] = defaultdict(list)
    for m in contracts.mutations:
        for nm in m.invalidates:
            if nm not in by_name:
                out.append(Violation(
                    rule="F001", path=m.module.path, line=m.line, col=0,
                    message=(f"mutation '{m.label}' invalidates unknown "
                             f"cache surface '{nm}' (no @cache_surface "
                             f"declares that name)")))
                continue
            invalidated_by[nm].append(m)

    # (pair) every declared mutation→surface edge must reach a purge
    for m in contracts.mutations:
        reach = None
        for nm in m.invalidates:
            s = by_name.get(nm)
            if s is None or s.immutable or not s.purge_keys:
                continue
            if reach is None:
                reach = _reachable(adj, m.key)
            if not any(pk in reach for pk in s.purge_keys):
                purges = ", ".join(sorted(
                    f"{k[1]}.{k[2]}" for k in s.purge_keys))
                out.append(Violation(
                    rule="F001", path=m.module.path, line=m.line, col=0,
                    message=(
                        f"mutation '{m.label}' ({m.kind}) declares it "
                        f"invalidates cache surface '{nm}' but no declared "
                        f"purge ({purges}) is reachable from it through "
                        f"the call graph — the cache survives this "
                        f"mutation")))

    for s in contracts.surfaces:
        if s is not by_name.get(s.name) or s.immutable:
            continue
        muts = invalidated_by.get(s.name, [])
        # (death) name-keyed caches must die with the name: the ISSUE-7
        # delete→recreate collision restarts the per-type epoch tuple at
        # equal values, so epoch stamps alone can serve a dead table
        if s.keyed_by == "type_name":
            if not any(m.kind in DEATH_KINDS for m in muts):
                out.append(Violation(
                    rule="F001", path=s.module.path, line=s.line, col=0,
                    message=(
                        f"cache surface '{s.name}' is keyed by type_name "
                        f"but no death mutation "
                        f"({'/'.join(sorted(DEATH_KINDS))}) declares it — "
                        f"a deleted-then-recreated type would serve the "
                        f"dead table's entries")))
        # (epoch) epoch-keyed caches must prove the stamp is monotonic
        elif s.keyed_by == "epoch" and s.epoch != "monotonic":
            out.append(Violation(
                rule="F001", path=s.module.path, line=s.line, col=0,
                message=(
                    f"cache surface '{s.name}' is keyed by epoch but does "
                    f"not declare epoch='monotonic' — an epoch tuple that "
                    f"can restart at an equal value re-validates dead "
                    f"entries")))
        # (orphan) nothing invalidates it and no monotonic epoch
        # self-validates entries: an undead cache
        if not muts and not (s.keyed_by == "epoch"
                             and s.epoch == "monotonic"):
            out.append(Violation(
                rule="F001", path=s.module.path, line=s.line, col=0,
                message=(
                    f"cache surface '{s.name}' is declared but no "
                    f"@mutation invalidates it and no monotonic epoch "
                    f"validates its entries — either declare the mutation "
                    f"paths or mark it immutable=True")))
    return out


# ---------------------------------------------------------------------------
# F002: shadow-plane taint
# ---------------------------------------------------------------------------

def _check_f002(project, summaries, contracts: Contracts) -> list[Violation]:
    out: list[Violation] = []
    guards = {g.key for g in contracts.guards}
    sinks = {d.key: d for d in contracts.sinks}
    if not sinks:
        return out
    root_keys: set[tuple] = set()
    for r in contracts.shadow_roots:
        root_keys.update(r.keys)
    seen_sites: set[tuple] = set()
    for root in contracts.shadow_roots:
        for rk in root.keys:
            if rk not in summaries:
                continue
            visited = {rk}
            stack = [rk]
            while stack:
                k = stack.pop()
                s = summaries[k]
                # a non-root function that consults a shadow guard is
                # shadow-aware: trusted to gate its own sinks, traversal
                # stops. Roots are never barriers — the auditor's own
                # shadow() wrapper must not bless everything below it.
                if k not in root_keys and any(
                    c.callee in guards for c in s.calls
                ):
                    continue
                for c in s.calls:
                    if c.callee in guards:
                        continue
                    if c.callee in sinks:
                        site = (s.module.path, c.line, c.callee)
                        if site in seen_sites:
                            continue
                        seen_sites.add(site)
                        d = sinks[c.callee]
                        out.append(Violation(
                            rule="F002", path=s.module.path, line=c.line,
                            col=0,
                            message=(
                                f"shadow-plane code (rooted at "
                                f"{root.label}) reaches feedback sink "
                                f"{d.label} with no shadow guard on the "
                                f"path — audit traffic would train/bill "
                                f"this sink; gate it behind in_shadow() "
                                f"or hoist it out of the shadow plane")))
                        continue
                    if c.callee in summaries and c.callee not in visited:
                        visited.add(c.callee)
                        stack.append(c.callee)
    return out


# ---------------------------------------------------------------------------
# F003: two-band f64 dtype discipline
# ---------------------------------------------------------------------------

_F64_SUFFIXES = (".float64", ".f64", ".double")


def _f64_reference(node: ast.AST, imports) -> str | None:
    """What (if anything) makes this node an f64 construction."""
    if isinstance(node, (ast.Attribute, ast.Name)):
        dotted = imports.resolve(node)
        if dotted is not None and (
            dotted == "float64" or dotted.endswith(_F64_SUFFIXES)
        ):
            return dotted
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "astype":
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Constant) and a.value == "float64":
                    return ".astype('float64')"
        for k in node.keywords:
            if k.arg == "dtype" and isinstance(k.value, ast.Constant) \
                    and k.value.value == "float64":
                return "dtype='float64'"
    return None


def _check_f003_certain(project, summaries, contracts) -> list[Violation]:
    out: list[Violation] = []
    refines = {b.key for b in contracts.bands if b.refine}
    for band in contracts.bands:
        if not band.certain:
            continue
        key = band.key
        fn = _fn_node(project, key)
        if fn is None or key not in summaries:
            continue
        s = summaries[key]
        imports = project.imports[s.module.relpath]
        for node in ast.walk(fn):
            what = _f64_reference(node, imports)
            if what is not None:
                out.append(Violation(
                    rule="F003", path=s.module.path, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"certain-band function {band.label} references "
                        f"f64 ({what}) — certain decisions must stay in "
                        f"the f32 device band; route exact work through a "
                        f"@device_band(refine=True) function")))
        for c in s.calls:
            if c.callee in refines:
                out.append(Violation(
                    rule="F003", path=s.module.path, line=c.line, col=0,
                    message=(
                        f"certain-band function {band.label} calls the "
                        f"f64 refine {c.callee[1]}.{c.callee[2]} — "
                        f"certain results must not depend on host f64 "
                        f"refinement")))
    return out


@dataclass
class _Taint:
    line: int
    col: int
    provider: str
    satisfied: bool = False


class _CandScan(_FnScan):
    """Forward taint pass: a cand-provider call taints its result (and a
    factory-returned step propagates — calling a tainted name yields a
    tainted value); taint is retired by flowing into a refine call or a
    return statement (the caller inherits the obligation)."""

    def __init__(self, project, summary, fn, providers, refines):
        super().__init__(project, summary, fn, cross_module=True)
        self.providers = providers      # key -> label
        self.refines = refines          # set of keys
        self.tainted: dict[str, _Taint] = {}
        self.refined: set[str] = set()  # names holding refine output
        self.taints: list[_Taint] = []
        self.returns_taint = False
        self._claimed: set[int] = set()

    def _is_refined(self, expr: ast.AST) -> bool:
        """Does this value derive from a refine call (directly or via a
        name that holds refine output)?"""
        if isinstance(expr, ast.Call) and self._callee_key(expr.func) \
                in self.refines:
            return True
        return any(
            isinstance(sub, ast.Name) and sub.id in self.refined
            for sub in ast.walk(expr)
        )

    def _merge_refined(self, target: ast.AST) -> None:
        """Refine output merged into ``target``: the band it carried is
        retired (``out[band_rows] |= exact`` — the two-band pattern), so
        the name is clean from here on and imposes no obligation on
        callers it is returned to."""
        root = target
        while isinstance(root, (ast.Subscript, ast.Attribute, ast.Starred)):
            root = root.value
        if isinstance(root, ast.Name):
            t = self.tainted.pop(root.id, None)
            if t is not None:
                t.satisfied = True
            self.refined.add(root.id)

    def _value_taint(self, expr: ast.AST) -> _Taint | None:
        if isinstance(expr, ast.Call):
            key = self._callee_key(expr.func)
            if key in self.refines:
                return None  # refined output is clean by definition
            if key in self.providers:
                self._claimed.add(id(expr))
                t = _Taint(expr.lineno, expr.col_offset,
                           self.providers[key])
                self.taints.append(t)
                return t
            f = expr.func
            if isinstance(f, ast.Name) and f.id in self.tainted:
                return self.tainted[f.id]  # calling the tainted step fn
        if isinstance(expr, ast.Name):
            return self.tainted.get(expr.id)
        if isinstance(expr, ast.IfExp):
            return (self._value_taint(expr.body)
                    or self._value_taint(expr.orelse))
        if isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                t = self._value_taint(el)
                if t is not None:
                    return t
            return None
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return self.tainted[sub.id]
        return None

    def _bind(self, target: ast.AST, t: _Taint) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, t)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, t)
        elif isinstance(target, ast.Name):
            self.tainted[target.id] = t
        else:
            # stored into an attribute/subscript: escapes local analysis
            t.satisfied = True

    def visit_Assign(self, node: ast.Assign):
        t = self._value_taint(node.value)
        if t is not None:
            for tgt in node.targets:
                self._bind(tgt, t)
        elif self._is_refined(node.value):
            for tgt in node.targets:
                self._merge_refined(tgt)
        super().visit_Assign(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            t = self._value_taint(node.value)
            if t is not None:
                self._bind(node.target, t)
            elif self._is_refined(node.value):
                self._merge_refined(node.target)
        super().visit_AnnAssign(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if self._is_refined(node.value):
            self._merge_refined(node.target)
        else:
            t = self._value_taint(node.value)
            if t is not None:
                self._bind(node.target, t)
        super().visit_AugAssign(node)

    def visit_Return(self, node: ast.Return):
        if node.value is not None:
            t = self._value_taint(node.value)
            if t is not None:
                t.satisfied = True
                self.returns_taint = True
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        key = self._callee_key(node.func)
        if key in self.refines:
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in self.tainted:
                        self.tainted[sub.id].satisfied = True
                    elif isinstance(sub, ast.Call):
                        # refine(cand_fn(...)) — direct composition
                        if self._callee_key(sub.func) in self.providers:
                            self._claimed.add(id(sub))
        elif key in self.providers and id(node) not in self._claimed:
            # bare provider call whose result is discarded
            self._claimed.add(id(node))
            self.taints.append(_Taint(
                node.lineno, node.col_offset, self.providers[key]))
        super().visit_Call(node)


def _check_f003_cand(project, summaries, contracts) -> list[Violation]:
    providers = {b.key: b.label for b in contracts.bands if b.cand}
    refines = {b.key for b in contracts.bands if b.refine}
    if not providers:
        return []
    results: dict[tuple, _CandScan] = {}
    pending = set(providers)
    while pending:
        callers = [
            k for k, s in summaries.items()
            if any(c.callee in pending for c in s.calls)
        ]
        pending = set()
        for key in callers:
            fn = _fn_node(project, key)
            if fn is None:
                continue
            s = summaries[key]
            scratch = _FnSummary(key=key, name=s.name, cls=s.cls,
                                 module=s.module)
            scan = _CandScan(project, scratch, fn, providers, refines)
            for stmt in fn.body:
                scan.visit(stmt)
            results[key] = scan
            if scan.returns_taint and key not in providers:
                # this function RETURNS an unrefined cand superset: its
                # callers inherit the refine obligation (fixpoint)
                label = (f"{key[1]}.{key[2]}" if key[0] == "method"
                         else f"{key[1]}:{key[2]}")
                providers[key] = label
                pending.add(key)
    out: list[Violation] = []
    for key, scan in results.items():
        for t in scan.taints:
            if t.satisfied:
                continue
            out.append(Violation(
                rule="F003", path=scan.mod.path, line=t.line, col=t.col,
                message=(
                    f"candidate-band superset from {t.provider} never "
                    f"reaches an f64 refine — pass it to a "
                    f"@device_band(refine=True) function or return it to "
                    f"a caller that does (an unrefined cand band ships "
                    f"false positives)")))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze_flow_modules(modules: list[Module],
                         config: LintConfig | None = None) -> list[Violation]:
    """Run F001/F002/F003 over a parsed module set (waivers/baseline are
    the caller's passes, same contract as ``analyze_modules``)."""
    config = config or LintConfig()
    active = active_flow_rules(config)
    project, summaries = build_flow_graph(modules, config)
    contracts = scan_contracts(project, modules)
    resolve_purge_specs(project, contracts)
    violations: list[Violation] = list(contracts.errors)
    if "F001" in active:
        violations.extend(_check_f001(project, summaries, contracts))
    if "F002" in active:
        violations.extend(_check_f002(project, summaries, contracts))
    if "F003" in active:
        violations.extend(_check_f003_certain(project, summaries, contracts))
        violations.extend(_check_f003_cand(project, summaries, contracts))
    violations = [v for v in violations if v.rule in active]
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations


def analyze_flow_paths(paths: list[str],
                       config: LintConfig | None = None) -> list[Violation]:
    """The ``--flow`` entry point: parse every file, run the contract
    dataflow analysis, and apply the shared waiver/staleness passes."""
    from geomesa_tpu.analysis.rules import all_rules

    config = config or LintConfig()
    if config.rules is not None:
        unknown = set(config.rules) - set(all_rules())
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    modules, violations = load_modules(paths)
    violations = list(violations)
    violations.extend(analyze_flow_modules(modules, config))
    by_path: dict[str, list[Violation]] = defaultdict(list)
    for v in violations:
        by_path[v.path].append(v)
    judged = active_flow_rules(config)
    emit_w001 = config.rules is None or "W001" in config.rules
    for mod in modules:
        vs = by_path.get(mod.path, [])
        violations.extend(finalize_module_violations(
            mod, vs, judged, emit_w001=emit_w001))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations


def contract_inventory(modules: list[Module],
                       config: LintConfig | None = None) -> dict:
    """The ``--flow --contracts`` view: every declared surface, mutation,
    sink, shadow root/guard, and band role, with declaration sites."""
    config = config or LintConfig()
    project, _ = build_flow_graph(modules, config)
    contracts = scan_contracts(project, modules)
    resolve_purge_specs(project, contracts)

    def at(module, line):
        return f"{module.relpath}:{line}"

    return {
        "cache_surfaces": [
            {
                "name": s.name, "keyed_by": s.keyed_by, "epoch": s.epoch,
                "immutable": s.immutable, "owner": s.owner,
                "purge": list(s.purge),
                "declared_at": at(s.module, s.line),
            }
            for s in sorted(contracts.surfaces, key=lambda s: s.name)
        ],
        "mutations": [
            {
                "fn": m.label, "kind": m.kind,
                "invalidates": list(m.invalidates),
                "declared_at": at(m.module, m.line),
            }
            for m in sorted(contracts.mutations,
                            key=lambda m: (m.label, m.kind))
        ],
        "feedback_sinks": [
            {"fn": d.label, "declared_at": at(d.module, d.line)}
            for d in sorted(contracts.sinks, key=lambda d: d.label)
        ],
        "shadow_planes": [
            {"name": r.label, "entry_points": len(r.keys),
             "declared_at": at(r.module, r.line)}
            for r in sorted(contracts.shadow_roots, key=lambda r: r.label)
        ],
        "shadow_guards": [
            {"fn": d.label, "declared_at": at(d.module, d.line)}
            for d in sorted(contracts.guards, key=lambda d: d.label)
        ],
        "device_bands": [
            {
                "fn": b.label,
                "role": ("certain" if b.certain
                         else "cand" if b.cand else "refine"),
                "declared_at": at(b.module, b.line),
            }
            for b in sorted(contracts.bands, key=lambda b: b.label)
        ],
    }
