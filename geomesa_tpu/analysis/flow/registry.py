"""Registry descriptors for the tpuflow rules.

F001-F003 are WHOLE-PROGRAM rules (``project = True``): their findings
come from :func:`geomesa_tpu.analysis.flow.rules.analyze_flow_paths`
(the ``--flow`` CLI mode), not the per-module ``check`` pass — the
``check`` here is a no-op so the ids still resolve for ``--list-rules``,
``--rules`` filtering, waivers, baselines, and SARIF rule metadata
(same pattern as the tpurace descriptors)."""

from __future__ import annotations

from geomesa_tpu.analysis.rules import register


@register
class EpochInvalidationCoherence:
    id = "F001"
    title = "cache surface not invalidated by a declared mutation path"
    project = True

    def check(self, mod, config):
        return ()


@register
class ShadowPlaneTaint:
    id = "F002"
    title = "shadow-plane execution reaches a feedback sink unguarded"
    project = True

    def check(self, mod, config):
        return ()


@register
class TwoBandDtypeTaint:
    id = "F003"
    title = "f64 in a certain-band decision, or an unrefined cand band"
    project = True

    def check(self, mod, config):
        return ()
