"""tpuflow — the contract-driven whole-program dataflow prong.

``python -m geomesa_tpu.analysis --flow`` is the CLI spelling;
:mod:`geomesa_tpu.analysis.contracts` is the declaration vocabulary the
live code imports; :mod:`geomesa_tpu.analysis.flow.rules` documents the
F001/F002/F003 rule families."""

from geomesa_tpu.analysis.flow.rules import (
    FLOW_RULE_IDS,
    active_flow_rules,
    analyze_flow_modules,
    analyze_flow_paths,
    contract_inventory,
)

__all__ = [
    "FLOW_RULE_IDS", "active_flow_rules", "analyze_flow_modules",
    "analyze_flow_paths", "contract_inventory",
]
