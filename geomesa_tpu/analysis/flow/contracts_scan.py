"""Pure-AST extraction of :mod:`geomesa_tpu.analysis.contracts` markers.

The live code imports the (no-op) decorators; this scanner reads them
back OFF THE AST — decorated modules are parsed, never imported, so the
flow prong keeps tpulint's no-JAX/no-sibling-import layering contract.
Decorator spellings canonicalize through each module's :class:`ImportMap`
(``@contracts.cache_surface`` and ``from ... import cache_surface`` are
the same marker), and every argument must be a literal — a computed
contract cannot be checked statically and is itself an F001 finding.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from geomesa_tpu.analysis.contracts import DEATH_KINDS, MUTATION_KINDS
from geomesa_tpu.analysis.core import Module, Violation
from geomesa_tpu.analysis.race.lockset import _module_id

__all__ = ["Contracts", "scan_contracts", "DEATH_KINDS", "MUTATION_KINDS"]

_NS = "geomesa_tpu.analysis.contracts."


@dataclass
class CacheSurface:
    name: str
    keyed_by: str
    epoch: str | None
    purge: tuple[str, ...]
    immutable: bool
    owner: str                      # human label ("QueryCache", "mod:fn")
    owner_class: str | None         # project-keyed class name, if a class
    module: Module
    line: int
    purge_keys: list = field(default_factory=list)  # resolved summary keys


@dataclass
class MutationDecl:
    kind: str
    invalidates: tuple[str, ...]
    key: tuple                      # summary key of the decorated function
    label: str
    module: Module
    line: int


@dataclass
class FnDecl:
    """A bare function marker: sink / shadow guard."""

    key: tuple
    label: str
    module: Module
    line: int


@dataclass
class ShadowRoot:
    keys: tuple                     # every entry key (all methods, for a class)
    label: str
    module: Module
    line: int


@dataclass
class BandDecl:
    key: tuple
    label: str
    certain: bool
    cand: bool
    refine: bool
    module: Module
    line: int


@dataclass
class Contracts:
    surfaces: list[CacheSurface] = field(default_factory=list)
    mutations: list[MutationDecl] = field(default_factory=list)
    sinks: list[FnDecl] = field(default_factory=list)
    shadow_roots: list[ShadowRoot] = field(default_factory=list)
    guards: list[FnDecl] = field(default_factory=list)
    bands: list[BandDecl] = field(default_factory=list)
    # malformed declarations (non-literal args, unknown kinds) — F001
    errors: list[Violation] = field(default_factory=list)


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return _BAD


_BAD = object()


def _kwargs(call: ast.Call, module: Module, out: Contracts) -> dict | None:
    kw = {}
    for k in call.keywords:
        if k.arg is None:
            out.errors.append(_decl_error(
                module, call, "contract arguments cannot be **-splatted"))
            return None
        v = _literal(k.value)
        if v is _BAD:
            out.errors.append(_decl_error(
                module, call,
                f"contract argument {k.arg!r} must be a literal "
                f"(a computed contract cannot be checked statically)"))
            return None
        kw[k.arg] = v
    return kw


def _decl_error(module: Module, node: ast.AST, msg: str) -> Violation:
    return Violation(
        rule="F001", path=module.path, line=node.lineno, col=node.col_offset,
        message=f"malformed contract declaration: {msg}")


def _tuple_of_str(val, default=()) -> tuple[str, ...]:
    if val is None:
        return tuple(default)
    if isinstance(val, str):
        return (val,)
    return tuple(str(x) for x in val)


class _Scanner:
    def __init__(self, project, contracts: Contracts):
        self.project = project
        self.out = contracts
        # ast node -> the name _Project keyed the class under (handles
        # the ambiguous-namesake re-keying)
        self.node_class = {
            id(info.node): keyed for keyed, info in project.classes.items()
        }

    def scan(self, module: Module) -> None:
        imports = self.project.imports[module.relpath]
        mid = _module_id(module.relpath)
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                keyed = self.node_class.get(id(node), node.name)
                self._decorators(module, imports, node, cls=keyed)
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._decorators(module, imports, m, cls=keyed,
                                         method=m.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._decorators(module, imports, node,
                                 fn_key=("fn", mid, node.name))

    # -- one decorated definition -------------------------------------------
    def _decorators(self, module, imports, node, cls=None, method=None,
                    fn_key=None) -> None:
        if method is not None:
            fn_key = ("method", cls, method)
            label = f"{cls}.{method}"
        elif fn_key is not None:
            label = f"{fn_key[1]}:{fn_key[2]}"
        else:
            label = cls
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            dotted = imports.resolve(target)
            if dotted is None or not dotted.startswith(_NS):
                continue
            marker = dotted[len(_NS):]
            if isinstance(dec, ast.Call):
                kw = _kwargs(dec, module, self.out)
                if kw is None:
                    continue
            else:
                kw = {}
            self._one(module, node, dec, marker, kw, label, cls, method,
                      fn_key)

    def _one(self, module, node, dec, marker, kw, label, cls, method,
             fn_key) -> None:
        line = dec.lineno
        if marker == "cache_surface":
            name = kw.get("name")
            keyed_by = kw.get("keyed_by")
            if not name or not keyed_by:
                self.out.errors.append(_decl_error(
                    module, dec,
                    "cache_surface requires name= and keyed_by="))
                return
            self.out.surfaces.append(CacheSurface(
                name=str(name), keyed_by=str(keyed_by),
                epoch=kw.get("epoch"),
                purge=_tuple_of_str(kw.get("purge")),
                immutable=bool(kw.get("immutable", False)),
                owner=label,
                owner_class=cls if method is None else None,
                module=module, line=line))
        elif marker == "mutation":
            if fn_key is None:
                self.out.errors.append(_decl_error(
                    module, dec, "@mutation applies to functions/methods, "
                    "not classes"))
                return
            kind = kw.get("kind")
            if kind not in MUTATION_KINDS:
                self.out.errors.append(_decl_error(
                    module, dec,
                    f"unknown mutation kind {kind!r} (expected one of "
                    f"{sorted(MUTATION_KINDS)})"))
                return
            self.out.mutations.append(MutationDecl(
                kind=kind, invalidates=_tuple_of_str(kw.get("invalidates")),
                key=fn_key, label=label, module=module, line=line))
        elif marker == "feedback_sink":
            if fn_key is None:
                self.out.errors.append(_decl_error(
                    module, dec, "@feedback_sink applies to "
                    "functions/methods, not classes"))
                return
            self.out.sinks.append(FnDecl(
                key=fn_key, label=label, module=module, line=line))
        elif marker == "shadow_plane":
            if fn_key is not None:
                keys = (fn_key,)
            else:
                info = self.project.classes.get(cls)
                keys = tuple(
                    ("method", cls, m)
                    for m in (info.methods if info else ())
                )
            self.out.shadow_roots.append(ShadowRoot(
                keys=keys, label=label, module=module, line=line))
        elif marker == "shadow_guard":
            if fn_key is None:
                self.out.errors.append(_decl_error(
                    module, dec, "@shadow_guard applies to "
                    "functions/methods, not classes"))
                return
            self.out.guards.append(FnDecl(
                key=fn_key, label=label, module=module, line=line))
        elif marker == "device_band":
            if fn_key is None:
                self.out.errors.append(_decl_error(
                    module, dec, "@device_band applies to "
                    "functions/methods, not classes"))
                return
            roles = {k for k in ("certain", "cand", "refine") if kw.get(k)}
            if len(roles) != 1:
                self.out.errors.append(_decl_error(
                    module, dec, "device_band requires exactly one of "
                    "certain/cand/refine"))
                return
            self.out.bands.append(BandDecl(
                key=fn_key, label=label,
                certain=bool(kw.get("certain")), cand=bool(kw.get("cand")),
                refine=bool(kw.get("refine")), module=module, line=line))


def scan_contracts(project, modules: list[Module]) -> Contracts:
    """Every contract declaration in ``modules``, keyed into ``project``'s
    summary-key namespace (the one :func:`build_flow_graph` emits)."""
    out = Contracts()
    scanner = _Scanner(project, out)
    for mod in modules:
        scanner.scan(mod)
    return out


def resolve_purge_specs(project, contracts: Contracts) -> None:
    """Fill each surface's ``purge_keys`` from its ``purge`` spec strings.

    Spellings: a bare name is a method of the decorated class, or a
    module-level function of the declaring module; ``Class.method``
    crosses classes; ``pkg.mod:fn`` crosses modules. An unresolvable
    spec is an F001 declaration error — a purge the analyzer cannot
    find is a purge reviewers cannot find either."""
    for s in contracts.surfaces:
        mid = _module_id(s.module.relpath)
        for spec in s.purge:
            key = _resolve_purge(project, s, mid, spec)
            if key is None:
                contracts.errors.append(Violation(
                    rule="F001", path=s.module.path, line=s.line, col=0,
                    message=(
                        f"cache surface '{s.name}': purge spec {spec!r} "
                        f"does not resolve to a known function (bare "
                        f"method, 'Class.method', or 'pkg.mod:fn')")))
            else:
                s.purge_keys.append(key)


def _resolve_purge(project, s: CacheSurface, mid: str, spec: str):
    if ":" in spec:
        mod_part, _, fn = spec.partition(":")
        return project.local_fn_key(f"{mod_part}.{fn}")
    if "." in spec:
        cls_part, _, m = spec.rpartition(".")
        cls = (cls_part if cls_part in project.classes
               else project.resolve_class(cls_part))
        if cls is not None and m in project.classes[cls].methods:
            return ("method", cls, m)
        return None
    if s.owner_class is not None:
        info = project.classes.get(s.owner_class)
        if info is not None and spec in info.methods:
            return ("method", s.owner_class, spec)
    if spec in project.functions.get(mid, {}):
        return ("fn", mid, spec)
    return None
