"""tpulint output: human text and a SARIF-ish JSON report."""

from __future__ import annotations

import json

from geomesa_tpu.analysis.core import Violation


def summarize(violations: list[Violation]) -> dict:
    new = [v for v in violations if not v.suppressed]
    return {
        "total": len(violations),
        "new": len(new),
        "waived": sum(v.waived for v in violations),
        "baselined": sum(v.baselined for v in violations),
        "by_rule": {
            rule: sum(1 for v in new if v.rule == rule)
            for rule in sorted({v.rule for v in new})
        },
    }


def render_text(violations: list[Violation], verbose: bool = False) -> str:
    out = []
    for v in violations:
        if v.suppressed and not verbose:
            continue
        tag = " [waived]" if v.waived else (" [baselined]" if v.baselined else "")
        out.append(f"{v.path}:{v.line}:{v.col}: {v.rule}{tag} {v.message}")
        if v.snippet:
            out.append(f"    {v.snippet}")
    s = summarize(violations)
    out.append(
        f"tpulint: {s['new']} new violation(s), {s['waived']} waived, "
        f"{s['baselined']} baselined"
    )
    if s["by_rule"]:
        out.append("  new by rule: " + ", ".join(
            f"{k}={n}" for k, n in s["by_rule"].items()))
    return "\n".join(out)


def render_json(violations: list[Violation]) -> str:
    """SARIF-shaped: one run, one result per violation, pass/fail in
    ``summary`` — enough structure for CI annotation tooling without the
    full SARIF schema weight."""
    from geomesa_tpu.analysis.rules import all_rules

    rules = all_rules()
    doc = {
        "$schema": "tpulint-report",
        "version": "1.0",
        "tool": {
            "name": "tpulint",
            "rules": [
                {"id": rid, "shortDescription": rules[rid].title}
                for rid in sorted(rules)
            ],
        },
        "results": [
            {
                "ruleId": v.rule,
                "level": "note" if v.suppressed else "error",
                "message": v.message,
                "location": {"path": v.path, "line": v.line, "col": v.col},
                "snippet": v.snippet,
                "suppressed": v.suppressed,
                "suppression": (
                    "waiver" if v.waived
                    else "baseline" if v.baselined else None
                ),
            }
            for v in violations
        ],
        "summary": summarize(violations),
    }
    return json.dumps(doc, indent=1)
