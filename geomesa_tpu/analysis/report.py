"""tpulint output: human text and a SARIF 2.1.0 report.

The SARIF document is the real schema (version 2.1.0, one ``run`` with
``tool.driver`` rule metadata, ``results`` with ``physicalLocation``
regions, in-source ``suppressions``) so CI can ingest it directly —
GitHub code-scanning upload, ``sarif-tools``, IDE SARIF viewers. The
run-level roll-up lives in ``runs[0].properties.summary`` (SARIF's
sanctioned extension point)."""

from __future__ import annotations

import json

from geomesa_tpu.analysis.core import Violation

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def summarize(violations: list[Violation]) -> dict:
    new = [v for v in violations if not v.suppressed]
    return {
        "total": len(violations),
        "new": len(new),
        "waived": sum(v.waived for v in violations),
        "baselined": sum(v.baselined for v in violations),
        "by_rule": {
            rule: sum(1 for v in new if v.rule == rule)
            for rule in sorted({v.rule for v in new})
        },
    }


def render_text(violations: list[Violation], verbose: bool = False) -> str:
    out = []
    for v in violations:
        if v.suppressed and not verbose:
            continue
        tag = " [waived]" if v.waived else (" [baselined]" if v.baselined else "")
        out.append(f"{v.path}:{v.line}:{v.col}: {v.rule}{tag} {v.message}")
        if v.snippet:
            out.append(f"    {v.snippet}")
    s = summarize(violations)
    out.append(
        f"tpulint: {s['new']} new violation(s), {s['waived']} waived, "
        f"{s['baselined']} baselined"
    )
    if s["by_rule"]:
        out.append("  new by rule: " + ", ".join(
            f"{k}={n}" for k, n in s["by_rule"].items()))
    return "\n".join(out)


def _sarif_result(v: Violation, rule_index: dict[str, int]) -> dict:
    region = {"startLine": v.line}
    if v.col:
        region["startColumn"] = v.col + 1  # SARIF columns are 1-based
    if v.snippet:
        region["snippet"] = {"text": v.snippet}
    result = {
        "ruleId": v.rule,
        "level": "note" if v.suppressed else "error",
        "message": {"text": v.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": v.path.replace("\\", "/"),
                    "uriBaseId": "SRCROOT",
                },
                "region": region,
            },
        }],
    }
    if v.rule in rule_index:
        result["ruleIndex"] = rule_index[v.rule]
    if v.suppressed:
        # SARIF semantics: a result with a non-empty suppressions array is
        # suppressed; "inSource" = waiver comment, "external" = baseline
        result["suppressions"] = [{
            "kind": "inSource" if v.waived else "external",
            "justification": (
                "per-line tpulint/tpurace waiver" if v.waived
                else "tracked legacy violation in .tpulint-baseline.json"
            ),
        }]
    return result


def _sarif_run(driver: str, violations: list[Violation],
               rule_ids: list[str], rules: dict) -> dict:
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    return {
        "tool": {
            "driver": {
                "name": driver,
                "informationUri":
                    "https://example.invalid/geomesa_tpu/docs/tpulint.md",
                "rules": [
                    {
                        "id": rid,
                        "shortDescription": {"text": rules[rid].title},
                        "defaultConfiguration": {"level": "error"},
                    }
                    for rid in rule_ids
                ],
            },
        },
        "originalUriBaseIds": {
            "SRCROOT": {"description": {"text": "repository root"}},
        },
        "results": [_sarif_result(v, rule_index) for v in violations],
        "properties": {"summary": summarize(violations)},
    }


def render_json(violations: list[Violation]) -> str:
    """The SARIF 2.1.0 document (``--format json``/``--format sarif``)."""
    from geomesa_tpu.analysis.rules import all_rules

    rules = all_rules()
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [_sarif_run("tpulint", violations, sorted(rules), rules)],
    }
    return json.dumps(doc, indent=1)


# which registered rule ids each prong's SARIF driver advertises; W001 is
# shared hygiene and appears under every driver (each prong judges it)
_PRONG_RULE_FILTERS = {
    "tpulint": lambda rid: rid[:1] not in ("R", "F", "S"),
    "tpurace": lambda rid: rid[:1] == "R" or rid == "W001",
    "tpuflow": lambda rid: rid[:1] == "F" or rid == "W001",
    "tpusync": lambda rid: rid[:1] == "S" or rid == "W001",
}


def render_json_multi(prong_runs: list[tuple[str, list[Violation]]]) -> str:
    """One SARIF log with one run per prong (``--all-prongs``): each run
    carries its own driver name and only that prong's rule metadata, so
    code-scanning ingestion attributes findings to the right tool."""
    from geomesa_tpu.analysis.rules import all_rules

    rules = all_rules()
    runs = []
    for driver, violations in prong_runs:
        keep = _PRONG_RULE_FILTERS[driver]
        rule_ids = [rid for rid in sorted(rules) if keep(rid)]
        runs.append(_sarif_run(driver, violations, rule_ids, rules))
    return json.dumps({
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": runs,
    }, indent=1)
