"""tpulint core: violations, waivers, baselines, and the lint driver.

The analyzer is pure AST — linted files are parsed, never imported, so a
full-package lint needs no JAX install and runs in a few seconds on a
bare CPU box (the CI tier-1 budget). Layering contract: nothing in
``geomesa_tpu.analysis`` may import JAX or any sibling geomesa_tpu
subsystem; the linter must stay runnable on a bare CPU box.

Suppression model, narrowest to widest:

- per-line waiver: ``# tpulint: disable=J002`` (same line) or
  ``# tpulint: disable-next-line=J002,C001`` — for reviewed, intentional
  sites (e.g. the one sanctioned device→host readback of a hot path).
  ``# tpurace: disable=R001`` is the identical syntax for the race
  rules; the two spellings share one namespace (either prefix waives
  either family), they just make intent greppable per prong.
- stale-waiver hygiene (W001): a waiver whose every listed rule ran in
  the current pass yet suppressed nothing is itself a violation — dead
  waivers otherwise accumulate and silently license future regressions
  at that line. Rules that did NOT run in the pass (race rules during a
  lint pass and vice versa) make the comment unjudgeable, so
  mixed-prong waivers belong on separate comments.
- baseline file: a committed JSON multiset of known legacy violations
  (``--baseline .tpulint-baseline.json``). Violations matching a baseline
  entry report as ``baselined`` and do not fail the run; NEW violations
  fail. ``--write-baseline`` refreshes the file. Entries are keyed by
  (rule, path, normalized source line), not line numbers, so unrelated
  edits don't invalidate the baseline.
"""

from __future__ import annotations

import ast
import json
import os
import re
from collections import Counter
from dataclasses import dataclass, field

__all__ = [
    "Violation", "LintConfig", "Module", "lint_source", "lint_paths",
    "load_baseline", "write_baseline", "apply_baseline", "iter_py_files",
    "parse_module", "waiver_map", "stale_waiver_violations",
    "finalize_module_violations", "AnalysisCrash",
]


class AnalysisCrash(Exception):
    """A rule/prong crashed mid-analysis. The CLI turns this into exit
    code 3 NAMING the failing file — a crash must never read as a clean
    "0 findings" run (a silently-skipped file is an unlinted file)."""

    def __init__(self, path: str, where: str, cause: BaseException):
        self.path = path
        self.where = where
        self.cause = cause
        super().__init__(
            f"analysis crashed in {where} while processing {path}: "
            f"{type(cause).__name__}: {cause}")


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""
    waived: bool = False
    baselined: bool = False

    @property
    def suppressed(self) -> bool:
        return self.waived or self.baselined

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)


@dataclass
class LintConfig:
    """Rule scoping knobs. Path tuples are package-relative prefixes; a
    module participates in a path-scoped rule when its package-relative
    path starts with one of them. ``("",)`` means "everywhere" (the
    fixture tests use that to lint files outside the package tree)."""

    # J002 hot paths: the device scan/refine/aggregate layers.
    j002_paths: tuple[str, ...] = ("ops/", "parallel/")
    # J004 TPU dtype contract: everything that computes keys or runs on
    # device — curve math feeds the device layout, so 64-bit creep there
    # flows straight into kernels.
    j004_paths: tuple[str, ...] = ("curve/", "index/", "ops/", "parallel/")
    # C001 shared-state heuristics: package-wide — the rule self-scopes to
    # classes that own a threading lock (the stream layer, lock utilities,
    # and every other utils/locks user).
    c001_paths: tuple[str, ...] = ("",)
    # tpurace (R001-R003) module scope: the whole package by default — the
    # analysis self-scopes to code that owns or touches locks.
    race_paths: tuple[str, ...] = ("",)
    # R003 "hot-path lock" owners: a blocking call is only flagged while a
    # lock owned by one of these layers is held (the serving path); a lock
    # in, say, a converter script may legally wrap I/O.
    r003_paths: tuple[str, ...] = (
        "store/", "stream/", "obs/", "utils/", "web/", "parallel/",
    )
    # Names of rules to run; None = all registered.
    rules: tuple[str, ...] | None = None

    def in_scope(self, relpath: str, prefixes: tuple[str, ...]) -> bool:
        return any(relpath.startswith(p) for p in prefixes)


@dataclass
class Module:
    """One parsed file handed to every rule."""

    path: str          # path as reported in violations
    relpath: str       # package-relative path for rule scoping
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


# All four prongs share one waiver namespace — ``# tpulint:``,
# ``# tpurace:``, ``# tpuflow:``, and ``# tpusync:`` are interchangeable
# spellings of the same suppression (intent stays greppable per prong;
# W001 judges them all through this single tokenizer).
_WAIVER = re.compile(
    r"#\s*tpu(?:lint|race|flow|sync):\s*disable(?P<next>-next-line)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*|all)"
)


@dataclass
class WaiverComment:
    """One ``disable=`` comment: where it sits, which line it waives, and
    the rule ids it names (``{"all"}`` waives everything)."""

    line: int
    target: int
    rules: set[str]


def _comment_texts(lines: list[str]) -> list[tuple[int, str]]:
    """(line, text) of REAL ``#`` comments — tokenized, so waiver syntax
    quoted inside a docstring (e.g. this module's own documentation) is
    neither a live waiver nor a stale one."""
    import io
    import tokenize

    try:
        toks = tokenize.generate_tokens(io.StringIO(
            "\n".join(lines) + "\n").readline)
        return [
            (t.start[0], t.string) for t in toks
            if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparseable tail (mid-edit file): fall back to the raw scan
        return list(enumerate(lines, start=1))


def waiver_comments(lines: list[str]) -> list[WaiverComment]:
    out: list[WaiverComment] = []
    for i, text in _comment_texts(lines):
        for m in _WAIVER.finditer(text):
            rules = {r.strip() for r in m.group("rules").split(",")}
            target = i + 1 if m.group("next") else i
            out.append(WaiverComment(line=i, target=target, rules=rules))
    return out


def waiver_map(
    lines: list[str],
    comments: list[WaiverComment] | None = None,
) -> dict[int, set[str]]:
    """Line number → set of waived rule ids ({'all'} waives everything).
    Pass ``comments`` (one :func:`waiver_comments` call) to avoid
    re-tokenizing the file."""
    out: dict[int, set[str]] = {}
    for c in comments if comments is not None else waiver_comments(lines):
        out.setdefault(c.target, set()).update(c.rules)
    return out


def apply_waivers(
    violations: list[Violation],
    lines: list[str],
    comments: list[WaiverComment] | None = None,
) -> None:
    """Mark waived violations (same ``comments`` contract as
    :func:`waiver_map`)."""
    waivers = waiver_map(lines, comments)
    for v in violations:
        waived = waivers.get(v.line, set())
        if "all" in waived or v.rule in waived:
            v.waived = True


def stale_waiver_violations(
    lines: list[str],
    violations: list[Violation],
    judged_ids: set[str],
    path: str,
    comments: list[WaiverComment] | None = None,
) -> list[Violation]:
    """W001: waiver comments that suppress nothing.

    A comment is judged only when EVERY rule it names ran in this pass
    (``judged_ids``) — a lint pass cannot call a race-rule waiver stale,
    and vice versa. ``disable=all`` is never judged (its scope spans both
    prongs by construction)."""
    hit = {(v.line, v.rule) for v in violations}
    out: list[Violation] = []
    for c in comments if comments is not None else waiver_comments(lines):
        rules = c.rules - {"W001"}
        if not rules or "all" in rules or not rules <= judged_ids:
            continue
        if any((c.target, r) in hit for r in rules):
            continue
        where = "this line" if c.target == c.line else f"line {c.target}"
        out.append(Violation(
            rule="W001", path=path, line=c.line, col=0,
            message=(
                f"stale waiver: {', '.join(sorted(rules))} suppress(es) "
                f"nothing on {where} — delete the comment, or fix the rule "
                f"list (a dead waiver licenses a future regression)"),
        ))
    return out


def finalize_module_violations(
    mod: Module,
    violations: list[Violation],
    judged_ids: set[str],
    emit_w001: bool = True,
) -> list[Violation]:
    """The one waiver-finalization pass every prong shares: tokenize the
    module's waiver comments ONCE, append W001 stale-waiver findings
    (judged against ``judged_ids`` — the rules that actually ran), fill
    snippets, and mark waived violations. Returns the W001 findings it
    appended (already in ``violations``' final state for waiver marking).

    tpulint, tpurace, and tpuflow all route through here so the three
    prongs cannot drift on waiver syntax or staleness semantics."""
    comments = waiver_comments(mod.lines)
    stale: list[Violation] = []
    if emit_w001:
        stale = stale_waiver_violations(
            mod.lines, violations, judged_ids, mod.path, comments)
        violations.extend(stale)
    for v in violations:
        if not v.snippet:
            v.snippet = mod.snippet(v.line)
    apply_waivers(violations, mod.lines, comments)
    return stale


def package_relpath(path: str) -> str:
    """Path relative to the geomesa_tpu package root, for rule scoping.
    Files outside the package keep their basename-ish path (path-scoped
    rules then simply don't match unless the config says ``("",)``)."""
    norm = path.replace(os.sep, "/")
    marker = "geomesa_tpu/"
    idx = norm.rfind(marker)
    if idx >= 0:
        return norm[idx + len(marker):]
    return norm


def parse_module(
    source: str, path: str, relpath: str | None = None
) -> Module | Violation:
    """Parse one file into a :class:`Module`, or an E000 violation on a
    syntax error (shared by the per-module linter and the whole-program
    race analysis)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return Violation(
            rule="E000", path=path, line=e.lineno or 1, col=e.offset or 0,
            message=f"syntax error: {e.msg}",
        )
    return Module(
        path=path,
        relpath=relpath if relpath is not None else package_relpath(path),
        source=source,
        tree=tree,
        lines=source.splitlines(),
    )


def lint_source(
    source: str,
    path: str,
    config: LintConfig | None = None,
    relpath: str | None = None,
) -> list[Violation]:
    """Lint one file's source text. Returns ALL violations, with per-line
    waivers already applied (``waived=True``); baseline matching is a
    separate pass (:func:`apply_baseline`)."""
    from geomesa_tpu.analysis.rules import active_rules

    config = config or LintConfig()
    mod = parse_module(source, path, relpath)
    if isinstance(mod, Violation):
        return [mod]
    violations: list[Violation] = []
    rules = active_rules(config)
    for rule in rules:
        try:
            violations.extend(rule.check(mod, config))
        except Exception as e:
            raise AnalysisCrash(path, f"rule {rule.id}", e) from e
    # W001 judges only the single-module rules that actually ran here; the
    # whole-program race/flow rules (project=True) are judged by their own
    # drivers through the same finalize pass
    judged = {
        r.id for r in rules
        if not getattr(r, "project", False) and r.id != "W001"
    }
    finalize_module_violations(
        mod, violations, judged,
        emit_w001=config.rules is None or "W001" in config.rules)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations


_EXCLUDE_DIRS = {"__pycache__", ".git", ".pytest_cache"}


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            out.extend(
                os.path.join(root, f) for f in sorted(files)
                if f.endswith(".py")
            )
    return out


def lint_paths(paths: list[str], config: LintConfig | None = None) -> list[Violation]:
    violations: list[Violation] = []
    for fp in iter_py_files(paths):
        with open(fp, encoding="utf-8") as f:
            source = f.read()
        violations.extend(lint_source(source, fp, config))
    return violations


# -- baseline --------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str) -> Counter:
    """Baseline file → multiset of (rule, path, snippet) keys."""
    if not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} in {path}")
    return Counter(
        (e["rule"], e["path"], e["snippet"]) for e in data.get("entries", [])
    )


def write_baseline(path: str, violations: list[Violation]) -> None:
    """Persist the still-unsuppressed violations as the new baseline."""
    entries = [
        {"rule": v.rule, "path": _portable(v.path), "line": v.line,
         "snippet": v.snippet}
        for v in violations if not v.waived
    ]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": BASELINE_VERSION, "entries": entries}, f,
                  indent=1)
        f.write("\n")


def _portable(path: str) -> str:
    """Repo-relative forward-slash path so baselines diff cleanly across
    machines and operating systems."""
    norm = os.path.normpath(path).replace(os.sep, "/")
    idx = norm.rfind("geomesa_tpu/")
    return norm[idx:] if idx >= 0 else norm


def apply_baseline(violations: list[Violation], baseline: Counter) -> None:
    """Mark violations covered by the baseline multiset (in file order, so
    N baseline entries for one snippet cover the first N occurrences)."""
    remaining = Counter(baseline)
    for v in violations:
        if v.waived:
            continue
        key = (v.rule, _portable(v.path), v.snippet)
        if remaining[key] > 0:
            remaining[key] -= 1
            v.baselined = True
