"""tpulint CLI: ``python -m geomesa_tpu.analysis [paths...]``.

Four prongs share this entry point: the per-module lint rules
(default), ``--race`` (tpurace R001-R003), ``--flow`` (tpuflow
F001-F003 over the contract registry), and ``--sync`` (tpusync
S001-S004 dispatch/host-sync budgets; add ``--reconcile ledger.json``
to check the static bounds against a live-exported host-roundtrip
ledger); ``--all-prongs`` runs all four in one invocation and, with
``--format sarif``, emits one log with one run per prong.

Exit codes: 0 = clean against waivers+baseline, 1 = new violations,
2 = usage error, 3 = the analysis itself crashed (a crash must never
read as a clean run). Set ``GEOMESA_TPU_NO_JAX=1`` to keep the parent
package import JAX-free (scripts/lint.sh does) — linting itself never
imports JAX or any linted module.

``--changed-only`` reuses content-hash caches under ``.tpulint-cache/``
(unchanged files/trees skip re-analysis); ``--full`` forces a fresh run
while still refreshing the caches.
"""

from __future__ import annotations

import argparse
import os
import sys

from geomesa_tpu.analysis.core import (
    AnalysisCrash,
    LintConfig,
    apply_baseline,
    lint_paths,
    load_baseline,
    write_baseline,
)
from geomesa_tpu.analysis.report import (
    render_json,
    render_json_multi,
    render_text,
)

_RACE_IDS = frozenset({"R001", "R002", "R003"})
_FLOW_IDS = frozenset({"F001", "F002", "F003"})
_SYNC_IDS = frozenset({"S001", "S002", "S003", "S004"})


def default_target() -> str:
    """The geomesa_tpu package directory itself."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m geomesa_tpu.analysis",
        description="tpulint: JAX/Pallas-aware static analysis for "
                    "geomesa_tpu (rules J001-J004, C001, W001; "
                    "--race runs the tpurace rules R001-R003; --flow "
                    "runs the tpuflow contract rules F001-F003; --sync "
                    "runs the tpusync budget rules S001-S004).",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint "
                             "(default: the geomesa_tpu package)")
    parser.add_argument("--race", action="store_true",
                        help="run the whole-program tpurace concurrency "
                             "analysis (R001 guarded-field access, R002 "
                             "lock-order cycles, R003 blocking under a "
                             "hot-path lock) instead of the per-module "
                             "lint rules")
    parser.add_argument("--flow", action="store_true",
                        help="run the whole-program tpuflow contract "
                             "analysis (F001 epoch/invalidation coherence, "
                             "F002 shadow-plane taint, F003 two-band f64 "
                             "discipline)")
    parser.add_argument("--sync", action="store_true",
                        help="run the whole-program tpusync budget "
                             "analysis (S001 dispatch budget exceeded, "
                             "S002 host sync in a sync-free region, S003 "
                             "loop-carried dispatch, S004 unmodeled "
                             "jit boundary)")
    parser.add_argument("--reconcile", metavar="FILE",
                        help="with --sync: check declared dispatch "
                             "budgets against a live host-roundtrip "
                             "ledger snapshot (geomesa-tpu obs "
                             "ledger-export); a measured rate above the "
                             "static bound is an S001 finding")
    parser.add_argument("--all-prongs", action="store_true",
                        help="run lint + race + flow + sync in one "
                             "invocation (with --format sarif: one log, "
                             "one run per prong)")
    parser.add_argument("--guards", action="store_true",
                        help="with --race: print the inferred guard map "
                             "(which lock protects which field) and exit")
    parser.add_argument("--contracts", action="store_true",
                        help="with --flow: print the declared contract "
                             "inventory (cache surfaces, mutations, "
                             "feedback sinks, shadow roots/guards, device "
                             "bands) and exit")
    parser.add_argument("--baseline", metavar="FILE",
                        help="baseline JSON; matching violations don't fail")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite --baseline with current violations "
                             "and exit 0")
    parser.add_argument("--changed-only", action="store_true",
                        help="reuse .tpulint-cache/ content-hash caches; "
                             "unchanged files (lint) and unchanged trees "
                             "(race/flow) skip re-analysis")
    parser.add_argument("--full", action="store_true",
                        help="ignore caches and re-analyze everything "
                             "(still refreshes the caches)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="'json' and 'sarif' both emit SARIF 2.1.0")
    parser.add_argument("--rules", metavar="IDS",
                        help="comma-separated rule ids to run (default all)")
    parser.add_argument("--verbose", action="store_true",
                        help="also list waived/baselined violations")
    parser.add_argument("--list-rules", action="store_true")
    return parser


def _validate_rules(args, config: LintConfig) -> int | None:
    """Reject --rules selections that are vacuous in the chosen mode (a
    misconfigured CI gate must not read as clean forever)."""
    from geomesa_tpu.analysis.rules import all_rules

    unknown = set(config.rules) - set(all_rules())
    if unknown:
        print(f"tpulint: unknown rule ids: {sorted(unknown)}",
              file=sys.stderr)
        return 2
    requested = set(config.rules)
    if requested == {"W001"}:
        # W001 judges waivers against the OTHER rules that ran; alone
        # it can never emit anything — another vacuous-always-pass
        print("tpulint: --rules W001 alone judges nothing — select "
              "the rules whose waivers it should check too",
              file=sys.stderr)
        return 2
    if args.all_prongs:
        return None  # every registered rule runs in one prong or another
    if args.race and not requested & (_RACE_IDS | {"W001"}):
        print(f"tpulint: --race with --rules {args.rules} selects no "
              f"race rule (R001/R002/R003/W001)", file=sys.stderr)
        return 2
    if args.flow and not requested & (_FLOW_IDS | {"W001"}):
        print(f"tpulint: --flow with --rules {args.rules} selects no "
              f"flow rule (F001/F002/F003/W001)", file=sys.stderr)
        return 2
    if args.sync and not requested & (_SYNC_IDS | {"W001"}):
        print(f"tpulint: --sync with --rules {args.rules} selects no "
              f"sync rule (S001/S002/S003/S004/W001)", file=sys.stderr)
        return 2
    if not args.race and not args.flow and not args.sync:
        if requested <= _RACE_IDS:
            print(f"tpulint: {args.rules} are whole-program race rules — "
                  f"pass --race to run them", file=sys.stderr)
            return 2
        if requested <= _FLOW_IDS:
            print(f"tpulint: {args.rules} are whole-program flow rules — "
                  f"pass --flow to run them", file=sys.stderr)
            return 2
        if requested <= _SYNC_IDS:
            print(f"tpulint: {args.rules} are whole-program sync rules — "
                  f"pass --sync to run them", file=sys.stderr)
            return 2
        if requested <= (_RACE_IDS | _FLOW_IDS | _SYNC_IDS):
            print(f"tpulint: {args.rules} mixes whole-program prongs — "
                  f"pass --race/--flow/--sync (or --all-prongs)",
                  file=sys.stderr)
            return 2
    return None


def _analyze(args, config: LintConfig, paths: list[str]):
    """(prong_name, violations) pairs for the selected mode(s), routed
    through the incremental caches when --changed-only asked for them."""
    from geomesa_tpu.analysis.flow import analyze_flow_paths
    from geomesa_tpu.analysis.incremental import (
        analyze_whole_cached,
        lint_paths_cached,
    )
    from geomesa_tpu.analysis.race import analyze_race_paths
    from geomesa_tpu.analysis.sync import (
        analyze_sync_paths,
        load_ledger_export,
    )

    use_cache = args.changed_only and not args.full
    caching = args.changed_only or args.full

    def run_lint():
        if caching:
            return lint_paths_cached(paths, config, use_cache=use_cache)
        return lint_paths(paths, config)

    def run_whole(mode, fn):
        if caching:
            return analyze_whole_cached(mode, fn, paths, config,
                                        use_cache=use_cache)
        return fn(paths, config)

    def run_sync():
        if args.reconcile:
            # ledger contents are outside the tree fingerprint — a
            # cached result could mask a fresh divergence, so reconcile
            # always analyzes live
            entries = load_ledger_export(args.reconcile)
            return analyze_sync_paths(paths, config, reconcile=entries)
        return run_whole("sync", analyze_sync_paths)

    if args.all_prongs:
        return [
            ("tpulint", run_lint()),
            ("tpurace", run_whole("race", analyze_race_paths)),
            ("tpuflow", run_whole("flow", analyze_flow_paths)),
            ("tpusync", run_sync()),
        ]
    if args.race:
        return [("tpurace", run_whole("race", analyze_race_paths))]
    if args.flow:
        return [("tpuflow", run_whole("flow", analyze_flow_paths))]
    if args.sync:
        return [("tpusync", run_sync())]
    return [("tpulint", run_lint())]


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        from geomesa_tpu.analysis.rules import all_rules

        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}  {rule.title}")
        return 0

    config = LintConfig(
        rules=tuple(args.rules.split(",")) if args.rules else None,
    )
    paths = args.paths or [default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"tpulint: no such path: {p}", file=sys.stderr)
            return 2
    if config.rules is not None:
        rc = _validate_rules(args, config)
        if rc is not None:
            return rc

    if args.guards:
        if not args.race:
            print("tpulint: --guards requires --race (the guard map is a "
                  "tpurace view)", file=sys.stderr)
            return 2
        import json

        from geomesa_tpu.analysis.race import guard_map
        from geomesa_tpu.analysis.race.lockset import load_modules

        # (unknown --rules ids were already rejected above)
        modules, errors = load_modules(paths)
        for e in errors:
            print(f"tpulint: {e.path}:{e.line}: {e.message}",
                  file=sys.stderr)
        print(json.dumps(guard_map(modules, config), indent=1))
        # a parse failure silently shrinks the map: that is an incomplete
        # analysis, not a clean one — it must not exit 0
        return 1 if errors else 0

    if args.reconcile and not (args.sync or args.all_prongs):
        print("tpulint: --reconcile requires --sync (budgets are a "
              "tpusync view)", file=sys.stderr)
        return 2
    if args.reconcile and not os.path.exists(args.reconcile):
        print(f"tpulint: --reconcile: no such file: {args.reconcile}",
              file=sys.stderr)
        return 2

    if args.contracts:
        if not args.flow:
            print("tpulint: --contracts requires --flow (the inventory is "
                  "a tpuflow view)", file=sys.stderr)
            return 2
        import json

        from geomesa_tpu.analysis.flow import contract_inventory
        from geomesa_tpu.analysis.race.lockset import load_modules

        modules, errors = load_modules(paths)
        for e in errors:
            print(f"tpulint: {e.path}:{e.line}: {e.message}",
                  file=sys.stderr)
        print(json.dumps(contract_inventory(modules, config), indent=1))
        return 1 if errors else 0

    try:
        prong_runs = _analyze(args, config, paths)
    except ValueError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2
    except AnalysisCrash as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 3
    except Exception as e:
        # any other mid-analysis crash (an ImportError under
        # GEOMESA_TPU_NO_JAX=1, a bug in a whole-program pass) must exit
        # loudly — never as a clean empty report
        print(f"tpulint: internal error during analysis: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 3

    violations = [v for _, vs in prong_runs for v in vs]

    if args.write_baseline:
        if not args.baseline:
            print("tpulint: --write-baseline requires --baseline",
                  file=sys.stderr)
            return 2
        write_baseline(args.baseline, violations)
        kept = sum(1 for v in violations if not v.waived)
        print(f"tpulint: wrote {kept} entr{'y' if kept == 1 else 'ies'} to "
              f"{args.baseline}")
        return 0

    if args.baseline:
        baseline = load_baseline(args.baseline)
        for _, vs in prong_runs:
            apply_baseline(vs, baseline)

    if args.format in ("json", "sarif"):
        if len(prong_runs) > 1:
            print(render_json_multi(prong_runs))
        else:
            print(render_json(violations))
    else:
        print(render_text(violations, verbose=args.verbose))
    return 0 if all(v.suppressed for v in violations) else 1


if __name__ == "__main__":
    sys.exit(main())
