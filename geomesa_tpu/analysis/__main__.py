"""tpulint CLI: ``python -m geomesa_tpu.analysis [paths...]``.

Exit codes: 0 = clean against waivers+baseline, 1 = new violations,
2 = usage error. Set ``GEOMESA_TPU_NO_JAX=1`` to keep the parent
package import JAX-free (scripts/lint.sh does) — linting itself never
imports JAX or any linted module.
"""

from __future__ import annotations

import argparse
import os
import sys

from geomesa_tpu.analysis.core import (
    LintConfig,
    apply_baseline,
    lint_paths,
    load_baseline,
    write_baseline,
)
from geomesa_tpu.analysis.report import render_json, render_text


def default_target() -> str:
    """The geomesa_tpu package directory itself."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m geomesa_tpu.analysis",
        description="tpulint: JAX/Pallas-aware static analysis for "
                    "geomesa_tpu (rules J001-J004, C001, W001; "
                    "--race runs the tpurace rules R001-R003).",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint "
                             "(default: the geomesa_tpu package)")
    parser.add_argument("--race", action="store_true",
                        help="run the whole-program tpurace concurrency "
                             "analysis (R001 guarded-field access, R002 "
                             "lock-order cycles, R003 blocking under a "
                             "hot-path lock) instead of the per-module "
                             "lint rules")
    parser.add_argument("--guards", action="store_true",
                        help="with --race: print the inferred guard map "
                             "(which lock protects which field) and exit")
    parser.add_argument("--baseline", metavar="FILE",
                        help="baseline JSON; matching violations don't fail")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite --baseline with current violations "
                             "and exit 0")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="'json' and 'sarif' both emit SARIF 2.1.0")
    parser.add_argument("--rules", metavar="IDS",
                        help="comma-separated rule ids to run (default all)")
    parser.add_argument("--verbose", action="store_true",
                        help="also list waived/baselined violations")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        from geomesa_tpu.analysis.rules import all_rules

        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}  {rule.title}")
        return 0

    config = LintConfig(
        rules=tuple(args.rules.split(",")) if args.rules else None,
    )
    paths = args.paths or [default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"tpulint: no such path: {p}", file=sys.stderr)
            return 2
    if config.rules is not None:
        from geomesa_tpu.analysis.rules import all_rules as _all_rules

        unknown = set(config.rules) - set(_all_rules())
        if unknown:
            print(f"tpulint: unknown rule ids: {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        # a --rules set that selects NOTHING in the chosen mode must be a
        # usage error, not a vacuous exit 0 (a misconfigured CI gate would
        # read as clean forever)
        race_ids = {"R001", "R002", "R003"}
        requested = set(config.rules)
        if requested == {"W001"}:
            # W001 judges waivers against the OTHER rules that ran; alone
            # it can never emit anything — another vacuous-always-pass
            print("tpulint: --rules W001 alone judges nothing — select "
                  "the rules whose waivers it should check too",
                  file=sys.stderr)
            return 2
        if args.race and not requested & (race_ids | {"W001"}):
            print(f"tpulint: --race with --rules {args.rules} selects no "
                  f"race rule (R001/R002/R003/W001)", file=sys.stderr)
            return 2
        if not args.race and requested <= race_ids:
            print(f"tpulint: {args.rules} are whole-program race rules — "
                  f"pass --race to run them", file=sys.stderr)
            return 2

    if args.guards:
        if not args.race:
            print("tpulint: --guards requires --race (the guard map is a "
                  "tpurace view)", file=sys.stderr)
            return 2
        import json

        from geomesa_tpu.analysis.race import guard_map
        from geomesa_tpu.analysis.race.lockset import load_modules

        # (unknown --rules ids were already rejected above)
        modules, errors = load_modules(paths)
        for e in errors:  # a skipped module would silently shrink the map
            print(f"tpulint: {e.path}:{e.line}: {e.message}",
                  file=sys.stderr)
        print(json.dumps(guard_map(modules, config), indent=1))
        return 0
    try:
        if args.race:
            from geomesa_tpu.analysis.race import analyze_race_paths

            violations = analyze_race_paths(paths, config)
        else:
            violations = lint_paths(paths, config)
    except ValueError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        if not args.baseline:
            print("tpulint: --write-baseline requires --baseline",
                  file=sys.stderr)
            return 2
        write_baseline(args.baseline, violations)
        kept = sum(1 for v in violations if not v.waived)
        print(f"tpulint: wrote {kept} entr{'y' if kept == 1 else 'ies'} to "
              f"{args.baseline}")
        return 0

    if args.baseline:
        apply_baseline(violations, load_baseline(args.baseline))

    if args.format in ("json", "sarif"):
        print(render_json(violations))
    else:
        print(render_text(violations, verbose=args.verbose))
    return 0 if all(v.suppressed for v in violations) else 1


if __name__ == "__main__":
    sys.exit(main())
