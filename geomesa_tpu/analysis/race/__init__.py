"""tpurace — concurrency checking for the serving path, two prongs.

Static (:mod:`~geomesa_tpu.analysis.race.lockset`): an inter-procedural
lockset analysis over the whole package. It infers the guard map (which
lock protects which fields, from majority-guarded writes) and flags

- R001 — a guarded field written outside its inferred guard lock,
- R002 — lock-order inversions (cycles in the static lock acquisition
  graph, built across call chains and modules),
- R003 — blocking calls (file/socket I/O, ``jax`` dispatch,
  ``time.sleep``) made while holding a hot-path lock.

Dynamic (:mod:`~geomesa_tpu.analysis.race.sanitizer`): with
``GEOMESA_TPU_SANITIZE=1`` the test harness monkey-patches
``threading.Lock``/``RLock`` creation to record per-thread lock stacks
into a global lock-order graph — an Eraser-style detector that fails the
run when real execution acquires locks in cycle-forming orders, even if
no deadlock happened on this schedule.

Both prongs share tpulint's rule registry, waiver syntax
(``# tpurace: disable=R001``), baseline file, and the
``python -m geomesa_tpu.analysis --race`` CLI; like the rest of the
analysis package they import neither JAX nor any sibling geomesa_tpu
subsystem. See docs/concurrency.md.
"""

from geomesa_tpu.analysis.race.lockset import (
    RACE_RULE_IDS,
    analyze_modules,
    analyze_race_paths,
    guard_map,
)

__all__ = [
    "RACE_RULE_IDS", "analyze_modules", "analyze_race_paths", "guard_map",
]
