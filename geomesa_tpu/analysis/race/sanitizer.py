"""tpurace dynamic prong: an Eraser-style runtime lock-order sanitizer.

With ``GEOMESA_TPU_SANITIZE=1`` (see tests/conftest.py) :func:`install`
monkey-patches ``threading.Lock`` and ``threading.RLock`` so every lock
CREATED BY REPO CODE is wrapped in a recorder. Each acquisition appends
to a per-thread stack and, for every lock already held, inserts an edge
``held-site → acquired-site`` into one global lock-order graph. An edge
that closes a cycle is recorded as a violation — the happened-in-wrong-
order signal: the schedule that actually deadlocks never needs to run,
two runs (or two threads) acquiring in opposite orders is enough.

Design constraints, in order:

- **Zero behavior change.** Wrappers delegate ``acquire``/``release``
  to a real ``_thread`` lock; bookkeeping happens only AFTER a
  successful acquire and never raises into application code. Cycles are
  collected, not thrown — the pytest session fixture (and
  ``scripts/lint.sh``) fails the run afterwards.
- **Bounded overhead.** Lock identity is the CREATION SITE
  (``file:line``), not the instance — the graph is as small as the
  code, and a hit on an existing edge is one dict lookup. Stacks are
  captured only when a NEW edge first appears.
- **Scope: the repo's locks.** The factory inspects its caller and
  returns an unwrapped lock for foreign frames (jax, stdlib — including
  ``threading.py`` itself, so ``Event``/``Condition`` internals keep
  their native primitives and their ``_release_save`` dance never
  desyncs our per-thread stacks).

Reentrant acquisition of the SAME lock object records nothing (RLock
semantics). Site-keyed identity also means nesting two DIFFERENT
instances of one lock role (same creation site) records no edge:
instance-order hazards within a single role are out of scope here —
catching them would need per-instance identity and an address-order
convention, at per-instance graph cost. The static prong's R002 has the
same granularity (one node per ``Class.attr``), so the two prongs agree
on what a "lock" is.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback

__all__ = [
    "install", "uninstall", "installed", "enabled_by_env",
    "cycle_report", "edges", "reset", "snapshot", "restore",
    "LockOrderError", "check",
]

_REPO_MARKERS = ("geomesa_tpu", "tests")

_real_lock = threading.Lock
_real_rlock = threading.RLock

# sanitizer-internal state guarded by a REAL (unwrapped) lock
_state_lock = _real_lock()
_graph: dict[str, dict[str, dict]] = {}   # site A -> site B -> edge info
_cycles: list[dict] = []
_installed = False
_tls = threading.local()


class LockOrderError(AssertionError):
    """Raised by :func:`check` when the run recorded lock-order cycles."""


def enabled_by_env() -> bool:
    return os.environ.get("GEOMESA_TPU_SANITIZE", "") not in ("", "0")


def _caller_site(depth: int = 2) -> str | None:
    """``file:line`` of the frame ``depth`` levels up, or None for frames
    outside the repo (foreign locks stay unwrapped)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover — interpreter startup edges
        return None
    fn = frame.f_code.co_filename.replace(os.sep, "/")
    parts = fn.split("/")
    for marker in _REPO_MARKERS:
        if marker in parts:
            short = "/".join(parts[parts.index(marker):])
            return f"{short}:{frame.f_lineno}"
    return None


def _held_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _record_acquire(lock_id: int, site: str) -> None:
    stack = _held_stack()
    for oid, _ in stack:
        if oid == lock_id:  # RLock re-entry: not an ordering event
            stack.append((lock_id, None))
            return
    new_edges = [
        (held_site, site) for _, held_site in stack
        if held_site is not None and held_site != site
        and site not in _graph.get(held_site, ())
    ]
    if new_edges:
        # capture context once per new edge, then check for cycles
        where = "".join(traceback.format_stack(sys._getframe(2), limit=4))
        with _state_lock:
            for a, b in new_edges:
                dst = _graph.setdefault(a, {})
                if b in dst:
                    continue
                dst[b] = {
                    "thread": threading.current_thread().name,
                    "stack": where,
                }
                cyc = _find_cycle(b, a)
                if cyc is not None:
                    _cycles.append({
                        "edge": (a, b),
                        "cycle": [a, b] + cyc[1:],
                        "thread": threading.current_thread().name,
                        "stack": where,
                    })
    stack.append((lock_id, site))


def _record_release(lock_id: int) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == lock_id:
            del stack[i]
            return


def _record_release_all(lock_id: int) -> None:
    """Drop EVERY stack entry for a lock — the Condition._release_save
    path strips all RLock recursion levels at once."""
    stack = _held_stack()
    stack[:] = [e for e in stack if e[0] != lock_id]


def _find_cycle(src: str, dst: str) -> list[str] | None:
    """Path src → dst in the graph (call with _state_lock held); with the
    new edge dst→src already inserted this closes a cycle."""
    seen = {src}
    work = [(src, [src])]
    while work:
        node, path = work.pop()
        for nxt in _graph.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                work.append((nxt, path + [nxt]))
    return None


class _SanitizedLock:
    """Recorder wrapping a real lock. Delegation is explicit (no
    ``__getattr__`` magic on the hot path)."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _record_acquire(id(self), self._site)
            except Exception:  # noqa: BLE001 — never break the app's locking
                pass
        return ok

    def release(self):
        self._inner.release()
        try:
            _record_release(id(self))
        except Exception:  # noqa: BLE001
            pass

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __repr__(self):  # pragma: no cover — debug aid
        return f"<SanitizedLock {self._site} wrapping {self._inner!r}>"


class _SanitizedRLock(_SanitizedLock):
    __slots__ = ()

    # acquire/release/__enter__/__exit__ inherit from _SanitizedLock
    # (re-entry is handled generically in _record_acquire/_record_release).

    # Condition() interop: delegate the RLock internals it probes for.
    # _release_save/_acquire_restore bracket a Condition.wait — the held
    # stack must drop the lock across the wait (all recursion levels at
    # once) and RE-RECORD it on wake, or every post-wait nested
    # acquisition would be an invisible (or phantom) ordering edge.
    def _is_owned(self):
        return self._inner._is_owned()

    def _acquire_restore(self, state):
        out = self._inner._acquire_restore(state)
        try:
            _record_acquire(id(self), self._site)
        except Exception:  # noqa: BLE001 — never break the app's locking
            pass
        return out

    def _release_save(self):
        try:
            _record_release_all(id(self))
        except Exception:  # noqa: BLE001
            pass
        return self._inner._release_save()

    def locked(self):  # RLock in 3.12+; probe defensively
        probe = getattr(self._inner, "locked", None)
        return probe() if probe is not None else False


def _lock_factory():
    site = _caller_site(depth=2)
    inner = _real_lock()
    if site is None:
        return inner
    return _SanitizedLock(inner, site)


def _rlock_factory():
    site = _caller_site(depth=2)
    inner = _real_rlock()
    if site is None:
        return inner
    return _SanitizedRLock(inner, site)


def install() -> None:
    """Patch ``threading.Lock``/``RLock``. Idempotent. Only locks created
    AFTER this call are tracked — the pytest plugin installs before any
    geomesa_tpu module is imported, so the serving path's locks all land
    in the graph."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _installed = False


def installed() -> bool:
    return _installed


def edges() -> dict[str, list[str]]:
    """The observed lock-order graph (site → successor sites)."""
    with _state_lock:
        return {a: sorted(bs) for a, bs in _graph.items()}


def cycle_report() -> list[dict]:
    """All lock-order cycles observed so far (empty = clean run)."""
    with _state_lock:
        return list(_cycles)


def reset() -> None:
    """Drop the graph and cycle list (test isolation)."""
    with _state_lock:
        _graph.clear()
        _cycles.clear()


def snapshot() -> tuple:
    """Copy of the current graph + cycle list — tests that DELIBERATELY
    create cycles save this first and :func:`restore` it after, so they
    never mask (or fabricate) findings for the session-end gate."""
    with _state_lock:
        return ({a: dict(bs) for a, bs in _graph.items()}, list(_cycles))


def restore(snap: tuple) -> None:
    graph, cycles = snap
    with _state_lock:
        _graph.clear()
        _graph.update({a: dict(bs) for a, bs in graph.items()})
        _cycles[:] = cycles


def check() -> None:
    """Raise :class:`LockOrderError` if any cycle was recorded — the
    fail-the-run hook for fixtures and scripts."""
    report = cycle_report()
    if not report:
        return
    lines = [f"{len(report)} lock-order cycle(s) detected:"]
    for c in report:
        lines.append("  cycle: " + " -> ".join(c["cycle"]))
        lines.append(f"  closing thread: {c['thread']}")
        lines.append("  at:\n" + c["stack"])
    raise LockOrderError("\n".join(lines))
