"""tpurace static prong: whole-program lockset & lock-order analysis.

Unlike the per-module tpulint rules, this pass parses EVERY module first
and reasons across them, because the bug classes it hunts are invisible
to any single file:

- a field guarded in ``store/datastore.py`` but assigned bare from a
  helper in another method (or another class's method holding a typed
  reference to the instance),
- a lock-order inversion where ``stream/journal.py`` takes A then calls
  into code that takes B while ``store/datastore.py`` nests B then A.

The model is deliberately lightweight — pure ``ast``, no imports of the
analyzed code — with just enough type inference to resolve the repo's
idioms:

- lock discovery: ``self.x = threading.Lock()/RLock()/Condition()``
  inside methods, and ``NAME = threading.Lock()`` at module scope. Lock
  identity is ``Class.attr`` / ``module:NAME`` — one node per *site*,
  not per instance (the order DISCIPLINE is per lock role).
- object typing: ``self.x = ClassName(...)`` (anywhere in the
  constructor expression), ``self.x: ClassName`` / ``x: ClassName``
  annotations, ``dict[str, ClassName]``-style container annotations for
  subscripted reads, and method return annotations
  (``def _state(...) -> _TypeState``). That is what lets
  ``st = self._state(name); st.table = ...`` attribute writes to
  ``_TypeState.table`` and ``with st.lock:`` acquisitions to
  ``_TypeState.lock``.
- held-lock tracking: a per-function walk maintains the lock stack from
  ``with`` statements; entry-held sets propagate inter-procedurally —
  ``*_locked`` methods are caller-holds-lock by repo convention, and a
  private function's entry set is the intersection of the held sets at
  its observed call sites (fixpoint).

R001 infers the guard map by majority: a field with ≥ 2 tracked writes,
more than half of them under one lock, is guarded by that lock, and
every write outside it is flagged. Reads are NOT flagged (Eraser-style
read checking is future work — the write-side race is the lost-update
class that corrupts state). R002 builds the global acquisition graph
(direct nestings plus, through the call graph, locks a callee may
acquire while the caller holds one) and reports each strongly-connected
component as one violation. R003 flags DIRECT blocking calls under a
hot-path lock; it does not chase calls, so a blocking helper invoked
under a lock needs the helper inlined or the call site reviewed (the
dynamic sanitizer covers what static depth misses).

Heuristics, not proofs: the expected answer for an intentional site is
a ``# tpurace: disable=Rxxx`` waiver with a one-line justification.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from dataclasses import dataclass, field

from geomesa_tpu.analysis.astutils import ImportMap
from geomesa_tpu.analysis.core import (
    LintConfig,
    Module,
    Violation,
    iter_py_files,
    parse_module,
)

__all__ = [
    "RACE_RULE_IDS", "analyze_modules", "analyze_race_paths", "guard_map",
    "load_modules", "build_flow_graph",
]

RACE_RULE_IDS = ("R001", "R002", "R003")

LOCK_FACTORIES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
})
MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "appendleft", "popleft",
})
# construction is single-threaded; writes there never need the lock
_CTOR_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

# Canonical dotted names of calls that block the calling thread.
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "open", "io.open", "os.open", "os.fsync", "os.fdatasync",
    "socket.create_connection", "socket.getaddrinfo",
    "urllib.request.urlopen",
    "subprocess.run", "subprocess.Popen", "subprocess.check_output",
    "subprocess.check_call",
    "fcntl.flock", "fcntl.lockf",
})
# Method names that block regardless of receiver type. ``join`` is only
# blocking for thread-likes — disambiguated from str.join at the call
# site (str.join always passes the iterable positionally).
BLOCKING_METHODS = frozenset({
    "block_until_ready", "sendall", "recv", "connect", "wait",
})


def _module_id(relpath: str) -> str:
    """``stream/journal.py`` → ``stream.journal`` (lock-id namespace)."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    return p.replace("/", ".")


@dataclass
class _ClassInfo:
    name: str
    module: Module
    node: ast.ClassDef
    lock_attrs: set[str] = field(default_factory=set)
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    attr_class: dict[str, str] = field(default_factory=dict)
    # attrs annotated as containers of a class: subscripting yields it
    attr_elem_class: dict[str, str] = field(default_factory=dict)
    method_returns: dict[str, str] = field(default_factory=dict)

    def lock_ids(self) -> set[str]:
        return {f"{self.name}.{a}" for a in self.lock_attrs}


@dataclass
class _Acquire:
    lock: str
    line: int
    held: tuple[str, ...]


@dataclass
class _Write:
    owner: str  # class name
    attr: str
    line: int
    col: int
    held: tuple[str, ...]
    what: str
    module: Module
    method: str  # enclosing function name (ctor writes are exempt)


@dataclass
class _CallSite:
    callee: tuple  # ("method", cls, name) | ("fn", module_id, name)
    line: int
    held: tuple[str, ...]


@dataclass
class _Blocking:
    what: str
    line: int
    col: int
    held: tuple[str, ...]
    module: Module


@dataclass
class _FnSummary:
    key: tuple
    name: str
    cls: _ClassInfo | None
    module: Module
    acquires: list[_Acquire] = field(default_factory=list)
    writes: list[_Write] = field(default_factory=list)
    calls: list[_CallSite] = field(default_factory=list)
    blocking: list[_Blocking] = field(default_factory=list)


class _Project:
    """Everything discovered in pass 1: classes, locks, typings."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        self.imports: dict[str, ImportMap] = {}
        self.classes: dict[str, _ClassInfo] = {}
        self.ambiguous: set[str] = set()
        # module_id -> {name: lockid} for module-scope locks
        self.module_locks: dict[str, dict[str, str]] = {}
        # lockid -> owning module relpath (R003 hot-path scoping)
        self.lock_home: dict[str, str] = {}
        # module_id -> top-level function defs
        self.functions: dict[str, dict[str, ast.FunctionDef]] = {}
        # (module_id, fn) -> class its return annotation resolves to —
        # lets cross-module scans type ``devmon.costs().forget(...)``
        # through the ``def costs() -> CostTable`` accessor idiom
        self.fn_returns: dict[tuple[str, str], str] = {}

        for mod in modules:
            imports = ImportMap(mod.tree)
            self.imports[mod.relpath] = imports
            mid = _module_id(mod.relpath)
            self.module_locks[mid] = {}
            self.functions[mid] = {}
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and _is_lock_call(
                    node.value, imports
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            lid = f"{mid}:{t.id}"
                            self.module_locks[mid][t.id] = lid
                            self.lock_home[lid] = mod.relpath
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.functions[mid][node.name] = node
                elif isinstance(node, ast.ClassDef):
                    self._index_class(mod, imports, node)

        # resolve attr/return annotations to known classes (second pass —
        # all class names must exist first)
        for info in list(self.classes.values()):
            self._type_class(info)
        self._fn_homes: dict[str, list[str]] = defaultdict(list)
        for mod in modules:
            mid = _module_id(mod.relpath)
            imports = self.imports[mod.relpath]
            for name, fn in self.functions[mid].items():
                self._fn_homes[name].append(mid)
                ret = self._ann_class(fn.returns, imports)
                if ret:
                    self.fn_returns[(mid, name)] = ret

    def local_fn_key(self, dotted: str | None) -> tuple | None:
        """Canonical ``geomesa_tpu.<mod>.<fn>`` path → ``("fn", mid, fn)``
        when the target is a known top-level function of an analyzed
        module (the cross-module half of the call graph). Fixture trees
        analyzed from outside the package have path-derived module ids, so
        an import path is also matched as a module-id suffix."""
        if dotted is None or "." not in dotted:
            return None
        head, _, name = dotted.rpartition(".")
        if head.startswith("geomesa_tpu."):
            head = head[len("geomesa_tpu."):]
        if name in self.functions.get(head, {}):
            return ("fn", head, name)
        for mid in self._fn_homes.get(name, ()):
            if mid.endswith("." + head) or head.endswith("." + mid):
                return ("fn", mid, name)
        return None

    # -- pass 1a: class inventory -------------------------------------------
    def _index_class(self, mod, imports, node: ast.ClassDef) -> None:
        info = _ClassInfo(name=node.name, module=mod, node=node)
        for m in node.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[m.name] = m
                for sub in ast.walk(m):
                    if isinstance(sub, ast.Assign) and _is_lock_call(
                        sub.value, imports
                    ):
                        for t in sub.targets:
                            attr = _self_attr_of(t, _self_name(m))
                            if attr is not None:
                                info.lock_attrs.add(attr)
        if node.name in self.classes:
            # duplicate top-level name (the repo has e.g. two Histograms):
            # BARE-name typing becomes unresolvable, but the class itself
            # must still be analyzed — re-key it under a module-qualified
            # name so its methods, locks, and writes stay in the pass and
            # its lock ids never conflate with the namesake's
            self.ambiguous.add(node.name)
            info.name = f"{_module_id(mod.relpath)}.{node.name}"
            if info.name in self.classes:  # same name twice in one module
                return
        self.classes[info.name] = info
        for lid in info.lock_ids():
            self.lock_home[lid] = mod.relpath

    # -- pass 1b: light type inference --------------------------------------
    def resolve_class(self, dotted: str | None) -> str | None:
        """Canonical dotted path (or bare name) → known class name."""
        if dotted is None:
            return None
        name = dotted.rsplit(".", 1)[-1]
        if name in self.classes and name not in self.ambiguous:
            return name
        return None

    def _ann_class(self, ann: ast.AST | None, imports) -> str | None:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        # X | None unions: take the first resolvable arm
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._ann_class(ann.left, imports)
                    or self._ann_class(ann.right, imports))
        return self.resolve_class(imports.resolve(ann))

    def _ann_elem_class(self, ann: ast.AST | None, imports) -> str | None:
        """``dict[str, C]`` / ``list[C]`` → C (what subscripting yields)."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if not isinstance(ann, ast.Subscript):
            return None
        sl = ann.slice
        if isinstance(sl, ast.Tuple) and sl.elts:
            sl = sl.elts[-1]  # dict value type
        return self._ann_class(sl, imports)

    def _type_class(self, info: _ClassInfo) -> None:
        imports = self.imports[info.module.relpath]
        for m in info.methods.values():
            ret = self._ann_class(m.returns, imports)
            if ret:
                info.method_returns[m.name] = ret
            sn = _self_name(m)
            # annotated params type the attrs they're stored into
            # (``def __init__(self, reg: Registry): self.reg = reg``)
            panns = {
                a.arg: c
                for a in (m.args.posonlyargs + m.args.args
                          + m.args.kwonlyargs)
                if (c := self._ann_class(a.annotation, imports))
            }
            for sub in ast.walk(m):
                if isinstance(sub, ast.AnnAssign):
                    attr = _self_attr_of(sub.target, sn)
                    if attr is None:
                        continue
                    c = self._ann_class(sub.annotation, imports)
                    if c:
                        info.attr_class[attr] = c
                    e = self._ann_elem_class(sub.annotation, imports)
                    if e:
                        info.attr_elem_class[attr] = e
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        attr = _self_attr_of(t, sn)
                        if attr is None or attr in info.attr_class:
                            continue
                        c = self._ctor_class(sub.value, imports)
                        if c is None and isinstance(sub.value, ast.Name):
                            c = panns.get(sub.value.id)
                        if c:
                            info.attr_class[attr] = c

    def _ctor_class(self, expr: ast.AST, imports) -> str | None:
        """Class constructed anywhere in ``expr`` (covers the
        ``x if x is not None else DataStore(...)`` idiom)."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                c = self.resolve_class(imports.resolve(node.func))
                if c:
                    return c
        return None


def _is_lock_call(expr: ast.AST, imports: ImportMap) -> bool:
    return (isinstance(expr, ast.Call)
            and imports.resolve(expr.func) in LOCK_FACTORIES)


def _self_name(method: ast.FunctionDef) -> str:
    args = method.args.posonlyargs + method.args.args
    return args[0].arg if args else "self"


def _self_attr_of(node: ast.AST, self_name: str) -> str | None:
    """``self.X`` (possibly through subscripts) → ``X``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == self_name
    ):
        return node.attr
    return None


# ---------------------------------------------------------------------------
# pass 2: per-function scan with held-lock tracking
# ---------------------------------------------------------------------------

class _FnScan(ast.NodeVisitor):
    def __init__(self, project: _Project, summary: _FnSummary,
                 fn: ast.FunctionDef, *, cross_module: bool = False):
        self.p = project
        self.s = summary
        self.mod = summary.module
        self.imports = project.imports[self.mod.relpath]
        self.mid = _module_id(self.mod.relpath)
        self.cls = summary.cls
        self.self_name = _self_name(fn) if self.cls is not None else None
        # opt-in (flow prong only): resolve imported geomesa_tpu functions
        # to call-graph edges and type accessor-call returns. Kept OFF for
        # the race prong so its edge set — and therefore R001-R003
        # findings and the committed baseline — stays byte-identical.
        self.cross_module = cross_module
        self.held: list[str] = []
        self.var_class: dict[str, str] = {}
        # annotated params type locals too
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            c = project._ann_class(a.annotation, self.imports)
            if c:
                self.var_class[a.arg] = c

    # -- typing -------------------------------------------------------------
    def _expr_class(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Name):
            if self.self_name is not None and expr.id == self.self_name:
                return self.cls.name
            return self.var_class.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_class(expr.value)
            if base is not None and base in self.p.classes:
                return self.p.classes[base].attr_class.get(expr.attr)
            return None
        if isinstance(expr, ast.Subscript):
            base = expr.value
            if isinstance(base, ast.Attribute):
                owner = self._expr_class(base.value)
                if owner is not None and owner in self.p.classes:
                    return self.p.classes[owner].attr_elem_class.get(base.attr)
            return None
        if isinstance(expr, ast.Call):
            c = self.p.resolve_class(self.imports.resolve(expr.func))
            if c:
                return c
            f = expr.func
            if isinstance(f, ast.Attribute):
                recv = self._expr_class(f.value)
                if recv is not None and recv in self.p.classes:
                    return self.p.classes[recv].method_returns.get(f.attr)
            if self.cross_module:
                # module-level accessor returns: ``devmon.costs()`` types
                # as CostTable through ``def costs() -> CostTable``
                key = self.p.local_fn_key(self.imports.resolve(f))
                if key is None and isinstance(f, ast.Name):
                    if f.id in self.p.functions.get(self.mid, {}):
                        key = ("fn", self.mid, f.id)
                if key is not None:
                    return self.p.fn_returns.get((key[1], key[2]))
            return None
        if isinstance(expr, ast.IfExp):
            return self._expr_class(expr.body) or self._expr_class(expr.orelse)
        return None

    def _lock_id(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Name):
            return self.p.module_locks.get(self.mid, {}).get(expr.id)
        if isinstance(expr, ast.Attribute):
            owner = self._expr_class(expr.value)
            if owner is not None and owner in self.p.classes:
                if expr.attr in self.p.classes[owner].lock_attrs:
                    return f"{owner}.{expr.attr}"
        return None

    def _owner_attr(self, node: ast.AST) -> tuple[str, str] | None:
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            owner = self._expr_class(node.value)
            if owner is not None:
                return (owner, node.attr)
        return None

    # -- visiting -----------------------------------------------------------
    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                if lid not in self.held:  # RLock re-entry is not an edge
                    self.s.acquires.append(_Acquire(
                        lock=lid, line=node.lineno, held=tuple(self.held)))
                acquired.append(lid)
                self.held.append(lid)
            else:
                # ``with open(...)``: the context expression itself is a
                # call site (blocking detection must see it)
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def _record_write(self, target: ast.AST, node: ast.AST, what: str):
        oa = self._owner_attr(target)
        if oa is None:
            return
        owner, attr = oa
        if owner in self.p.classes and attr in self.p.classes[owner].lock_attrs:
            return  # swapping the lock object itself is not a field write
        self.s.writes.append(_Write(
            owner=owner, attr=attr, line=node.lineno, col=node.col_offset,
            held=tuple(self.held), what=what, module=self.mod,
            method=self.s.name))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Name):
                c = self._expr_class(node.value)
                if c:
                    self.var_class[t.id] = c
            for el in _flat_targets(t):
                self._record_write(el, node, "assignment")
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is None:
            return
        if isinstance(node.target, ast.Name):
            c = (self.p._ann_class(node.annotation, self.imports)
                 or self._expr_class(node.value))
            if c:
                self.var_class[node.target.id] = c
        self._record_write(node.target, node, "assignment")
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record_write(node.target, node, "augmented assignment")
        self.visit(node.value)

    def visit_Call(self, node: ast.Call):
        f = node.func
        dotted = self.imports.resolve(f)
        # blocking-call detection (direct sites only)
        blocked = None
        if dotted in BLOCKING_CALLS:
            blocked = dotted
        elif dotted is not None and self.imports.is_device_namespace(dotted):
            blocked = f"{dotted} (jax dispatch)"
        elif isinstance(f, ast.Attribute):
            if f.attr in BLOCKING_METHODS:
                blocked = f".{f.attr}()"
            elif f.attr == "join" and (
                not node.args or any(k.arg == "timeout" for k in node.keywords)
            ):
                blocked = ".join()"  # thread join; str.join passes args
        if blocked is not None and self.held:
            self.s.blocking.append(_Blocking(
                what=blocked, line=node.lineno, col=node.col_offset,
                held=tuple(self.held), module=self.mod))
        # mutating container methods are writes to the container attr
        if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
            oa = self._owner_attr(f.value)
            if oa is not None:
                owner, attr = oa
                if not (owner in self.p.classes
                        and attr in self.p.classes[owner].lock_attrs):
                    self.s.writes.append(_Write(
                        owner=owner, attr=attr, line=node.lineno,
                        col=node.col_offset, held=tuple(self.held),
                        what=f".{f.attr}()", module=self.mod,
                        method=self.s.name))
        # call-graph edges
        callee = self._callee_key(f)
        if callee is not None:
            self.s.calls.append(_CallSite(
                callee=callee, line=node.lineno, held=tuple(self.held)))
        self.generic_visit(node)

    def _callee_key(self, f: ast.AST) -> tuple | None:
        if isinstance(f, ast.Name):
            if f.id in self.p.functions.get(self.mid, {}):
                return ("fn", self.mid, f.id)
            if self.cross_module:
                return self.p.local_fn_key(self.imports.resolve(f))
            return None
        if isinstance(f, ast.Attribute):
            recv = self._expr_class(f.value)
            if recv is not None and recv in self.p.classes:
                if f.attr in self.p.classes[recv].methods:
                    return ("method", recv, f.attr)
            if self.cross_module:
                # ``_traj_state.invalidate(...)`` through a module alias
                return self.p.local_fn_key(self.imports.resolve(f))
        return None

    # nested defs / lambdas run who-knows-where; don't attribute their
    # bodies to this function's lockset
    def visit_FunctionDef(self, node: ast.FunctionDef):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        pass

    def visit_ClassDef(self, node: ast.ClassDef):
        pass


def _flat_targets(t: ast.AST):
    if isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            yield from _flat_targets(el)
    elif isinstance(t, ast.Starred):
        yield from _flat_targets(t.value)
    else:
        yield t


# ---------------------------------------------------------------------------
# pass 3: inter-procedural propagation + rule evaluation
# ---------------------------------------------------------------------------

def _summaries(project: _Project, config: LintConfig, *,
               prefixes: tuple[str, ...] | None = None,
               cross_module: bool = False) -> dict[tuple, _FnSummary]:
    out: dict[tuple, _FnSummary] = {}
    scope = prefixes if prefixes is not None else config.race_paths
    for mod in project.modules:
        if not config.in_scope(mod.relpath, scope):
            continue
        mid = _module_id(mod.relpath)
        for name, fn in project.functions[mid].items():
            key = ("fn", mid, name)
            s = _FnSummary(key=key, name=name, cls=None, module=mod)
            scan = _FnScan(project, s, fn, cross_module=cross_module)
            for stmt in fn.body:
                scan.visit(stmt)
            out[key] = s
        for cname, info in project.classes.items():
            if info.module is not mod:
                continue
            for mname, m in info.methods.items():
                key = ("method", cname, mname)
                s = _FnSummary(key=key, name=mname, cls=info, module=mod)
                scan = _FnScan(project, s, m, cross_module=cross_module)
                for stmt in m.body:
                    scan.visit(stmt)
                out[key] = s
    return out


def _entry_held(summaries: dict[tuple, _FnSummary],
                universe: frozenset[str]) -> dict[tuple, frozenset[str]]:
    """Locks provably held at function entry.

    ``*_locked`` methods hold their class's locks by repo convention.
    Other PRIVATE functions start at top (all locks) and narrow to the
    intersection over observed call sites — standard optimistic fixpoint.
    Public names are entry points (callable bare from anywhere): ∅."""
    entry: dict[tuple, frozenset[str]] = {}
    callers: dict[tuple, list[tuple[tuple, tuple[str, ...]]]] = defaultdict(list)
    for key, s in summaries.items():
        for c in s.calls:
            if c.callee in summaries:
                callers[c.callee].append((key, c.held))
    fixed: set[tuple] = set()
    for key, s in summaries.items():
        name = s.name
        if name.endswith("_locked") and s.cls is not None:
            entry[key] = frozenset(s.cls.lock_ids())
            fixed.add(key)
        elif not name.startswith("_") or name.startswith("__"):
            entry[key] = frozenset()
            fixed.add(key)
        elif not callers[key]:
            entry[key] = frozenset()
            fixed.add(key)
        else:
            entry[key] = universe
    changed = True
    while changed:
        changed = False
        for key in summaries:
            if key in fixed:
                continue
            acc = None
            for caller, held in callers[key]:
                site = frozenset(held) | entry.get(caller, frozenset())
                acc = site if acc is None else (acc & site)
            acc = acc if acc is not None else frozenset()
            if acc != entry[key]:
                entry[key] = acc
                changed = True
    return entry


def _may_acquire(summaries: dict[tuple, _FnSummary]) -> dict[tuple, frozenset[str]]:
    ma = {k: frozenset(a.lock for a in s.acquires)
          for k, s in summaries.items()}
    changed = True
    while changed:
        changed = False
        for k, s in summaries.items():
            acc = ma[k]
            for c in s.calls:
                if c.callee in ma:
                    acc = acc | ma[c.callee]
            if acc != ma[k]:
                ma[k] = acc
                changed = True
    return ma


def _grouped_writes(
    summaries: dict[tuple, _FnSummary],
    entry: dict[tuple, frozenset[str]],
) -> dict[tuple[str, str], list[_Write]]:
    """Tracked non-constructor writes per (class, attr), with entry-held
    locks folded into each write's held set."""
    by_field: dict[tuple[str, str], list[_Write]] = defaultdict(list)
    for key, s in summaries.items():
        for w in s.writes:
            if w.method in _CTOR_METHODS:
                continue
            by_field[(w.owner, w.attr)].append(
                _Write(owner=w.owner, attr=w.attr, line=w.line, col=w.col,
                       held=tuple(frozenset(w.held) | entry[key]),
                       what=w.what, module=w.module, method=w.method))
    return by_field


def _infer_guard(writes: list[_Write]) -> tuple[str | None, int]:
    """Majority vote: the lock held across >50% of a field's tracked
    writes (≥ 2 writes required) is its guard."""
    if len(writes) < 2:
        return None, 0
    counts: dict[str, int] = defaultdict(int)
    for w in writes:
        for lid in set(w.held):
            counts[lid] += 1
    for lid, n in sorted(counts.items()):
        if n * 2 > len(writes):
            return lid, n
    return None, 0


def guard_map(modules: list[Module],
              config: LintConfig | None = None) -> dict[str, dict]:
    """The inferred guard map: ``Class.attr`` → guard lock + coverage
    (the ``--race --guards`` CLI view, and the docs/concurrency.md
    source of truth)."""
    config = config or LintConfig()
    project = _Project(modules)
    summaries = _summaries(project, config)
    entry = _entry_held(summaries, frozenset(project.lock_home))
    out: dict[str, dict] = {}
    for (owner, attr), writes in sorted(_grouped_writes(summaries, entry).items()):
        guard, n = _infer_guard(writes)
        if guard is not None:
            out[f"{owner}.{attr}"] = {
                "guard": guard, "guarded_writes": n,
                "total_writes": len(writes),
            }
    return out


def active_race_rules(config: LintConfig) -> set[str]:
    """The race rules this run evaluates (``--rules`` filters here just
    like it does the per-module pass)."""
    if config.rules is None:
        return set(RACE_RULE_IDS)
    return set(config.rules) & set(RACE_RULE_IDS)


def analyze_modules(modules: list[Module],
                    config: LintConfig | None = None) -> list[Violation]:
    """Run R001/R002/R003 over a parsed module set (the whole-program
    entry point; waivers/baseline are the caller's passes)."""
    config = config or LintConfig()
    active = active_race_rules(config)
    project = _Project(modules)
    summaries = _summaries(project, config)
    universe = frozenset(project.lock_home)
    entry = _entry_held(summaries, universe)
    ma = _may_acquire(summaries)
    violations: list[Violation] = []

    # ---- R001: guard-map inference + bare writes --------------------------
    by_field = (
        _grouped_writes(summaries, entry) if "R001" in active else {}
    )
    for (owner, attr), writes in sorted(by_field.items()):
        guard, guard_n = _infer_guard(writes)
        if guard is None:
            continue
        for w in writes:
            if guard in w.held:
                continue
            guarded = next(x for x in writes if guard in x.held)
            violations.append(Violation(
                rule="R001", path=w.module.path, line=w.line, col=w.col,
                message=(
                    f"{owner}.{attr} is written here ({w.what}) without "
                    f"{guard}, which guards {guard_n}/{len(writes)} "
                    f"tracked writes (e.g. "
                    f"{guarded.module.relpath}:{guarded.line}) — take the "
                    f"lock, or waive with a justification if the bare "
                    f"publication is intentional"),
            ))

    # ---- R002: lock-order inversions (SCCs of the acquisition graph) ------
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def _edge(a: str, b: str, mod: Module, line: int, how: str):
        if a == b:
            return
        key = (a, b)
        cur = (mod.path, line, how)
        if key not in edges or cur[:2] < edges[key][:2]:
            edges[key] = cur

    for key, s in (summaries.items() if "R002" in active else ()):
        ent = entry[key]
        for a in s.acquires:
            for h in frozenset(a.held) | ent:
                _edge(h, a.lock, s.module, a.line, "acquired here")
        for c in s.calls:
            held = frozenset(c.held) | ent
            if not held or c.callee not in ma:
                continue
            for b in ma[c.callee]:
                for h in held:
                    _edge(h, b, s.module, c.line,
                          f"via {c.callee[1]}.{c.callee[2]}()")
    for scc in _cycle_components(edges):
        members = sorted(scc)
        detail = []
        anchor = None
        for (a, b), (path, line, how) in sorted(edges.items(),
                                                key=lambda kv: kv[1][:2]):
            if a in scc and b in scc:
                detail.append(f"{a} -> {b} ({path}:{line}, {how})")
                anchor = (path, line)
        if anchor is None:
            continue
        violations.append(Violation(
            rule="R002", path=anchor[0], line=anchor[1], col=0,
            message=(
                f"lock-order cycle among {', '.join(members)}: "
                f"{'; '.join(detail)} — pick one global order "
                f"(docs/concurrency.md)"),
        ))

    # ---- R003: blocking calls under a hot-path lock -----------------------
    for key, s in (summaries.items() if "R003" in active else ()):
        ent = entry[key]
        for b in s.blocking:
            hot = sorted(
                lid for lid in frozenset(b.held) | ent
                if config.in_scope(
                    project.lock_home.get(lid, ""), config.r003_paths)
            )
            if not hot:
                continue
            violations.append(Violation(
                rule="R003", path=b.module.path, line=b.line, col=b.col,
                message=(
                    f"blocking call {b.what} while holding hot-path lock "
                    f"{'/'.join(hot)} — hoist the I/O out of the critical "
                    f"section, or waive if this lock exists to serialize "
                    f"exactly this I/O"),
            ))

    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations


def _cycle_components(edges: dict[tuple[str, str], tuple]) -> list[frozenset[str]]:
    """Strongly-connected components with ≥ 2 nodes (each is one deadlock
    knot; iterative Tarjan so pathological graphs can't blow the stack)."""
    adj: dict[str, list[str]] = defaultdict(list)
    nodes: set[str] = set()
    for a, b in edges:
        adj[a].append(b)
        nodes.update((a, b))
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    out: list[frozenset[str]] = []

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(frozenset(comp))
    return out


# ---------------------------------------------------------------------------
# driver: paths → violations with waivers + stale-waiver hygiene applied
# ---------------------------------------------------------------------------

def load_modules(paths: list[str]) -> tuple[list[Module], list[Violation]]:
    """Parse every ``.py`` under ``paths`` → (modules, E000 violations for
    unparseable files). The one file-loading loop every whole-program
    consumer (race driver, ``--guards``) shares."""
    modules: list[Module] = []
    errors: list[Violation] = []
    for fp in iter_py_files(paths):
        with open(fp, encoding="utf-8") as f:
            source = f.read()
        mod = parse_module(source, fp)
        if isinstance(mod, Violation):
            errors.append(mod)
        else:
            modules.append(mod)
    return modules, errors


def build_flow_graph(
    modules: list[Module], config: LintConfig | None = None,
) -> tuple[_Project, dict[tuple, _FnSummary]]:
    """Shared fixpoint machinery for the flow prong: index the project
    and scan every function with CROSS-MODULE call-graph edges enabled
    (``devmon.costs().forget(...)`` and ``_traj_state.invalidate(...)``
    resolve). The race prong keeps its narrower edge set — this helper
    exists so F-rules ride the same type inference without perturbing
    R001-R003 results."""
    config = config or LintConfig()
    project = _Project(modules)
    summaries = _summaries(project, config, prefixes=("",),
                           cross_module=True)
    return project, summaries


def analyze_race_paths(paths: list[str],
                       config: LintConfig | None = None) -> list[Violation]:
    """The ``--race`` entry point: parse every file, run the whole-program
    analysis, apply per-line waivers, and flag stale tpurace waivers."""
    from geomesa_tpu.analysis.core import finalize_module_violations
    from geomesa_tpu.analysis.rules import all_rules

    config = config or LintConfig()
    if config.rules is not None:
        unknown = set(config.rules) - set(all_rules())
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    modules, violations = load_modules(paths)
    violations = list(violations)
    violations.extend(analyze_modules(modules, config))
    by_path: dict[str, list[Violation]] = defaultdict(list)
    for v in violations:
        by_path[v.path].append(v)
    # waivers are judged stale only against the rules that RAN this pass
    judged = active_race_rules(config)
    emit_w001 = config.rules is None or "W001" in config.rules
    for mod in modules:
        vs = by_path.get(mod.path, [])
        violations.extend(finalize_module_violations(
            mod, vs, judged, emit_w001=emit_w001))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations
