"""Registry descriptors for the tpurace rules.

R001-R003 are WHOLE-PROGRAM rules (``project = True``): they reason
across modules, so their findings come from
:func:`geomesa_tpu.analysis.race.lockset.analyze_race_paths` (the
``--race`` CLI mode), not from the per-module ``check`` pass — the
``check`` here is a no-op so the ids still resolve for ``--list-rules``,
``--rules`` filtering, waivers, baselines, and SARIF rule metadata.

W001 (stale waivers) is likewise emitted by shared machinery
(:func:`geomesa_tpu.analysis.core.stale_waiver_violations`) in BOTH
passes, each judging only the rules that ran in it.
"""

from __future__ import annotations

from geomesa_tpu.analysis.rules import register


@register
class GuardedFieldBareWrite:
    id = "R001"
    title = "field written outside its majority-inferred guard lock"
    project = True

    def check(self, mod, config):
        return ()


@register
class LockOrderInversion:
    id = "R002"
    title = "lock-order cycle in the static acquisition graph"
    project = True

    def check(self, mod, config):
        return ()


@register
class BlockingUnderHotLock:
    id = "R003"
    title = "blocking call (I/O, jax dispatch, sleep) under a hot-path lock"
    project = True

    def check(self, mod, config):
        return ()


@register
class StaleWaiver:
    id = "W001"
    title = "waiver comment that suppresses nothing"

    def check(self, mod, config):
        return ()  # emitted by core.stale_waiver_violations in each pass
