"""Incremental analysis: content-hash caching for the three prongs.

``--changed-only`` makes the CI gate stop re-analyzing ~240 unchanged
files per prong. The cache is keyed by CONTENT, not git state or
mtimes — a byte-identical tree always hits, an edited file always
misses — so it is equivalent to git-diff scoping without trusting the
index, and works in a dirty checkout.

Two cache shapes, matching the two analysis shapes:

- the per-module lint prong caches each file's violation list under its
  source digest (``lint.json``): an edit re-lints exactly that file;
- the whole-program race/flow prongs reason across modules, so any edit
  can change any finding: their runs are cached under a digest of the
  WHOLE file set (``race.json``/``flow.json``) — an unchanged tree is
  free, any edit re-runs the prong.

Every cache entry also carries a fingerprint of the analyzer itself
(registered rule ids + the config's scoping knobs), so upgrading a rule
or re-scoping a path invalidates everything. ``--full`` bypasses reads
but still refreshes the cache; deleting ``.tpulint-cache/`` is always
safe. Waived flags are content-derived and cached; baseline matching is
run-specific and is re-applied by the caller after load.
"""

from __future__ import annotations

import hashlib
import json
import os

from geomesa_tpu.analysis.core import (
    LintConfig,
    Violation,
    iter_py_files,
    lint_source,
)

__all__ = [
    "cache_root", "lint_paths_cached", "analyze_whole_cached",
    "CACHE_DIR_NAME",
]

CACHE_VERSION = 1
CACHE_DIR_NAME = ".tpulint-cache"


def cache_root() -> str:
    """``$TPULINT_CACHE_DIR`` or ``./.tpulint-cache`` (lint.sh runs from
    the repo root; tests point this at a tmp dir)."""
    return os.environ.get(
        "TPULINT_CACHE_DIR", os.path.join(os.getcwd(), CACHE_DIR_NAME))


def _digest(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _fingerprint(config: LintConfig, mode: str) -> str:
    """Analyzer identity: cached results are only valid for the same
    rule set and the same scoping config that produced them."""
    from geomesa_tpu.analysis.rules import all_rules

    return _digest(json.dumps({
        "mode": mode,
        "config": repr(config),
        "rules": sorted(all_rules()),
        "version": CACHE_VERSION,
    }))


def _v_to_dict(v: Violation) -> dict:
    return {
        "rule": v.rule, "path": v.path, "line": v.line, "col": v.col,
        "message": v.message, "snippet": v.snippet, "waived": v.waived,
    }


def _v_from_dict(d: dict) -> Violation:
    return Violation(
        rule=d["rule"], path=d["path"], line=d["line"], col=d["col"],
        message=d["message"], snippet=d["snippet"], waived=d["waived"],
    )


def _load(path: str, fingerprint: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if (data.get("version") != CACHE_VERSION
            or data.get("fingerprint") != fingerprint):
        return None
    return data


def _save(path: str, data: dict) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f)
    os.replace(tmp, path)  # atomic: a killed run never corrupts the cache


def lint_paths_cached(
    paths: list[str],
    config: LintConfig | None = None,
    root: str | None = None,
    use_cache: bool = True,
) -> list[Violation]:
    """Per-file cached spelling of ``lint_paths``: unchanged files reuse
    their cached violation lists, edited files re-lint, and the cache is
    rewritten with whatever this run saw."""
    config = config or LintConfig()
    root = root if root is not None else cache_root()
    fp_path = os.path.join(root, "lint.json")
    fingerprint = _fingerprint(config, "lint")
    cached = (_load(fp_path, fingerprint) or {}) if use_cache else {}
    files_cache: dict = cached.get("files", {})
    out: list[Violation] = []
    new_files: dict = {}
    for fp in iter_py_files(paths):
        with open(fp, encoding="utf-8") as f:
            source = f.read()
        d = _digest(source)
        entry = files_cache.get(fp)
        if entry is not None and entry.get("digest") == d:
            vs = [_v_from_dict(x) for x in entry["violations"]]
        else:
            vs = lint_source(source, fp, config)
        new_files[fp] = {
            "digest": d, "violations": [_v_to_dict(v) for v in vs],
        }
        out.extend(vs)
    _save(fp_path, {
        "version": CACHE_VERSION, "fingerprint": fingerprint,
        "files": new_files,
    })
    return out


def analyze_whole_cached(
    mode: str,
    analyze_fn,
    paths: list[str],
    config: LintConfig | None = None,
    root: str | None = None,
    use_cache: bool = True,
) -> list[Violation]:
    """Whole-run cache for the race/flow prongs: hash every analyzed
    file; an identical file set reuses the previous run's findings, any
    difference re-runs ``analyze_fn(paths, config)`` in full (the
    analyses are cross-module — there is no sound per-file slice)."""
    config = config or LintConfig()
    root = root if root is not None else cache_root()
    fp_path = os.path.join(root, f"{mode}.json")
    fingerprint = _fingerprint(config, mode)
    tree = _digest(json.dumps([
        (fp, _digest(open(fp, encoding="utf-8").read()))
        for fp in iter_py_files(paths)
    ]))
    cached = _load(fp_path, fingerprint) if use_cache else None
    if cached is not None and cached.get("tree") == tree:
        return [_v_from_dict(x) for x in cached["violations"]]
    violations = analyze_fn(paths, config)
    _save(fp_path, {
        "version": CACHE_VERSION, "fingerprint": fingerprint,
        "tree": tree, "violations": [_v_to_dict(v) for v in violations],
    })
    return violations
