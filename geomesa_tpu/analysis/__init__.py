"""tpulint + tpurace — static analysis for geomesa_tpu.

The JVM reference enforces its layer contracts through the type system
(PAPER.md §1); this package is the equivalent machine check for the
invariants Python can't type: tracer-safe control flow (J001), sync-free
hot paths (J002), stable jit caches (J003), the TPU 32-bit dtype
contract (J004), lock discipline in the stream layer (C001), waiver
hygiene (W001), and — via the whole-program ``--race`` pass
(:mod:`geomesa_tpu.analysis.race`) — guarded-field access (R001),
lock-order cycles (R002), and blocking calls under hot-path locks
(R003), with a runtime lock-order sanitizer as the dynamic twin.

Run it::

    python -m geomesa_tpu.analysis --baseline .tpulint-baseline.json
    python -m geomesa_tpu.analysis --race

Pure AST: linted files are parsed, never imported, and this package
imports neither JAX nor any other geomesa_tpu subsystem (scripts/lint.sh
sets ``GEOMESA_TPU_NO_JAX=1`` so even the parent package import stays
JAX-free). See docs/tpulint.md for the rule catalog, waiver syntax, and
the baseline workflow.
"""

from geomesa_tpu.analysis.core import (
    LintConfig,
    Violation,
    apply_baseline,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)

__all__ = [
    "LintConfig", "Violation", "lint_paths", "lint_source",
    "load_baseline", "write_baseline", "apply_baseline",
]
