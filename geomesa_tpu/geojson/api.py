"""GeoJSON document store facade over a datastore.

Role parity: ``geomesa-geojson/.../GeoJsonGtIndex.scala`` (439 LoC — SURVEY.md
§2.8): schemaless GeoJSON features stored whole (the document is the value),
with geometry — and optionally a date path — extracted into indexed attributes
so the mongo-style query language (:mod:`geomesa_tpu.geojson.query`) rides the
normal planned index scans; property predicates refine the parsed documents.
"""

from __future__ import annotations

import json

import numpy as np

from geomesa_tpu.convert.json_converter import geojson_geometry
from geomesa_tpu.geojson.query import compile_query
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.sft import parse_spec

_GEOM = "geom"


class GeoJsonIndex:
    """Spatially-indexed GeoJSON document collections."""

    def __init__(self, store=None):
        if store is None:
            from geomesa_tpu.store.datastore import DataStore

            store = DataStore(backend="tpu")
        self.store = store
        self._meta: dict[str, dict] = {}

    def create_index(
        self,
        name: str,
        id_path: str | None = None,
        dtg_path: str | None = None,
        points: bool = False,
    ) -> None:
        """``id_path``/``dtg_path``: dotted document paths (e.g.
        ``properties.id``); ``points`` promises Point-only geometries (enables
        the Z2/Z3 point indexes instead of XZ)."""
        gtype = "Point" if points else "Geometry"
        spec = f"json:String,dtg:Date,*{_GEOM}:{gtype}" if dtg_path else f"json:String,*{_GEOM}:{gtype}"
        self.store.create_schema(parse_spec(name, spec))
        self._meta[name] = {"id_path": id_path, "dtg_path": dtg_path}

    def delete_index(self, name: str) -> None:
        self.store.delete_schema(name)
        self._meta.pop(name, None)

    # -- write ---------------------------------------------------------------
    def add(self, name: str, features) -> list[str]:
        """Add GeoJSON: a FeatureCollection (dict or JSON string), a single
        feature, or a list of features. Returns assigned feature ids."""
        meta = self._meta[name]
        if isinstance(features, str):
            features = json.loads(features)
        if isinstance(features, dict):
            if features.get("type") == "FeatureCollection":
                features = features.get("features", [])
            else:
                features = [features]

        from geomesa_tpu.geojson.query import _doc_get

        st = self.store.get_schema(name)
        base = self.store.stats_count(name)
        recs = []
        fids = []
        for i, doc in enumerate(features):
            g = geojson_geometry(doc.get("geometry"))
            if g is None:
                raise ValueError(f"feature {i} has no valid geometry")
            rec = {"json": json.dumps(doc, separators=(",", ":")), _GEOM: g}
            if meta["dtg_path"]:
                rec["dtg"] = _millis(_doc_get(doc, meta["dtg_path"]))
            if meta["id_path"]:
                fid = _doc_get(doc, meta["id_path"])
            else:
                fid = doc.get("id")
            fids.append(str(fid) if fid is not None else f"{name}.{base + i}")
            recs.append(rec)
        if st.dtg_field and any(r.get("dtg") is None for r in recs):
            bad = next(i for i, r in enumerate(recs) if r.get("dtg") is None)
            raise ValueError(f"feature {bad} missing date at {meta['dtg_path']!r}")
        self.store.write(name, recs, fids=fids)
        return fids

    # -- read ----------------------------------------------------------------
    def query(self, name: str, q=None) -> list[dict]:
        """Run a GeoJSON query → list of parsed feature documents (with the
        stored feature id filled into ``id`` when absent)."""
        f, pred = compile_query(q or {}, geom_field=_GEOM)
        r = self.store.query(name, Query(filter=f))
        docs = []
        col = r.table.columns["json"]
        for i in range(len(r.table)):
            doc = json.loads(col.values[i])
            doc.setdefault("id", str(r.table.fids[i]))
            if pred(doc):
                docs.append(doc)
        return docs

    def query_collection(self, name: str, q=None) -> dict:
        """Like :meth:`query` but wrapped as a FeatureCollection dict."""
        return {"type": "FeatureCollection", "features": self.query(name, q)}

    def get(self, name: str, ids) -> list[dict]:
        ids = [ids] if isinstance(ids, str) else list(ids)
        return self.query(name, {"$id": ids})


def _millis(v):
    if v is None:
        return None
    if isinstance(v, (int, float, np.integer)):
        return int(v)
    from geomesa_tpu.schema.columnar import _to_millis

    return _to_millis(str(v))
