"""GeoJSON mini query language → (index filter, residual doc predicate).

Role parity: ``geomesa-geojson/.../GeoJsonQuery`` (446 LoC — SURVEY.md §2.8):
a mongo-style JSON query language over GeoJSON documents. Spatial/temporal/id
operators compile into the normal filter AST (so they ride the planned Z/XZ
index scans); property predicates — schemaless, dotted paths into the
document — become a residual Python predicate applied to the parsed docs.

Supported:

    {}                                     everything
    {"$bbox": [x1, y1, x2, y2]}            geometry bbox
    {"$intersects"|"$within"|"$contains": {"$geometry": <geojson geom>}}
    {"$dwithin": {"$geometry": ..., "$distance": deg}}
    {"$id": ["id1", ...]}                  feature ids
    {"properties.a.b": v}                  equality on a document path
    {"path": {"$lt"|"$lte"|"$gt"|"$gte"|"$ne": v}} | {"path": {"$in": [...]}}
    {"$and": [q, ...]} / {"$or": [q, ...]} / {"$not": q}
"""

from __future__ import annotations

import json
import operator

from geomesa_tpu.filter import ast

_CMP = {
    "$lt": operator.lt,
    "$lte": operator.le,
    "$gt": operator.gt,
    "$gte": operator.ge,
    "$ne": operator.ne,
}
_SPATIAL = {"$intersects": "intersects", "$within": "within", "$contains": "contains"}


def _doc_get(doc: dict, path: str):
    cur = doc
    for step in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(step)
    return cur


def _geom_literal(spec: dict):
    from geomesa_tpu.convert.json_converter import geojson_geometry

    g = geojson_geometry(spec.get("$geometry") if "$geometry" in spec else spec)
    if g is None:
        raise ValueError(f"invalid $geometry: {spec!r}")
    return g


class _True:
    def __call__(self, doc) -> bool:
        return True


def _and(preds):
    preds = [p for p in preds if not isinstance(p, _True)]
    if not preds:
        return _True()
    return lambda doc: all(p(doc) for p in preds)


def compile_query(query, geom_field: str = "geom"):
    """Query dict (or JSON string) → (ast.Filter, doc_predicate).

    ``doc_predicate(doc) -> bool`` evaluates the schemaless property part
    against a parsed GeoJSON feature dict; the AST part is index-plannable.
    """
    if isinstance(query, str):
        query = json.loads(query) if query.strip() else {}
    if not query:
        return ast.Include(), _True()

    filters: list[ast.Filter] = []
    preds = []
    for key, val in query.items():
        if key == "$and":
            subs = [compile_query(q, geom_field) for q in val]
            filters.append(ast.And([f for f, _ in subs]))
            preds.append(_and([p for _, p in subs]))
        elif key == "$or":
            subs = [compile_query(q, geom_field) for q in val]
            # OR with any residual part can't split between index and doc
            # predicate: fall back to a full-disjunction doc predicate unless
            # every branch is residual-free
            filters.append(ast.Or([f for f, _ in subs]))
            if any(not isinstance(p, _True) for _, p in subs):
                raise ValueError(
                    "$or over property predicates is not supported; "
                    "use $or of spatial/id terms or restructure the query"
                )
            preds.append(_True())
        elif key == "$not":
            f, p = compile_query(val, geom_field)
            if not isinstance(p, _True):
                raise ValueError("$not over property predicates is not supported")
            filters.append(ast.Not(f))
            preds.append(_True())
        elif key == "$bbox":
            x1, y1, x2, y2 = val
            filters.append(ast.BBox(geom_field, x1, y1, x2, y2))
            preds.append(_True())
        elif key in _SPATIAL:
            filters.append(ast.SpatialOp(_SPATIAL[key], geom_field, _geom_literal(val)))
            preds.append(_True())
        elif key == "$dwithin":
            filters.append(
                ast.SpatialOp(
                    "dwithin", geom_field, _geom_literal(val),
                    distance=float(val["$distance"]),
                )
            )
            preds.append(_True())
        elif key == "$id":
            ids = [val] if isinstance(val, str) else list(val)
            filters.append(ast.FidIn(ids))
            preds.append(_True())
        elif key.startswith("$"):
            raise ValueError(f"unknown operator {key!r}")
        else:  # document property path
            if isinstance(val, dict):
                for op, lit in val.items():
                    if op == "$in":
                        opts = list(lit)
                        preds.append(
                            lambda d, _p=key, _o=opts: _doc_get(d, _p) in _o
                        )
                    elif op in _CMP:
                        def _cmp(d, _p=key, _f=_CMP[op], _l=lit):
                            v = _doc_get(d, _p)
                            try:
                                return v is not None and _f(v, _l)
                            except TypeError:
                                return False

                        preds.append(_cmp)
                    else:
                        raise ValueError(f"unknown comparison {op!r}")
            else:
                preds.append(lambda d, _p=key, _l=val: _doc_get(d, _p) == _l)
            filters.append(ast.Include())

    f = filters[0] if len(filters) == 1 else ast.And(filters)
    return f, _and(preds)
