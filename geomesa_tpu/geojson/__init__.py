"""GeoJSON document API over any datastore."""

from geomesa_tpu.geojson.api import GeoJsonIndex
from geomesa_tpu.geojson.query import compile_query

__all__ = ["GeoJsonIndex", "compile_query"]
