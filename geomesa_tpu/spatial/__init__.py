"""Spatial analytics surface: geohash + the batched ST_* function library.

Role parity: ``geomesa-utils/.../utils/geohash/`` and the 69-UDF
``geomesa-spark-jts`` Spark SQL library (SURVEY.md §2.14, §2.18).
"""

from geomesa_tpu.spatial.geohash import (  # noqa: F401
    geohash_bbox,
    geohash_decode,
    geohash_encode,
    geohash_neighbors,
)
from geomesa_tpu.spatial.st_functions import ST  # noqa: F401
