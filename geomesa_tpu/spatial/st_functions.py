"""The ST_* spatial function library (batched, numpy-first).

Role parity: the 69+ Spark SQL UDFs in ``geomesa-spark-jts``
(``.../udf/GeometricConstructorFunctions.scala``, ``GeometricAccessorFunctions
.scala``, ``GeometricCastFunctions.scala``, ``GeometricOutputFunctions.scala``,
``GeometricProcessingFunctions.scala``, ``SpatialRelationFunctions.scala`` —
SURVEY.md §2.14). Every reference UDF name is present in the :data:`ST`
registry (lower-cased). Functions are scalar-first over the numpy geometry
model; every function also accepts numpy object arrays of geometries and maps
elementwise (the Spark "column" role), and point-vs-geometry relations have
dedicated vectorized fast paths over raw x/y columns for the billion-row join
path (:mod:`geomesa_tpu.ops.join`).
"""

from __future__ import annotations

import json

import numpy as np

from geomesa_tpu.geometry import ops as _ops
from geomesa_tpu.geometry import predicates as _pred
from geomesa_tpu.geometry.types import (
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    _Multi,
    box,
)
from geomesa_tpu.geometry.wkb import from_wkb, to_wkb
from geomesa_tpu.geometry.wkt import from_wkt, to_wkt
from geomesa_tpu.spatial.geohash import (
    geohash_bbox,
    geohash_decode,
    geohash_encode,
)

__all__ = ["ST", "st"]


def _is_geom_array(v) -> bool:
    return isinstance(v, np.ndarray) and v.dtype == object


def _elementwise(fn):
    """Lift a scalar function over numpy object arrays in any argument slot."""

    def wrapper(*args):
        arr_idx = [i for i, a in enumerate(args) if _is_geom_array(a)]
        if not arr_idx:
            return fn(*args)
        n = len(args[arr_idx[0]])
        out = []
        for k in range(n):
            row = [a[k] if _is_geom_array(a) else a for a in args]
            out.append(fn(*row))
        res = np.empty(n, dtype=object)
        res[:] = out
        # collapse to a primitive dtype when possible (bool/int/float columns)
        if out and all(isinstance(v, (bool, np.bool_)) for v in out):
            return res.astype(bool)
        if out and all(
            isinstance(v, (int, np.integer)) and not isinstance(v, bool) for v in out
        ):
            return res.astype(np.int64)
        if out and all(isinstance(v, (int, float, np.floating)) for v in out):
            return res.astype(np.float64)
        return res

    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------

@_elementwise
def st_geom_from_wkt(w: str) -> Geometry:
    return from_wkt(w)


@_elementwise
def st_geom_from_wkb(b: bytes) -> Geometry:
    return from_wkb(b)


def _typed_from_text(expected: type):
    @_elementwise
    def fn(w: str):
        g = from_wkt(w)
        if not isinstance(g, expected):
            raise TypeError(f"expected {expected.__name__}: got {g.geom_type}")
        return g

    return fn


@_elementwise
def st_make_point(x: float, y: float) -> Point:
    return Point(float(x), float(y))


@_elementwise
def st_make_bbox(xmin, ymin, xmax, ymax) -> Polygon:
    return box(float(xmin), float(ymin), float(xmax), float(ymax))


def st_make_line(points) -> LineString:
    coords = np.array([[p.x, p.y] for p in points], dtype=np.float64)
    return LineString(coords)


def st_make_polygon(line: LineString) -> Polygon:
    return Polygon(line.coords)


@_elementwise
def st_point_from_geohash(gh: str) -> Point:
    lon, lat = geohash_decode(gh)
    return Point(lon, lat)


@_elementwise
def st_geom_from_geohash(gh: str) -> Polygon:
    return box(*geohash_bbox(gh))


# ---------------------------------------------------------------------------
# outputs / casts
# ---------------------------------------------------------------------------

@_elementwise
def st_as_text(g: Geometry) -> str:
    return to_wkt(g)


@_elementwise
def st_as_binary(g: Geometry) -> bytes:
    return to_wkb(g)


def _geojson_coords(g: Geometry):
    if isinstance(g, Point):
        return [g.x, g.y]
    if isinstance(g, LineString):
        return g.coords.tolist()
    if isinstance(g, Polygon):
        return [r.tolist() for r in g.rings]
    raise TypeError(type(g).__name__)


@_elementwise
def st_as_geojson(g: Geometry) -> str:
    if isinstance(g, _Multi):
        if isinstance(g, MultiPoint):
            t, c = "MultiPoint", [[p.x, p.y] for p in g.parts]
        elif isinstance(g, MultiLineString):
            t, c = "MultiLineString", [p.coords.tolist() for p in g.parts]
        else:
            t, c = "MultiPolygon", [[r.tolist() for r in p.rings] for p in g.parts]
    else:
        t, c = g.geom_type, _geojson_coords(g)
    return json.dumps({"type": t, "coordinates": c})


def _dms(v: float, pos: str, neg: str) -> str:
    h = pos if v >= 0 else neg
    v = abs(v)
    d = int(v)
    m = int((v - d) * 60)
    s = (v - d - m / 60) * 3600
    return f"{d}°{m}'{s:.3f}\"{h}"


@_elementwise
def st_as_lat_lon_text(p: Point) -> str:
    return f"{_dms(p.y, 'N', 'S')} {_dms(p.x, 'E', 'W')}"


@_elementwise
def st_geohash(g: Geometry, precision_bits: int = 25) -> str:
    c = _ops.centroid(g)
    chars = max(1, (int(precision_bits) + 4) // 5)
    return str(geohash_encode(c.x, c.y, chars))


@_elementwise
def st_byte_array(s: str) -> bytes:
    return s.encode("utf-8")


def _cast_to(expected: type):
    @_elementwise
    def fn(g: Geometry):
        if not isinstance(g, expected):
            raise TypeError(f"cannot cast {g.geom_type} to {expected.__name__}")
        return g

    return fn


# ---------------------------------------------------------------------------
# accessors
# ---------------------------------------------------------------------------

@_elementwise
def st_x(g: Geometry) -> float:
    if not isinstance(g, Point):
        raise TypeError("st_x requires a point")
    return g.x


@_elementwise
def st_y(g: Geometry) -> float:
    if not isinstance(g, Point):
        raise TypeError("st_y requires a point")
    return g.y


# ---------------------------------------------------------------------------
# relations with point-column fast paths
# ---------------------------------------------------------------------------

def _relation(scalar_fn):
    """Lift a binary relation over geometry columns (object arrays)."""

    def fn(a, b):
        if _is_geom_array(a) or _is_geom_array(b):
            return _elementwise(scalar_fn)(a, b)
        return scalar_fn(a, b)

    fn.__name__ = scalar_fn.__name__
    return fn


st_contains = _relation(_pred.contains)
st_within = _relation(_pred.within)
st_intersects = _relation(_pred.intersects)
st_disjoint = _relation(_pred.disjoint)
st_distance = _relation(_pred.distance)
st_equals = _relation(_ops.equals)
st_touches = _relation(_ops.touches)
st_crosses = _relation(_ops.crosses)
st_overlaps = _relation(_ops.overlaps)
st_covers = _relation(_ops.covers)
st_distance_sphere = _relation(_ops.distance_sphere)


def st_aggregate_distance_sphere(points) -> float:
    """Sum of great-circle leg lengths along a point sequence (meters)."""
    if _is_geom_array(points):
        points = list(points)
    total = 0.0
    for p, q in zip(points[:-1], points[1:]):
        total += _ops.distance_sphere(p, q)
    return total


@_elementwise
def st_relate(a: Geometry, b: Geometry) -> str:
    return _ops.relate(a, b)


@_elementwise
def st_relate_bool(a: Geometry, b: Geometry, pattern: str) -> bool:
    return _ops.relate_bool(a, b, pattern)


# ---------------------------------------------------------------------------
# the registry: every reference UDF name → implementation
# ---------------------------------------------------------------------------

ST: dict[str, object] = {
    # constructors (GeometricConstructorFunctions.scala)
    "st_geomfromtext": st_geom_from_wkt,
    "st_geometryfromtext": st_geom_from_wkt,
    "st_geomfromwkt": st_geom_from_wkt,
    "st_geomfromwkb": st_geom_from_wkb,
    "st_linefromtext": _typed_from_text(LineString),
    "st_mlinefromtext": _typed_from_text(MultiLineString),
    "st_mpointfromtext": _typed_from_text(MultiPoint),
    "st_mpolyfromtext": _typed_from_text(MultiPolygon),
    "st_makebbox": st_make_bbox,
    "st_makebox2d": _elementwise(
        lambda p1, p2: box(min(p1.x, p2.x), min(p1.y, p2.y), max(p1.x, p2.x), max(p1.y, p2.y))
    ),
    "st_makeline": st_make_line,
    "st_makepolygon": _elementwise(st_make_polygon),
    "st_makepoint": st_make_point,
    "st_makepointm": st_make_point,  # M ordinate not modeled (2D framework)
    "st_point": st_make_point,
    "st_pointfromtext": _typed_from_text(Point),
    "st_pointfromwkb": st_geom_from_wkb,
    "st_polygon": _elementwise(st_make_polygon),
    "st_polygonfromtext": _typed_from_text(Polygon),
    "st_geomfromgeohash": st_geom_from_geohash,
    "st_pointfromgeohash": st_point_from_geohash,
    "st_box2dfromgeohash": st_geom_from_geohash,
    # accessors (GeometricAccessorFunctions.scala)
    "st_boundary": _elementwise(_ops.boundary),
    "st_coorddim": _elementwise(lambda g: 2),
    "st_dimension": _elementwise(_ops.dimension),
    "st_envelope": _elementwise(_ops.envelope),
    "st_exteriorring": _elementwise(_ops.exterior_ring),
    "st_geometryn": _elementwise(_ops.geometry_n),
    "st_interiorringn": _elementwise(_ops.interior_ring_n),
    "st_isclosed": _elementwise(_ops.is_closed),
    "st_iscollection": _elementwise(lambda g: isinstance(g, _Multi)),
    "st_isempty": _elementwise(_ops.is_empty),
    "st_isring": _elementwise(_ops.is_ring),
    "st_issimple": _elementwise(_ops.is_simple),
    "st_isvalid": _elementwise(_ops.is_valid),
    "st_geometrytype": _elementwise(lambda g: type(g).__name__),
    "st_numgeometries": _elementwise(_ops.num_geometries),
    "st_numpoints": _elementwise(_ops.num_points),
    "st_pointn": _elementwise(_ops.point_n),
    "st_x": st_x,
    "st_y": st_y,
    # casts (GeometricCastFunctions.scala)
    "st_casttopoint": _cast_to(Point),
    "st_casttolinestring": _cast_to(LineString),
    "st_casttopolygon": _cast_to(Polygon),
    "st_casttogeometry": _elementwise(lambda g: g),
    "st_bytearray": st_byte_array,
    # outputs (GeometricOutputFunctions.scala)
    "st_asbinary": st_as_binary,
    "st_asgeojson": st_as_geojson,
    "st_aslatlontext": st_as_lat_lon_text,
    "st_astext": st_as_text,
    "st_geohash": st_geohash,
    # processing (GeometricProcessingFunctions.scala)
    "st_antimeridiansafegeom": _elementwise(_ops.antimeridian_safe),
    "st_idlsafegeom": _elementwise(_ops.antimeridian_safe),
    "st_bufferpoint": _elementwise(_ops.buffer_point),
    "st_buffer": _elementwise(_ops.buffer_geometry),
    "st_convexhull": _elementwise(_ops.convex_hull),
    "st_translate": _elementwise(_ops.translate),
    "st_closestpoint": _elementwise(_ops.closest_point),
    # relations (SpatialRelationFunctions.scala)
    "st_area": _elementwise(_ops.area),
    "st_centroid": _elementwise(_ops.centroid),
    "st_length": _elementwise(_ops.length),
    "st_lengthsphere": _elementwise(_ops.length_sphere),
    "st_distance": st_distance,
    "st_distancesphere": st_distance_sphere,
    "st_distancespheroid": st_distance_sphere,
    "st_aggregatedistancesphere": st_aggregate_distance_sphere,
    "st_contains": st_contains,
    "st_covers": st_covers,
    "st_crosses": st_crosses,
    "st_disjoint": st_disjoint,
    "st_equals": st_equals,
    "st_intersects": st_intersects,
    "st_overlaps": st_overlaps,
    "st_touches": st_touches,
    "st_within": st_within,
    "st_relate": st_relate,
    "st_relatebool": st_relate_bool,
}


def st(name: str, *args):
    """Call an ST function by its (case-insensitive) reference UDF name."""
    try:
        fn = ST[name.lower()]
    except KeyError:
        raise KeyError(f"unknown ST function: {name}") from None
    return fn(*args)
