"""Vectorized base-32 geohash encode/decode.

Role parity: ``geomesa-utils/src/main/scala/org/locationtech/geomesa/utils/
geohash/GeoHash.scala`` (SURVEY.md §2.18) and the ``st_geoHash`` family of
Spark UDFs. Geohash is a bit-interleaved (lon-first) Morton code rendered in
base-32 — so this reuses the same fixed-point + interleave idiom as the Z
curves, vectorized over numpy int64 lanes.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.curve.zorder import compact2 as _squash
from geomesa_tpu.curve.zorder import spread2 as _spread

__all__ = [
    "geohash_encode",
    "geohash_decode",
    "geohash_bbox",
    "geohash_neighbors",
    "geohashes_in_bbox",
]

# 12 chars = 60 bits, the standard maximum (and the most the 31-bit-per-dim
# spread2 interleave lanes can hold)
MAX_PRECISION_CHARS = 12

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_BASE32_INV = {c: i for i, c in enumerate(_BASE32)}
_BASE32_ARR = np.array(list(_BASE32), dtype="<U1")


def geohash_code(lons, lats, precision_bits: int) -> np.ndarray:
    """The raw interleaved geohash integer (lon bit first), vectorized."""
    if not 1 <= precision_bits <= 5 * MAX_PRECISION_CHARS:
        raise ValueError(f"geohash precision must be 1..60 bits: {precision_bits}")
    lons = np.asarray(lons, dtype=np.float64)
    lats = np.asarray(lats, dtype=np.float64)
    lon_bits = (precision_bits + 1) // 2
    lat_bits = precision_bits // 2
    li = np.clip(
        ((lons + 180.0) / 360.0 * (1 << lon_bits)).astype(np.int64),
        0,
        (1 << lon_bits) - 1,
    )
    la = np.clip(
        ((lats + 90.0) / 180.0 * (1 << lat_bits)).astype(np.int64),
        0,
        (1 << lat_bits) - 1,
    )
    # lon comes first counted from the MSB; which parity of bit position that
    # lands on depends on whether the total bit count is even or odd
    if precision_bits % 2 == 0:
        code = (_spread(li) << np.uint64(1)) | _spread(la)
    else:
        code = _spread(li) | (_spread(la) << np.uint64(1))
    return code.astype(np.int64)


def geohash_encode(lons, lats, precision_chars: int = 12) -> np.ndarray:
    """Base-32 geohash strings for arrays of lon/lat (``st_geoHash``)."""
    bits = precision_chars * 5
    code = geohash_code(lons, lats, bits).astype(np.uint64)
    scalar = np.isscalar(lons) or np.ndim(lons) == 0
    code = np.atleast_1d(code)
    chars = np.empty((len(code), precision_chars), dtype="<U1")
    for k in range(precision_chars):
        sh = np.uint64(bits - 5 * (k + 1))
        chars[:, k] = _BASE32_ARR[((code >> sh) & np.uint64(31)).astype(np.int64)]
    out = np.ascontiguousarray(chars).view(f"<U{precision_chars}").reshape(len(code))
    return out[0] if scalar else out


def geohash_decode(gh: str) -> tuple[float, float]:
    """Geohash → (lon, lat) cell-center (``st_geomFromGeoHash`` center)."""
    xmin, ymin, xmax, ymax = geohash_bbox(gh)
    return ((xmin + xmax) / 2.0, (ymin + ymax) / 2.0)


def geohash_bbox(gh: str) -> tuple[float, float, float, float]:
    """Geohash → (xmin, ymin, xmax, ymax) cell bounds (``st_box2DFromGeoHash``)."""
    code = 0
    for c in gh.lower():
        code = (code << 5) | _BASE32_INV[c]
    bits = len(gh) * 5
    lon_bits = (bits + 1) // 2
    lat_bits = bits // 2
    if len(gh) > MAX_PRECISION_CHARS:
        raise ValueError(f"geohash longer than {MAX_PRECISION_CHARS} chars: {gh!r}")
    code = np.uint64(code)
    if bits % 2 == 0:
        li = int(_squash(code >> np.uint64(1)))
        la = int(_squash(code))
    else:
        li = int(_squash(code))
        la = int(_squash(code >> np.uint64(1)))
    lon_size = 360.0 / (1 << lon_bits)
    lat_size = 180.0 / (1 << lat_bits)
    xmin = -180.0 + li * lon_size
    ymin = -90.0 + la * lat_size
    return (xmin, ymin, xmin + lon_size, ymin + lat_size)


def geohash_neighbors(gh: str) -> list[str]:
    """The 8 neighboring cells at the same precision."""
    xmin, ymin, xmax, ymax = geohash_bbox(gh)
    cx, cy = (xmin + xmax) / 2, (ymin + ymax) / 2
    dx, dy = xmax - xmin, ymax - ymin
    out = []
    for oy in (-dy, 0.0, dy):
        for ox in (-dx, 0.0, dx):
            if ox == 0.0 and oy == 0.0:
                continue
            lon = cx + ox
            lat = cy + oy
            if lat <= -90.0 or lat >= 90.0:
                continue
            lon = ((lon + 180.0) % 360.0) - 180.0
            out.append(str(geohash_encode(lon, lat, len(gh))))
    return out


def geohashes_in_bbox(
    bbox, precision_chars: int = 5, max_hashes: int = 100_000
) -> list[str]:
    """Enumerate the geohash cells intersecting a (xmin, ymin, xmax, ymax)
    box — the ``GeohashUtils`` bbox-iteration role (coarse covers for
    polygon filters / raster keying). Cells come back column-major: one
    west→east column at a time, south→north within each column. Raises when
    the cover would exceed ``max_hashes`` (pick a coarser precision
    instead)."""
    if not 1 <= precision_chars <= MAX_PRECISION_CHARS:
        raise ValueError(f"precision must be 1..12 chars: {precision_chars}")
    xmin, ymin, xmax, ymax = (float(v) for v in bbox)
    if xmin > xmax or ymin > ymax:
        raise ValueError(f"malformed bbox: {bbox}")
    bits = 5 * precision_chars
    lon_bits = (bits + 1) // 2
    lat_bits = bits // 2
    dx = 360.0 / (1 << lon_bits)
    dy = 180.0 / (1 << lat_bits)
    ix0 = int(np.clip((xmin + 180.0) // dx, 0, (1 << lon_bits) - 1))
    ix1 = int(np.clip((xmax + 180.0) // dx, 0, (1 << lon_bits) - 1))
    iy0 = int(np.clip((ymin + 90.0) // dy, 0, (1 << lat_bits) - 1))
    iy1 = int(np.clip((ymax + 90.0) // dy, 0, (1 << lat_bits) - 1))
    n = (ix1 - ix0 + 1) * (iy1 - iy0 + 1)
    if n > max_hashes:
        raise ValueError(
            f"bbox cover needs {n} geohashes at {precision_chars} chars "
            f"(max_hashes={max_hashes}); use a coarser precision"
        )
    xs = np.arange(ix0, ix1 + 1)
    ys = np.arange(iy0, iy1 + 1)
    cx = -180.0 + (np.repeat(xs, len(ys)) + 0.5) * dx
    cy = -90.0 + (np.tile(ys, len(xs)) + 0.5) * dy
    return geohash_encode(cx, cy, precision_chars).tolist()
