"""Elastic federation — live shard migration, membership autoscaling, and
HBM → host-RAM → disk buffer tiering (docs/operations.md § Elasticity).

Three planes, one module, because they share the generation machinery of
:mod:`geomesa_tpu.serving.shards`:

- :class:`ShardMigrator` moves ONE shard's rows between federation
  members with zero downtime and zero acked-write loss. The protocol
  (docs/serving.md § Shard-map lifecycle)::

      stable → shipping → dual_apply → cutover → stable

  *Shipping* exports the shard's rows via
  :func:`~geomesa_tpu.store.persistence.save_shard` — the bundle is
  stamped with the source's WAL replay floor at the SAME instant the
  rows are captured — and bulk-loads them into the destination.
  *Dual-apply* installs a generation whose
  :class:`~geomesa_tpu.serving.shards.ShardMigration` record makes every
  new write apply to BOTH owners (the fid lands in the migration's
  exactly-once ledger before the source apply commits to the WAL) and
  row reads fan to their union; the migrator then drains the pre-dual
  generations, captures a stop seq, and replays the source's WAL tail
  ``(floor, stop]`` onto the destination — shard-keyed rows only, ledger
  fids skipped under the migration lock, so tail replay and dual writes
  compose to exactly-once. *Cutover* journals the new assignment FIRST,
  then installs the generation that makes the destination authoritative;
  only after the dual generation drains do the source's copies drop.
  Every step is bracketed by named crash points (``elastic.*``) and the
  on-disk :class:`ElasticJournal` makes :meth:`ShardMigrator.recover`
  deterministic after a SIGKILL anywhere: pre-cutover phases roll BACK
  (source was authoritative throughout), a journaled cutover rolls
  FORWARD (destination already owns the shard). Proven end to end by
  ``scripts/rebalance_smoke.py``.

- :class:`FederationAutoscaler` is the background control plane: it
  watches per-member SLO burn (``member_health``), admission shed rates,
  and devmon HBM headroom, and turns them into membership *proposals*
  (add / rebalance). Execution is gated (``auto_execute``) and bounded
  (``max_moves_per_eval``); evaluation runs inside ``audit.shadow()`` so
  the control plane's own reads never train the feedback planes.

- :class:`TieringPolicy` extends the buffer pool's eviction ladder:
  instead of freeing an evicted index's device arrays outright, the
  owner state is kept alive with its columns exported to pinned host RAM
  (budget ``GEOMESA_TPU_TIER_RAM`` bytes), overflowing to on-disk
  ``.npz`` bundles under ``GEOMESA_TPU_TIER_DIR`` — the RAM victim is
  the entry whose plan shapes the ISSUE-9 cost table values LEAST
  (cheapest to lose). A later load promotes straight back
  (disk → RAM → device) without re-staging from the columnar tier.
  Demotion unregisters the devmon ledger entries at the instant the
  bytes leave the device (``unregister_matching`` — the owner stays
  alive, so its GC finalizer can never fire) and promotion re-registers
  them, keeping the ledger-vs-residency agreement the invariant sweeper
  checks (``check_tiering``).

Locking (docs/concurrency.md § elastic plane): the migrator lock
serializes migrations and nests ABOVE every store lock (save_shard /
write / delete run inside it); the migration's own ``lock`` is taken
only around destination check-then-apply pairs; the tiering lock is a
LEAF guarding the tier maps — array export/import and file I/O run
outside it.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import OrderedDict
from pathlib import Path

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.analysis.contracts import shadow_plane
from geomesa_tpu.resilience import faults
from geomesa_tpu.serving.shards import (
    MIG_DUAL,
    MIG_SHIPPING,
    RouterGeneration,
    ShardMigration,
    ShardRouter,
)
from geomesa_tpu.store import persistence as _persist
from geomesa_tpu.store import wal as _walmod

__all__ = [
    "ELASTIC_UNSAFE_ENV", "ElasticJournal", "FederationAutoscaler",
    "MigrationError", "ShardMigrator", "TIER_DIR_ENV", "TIER_RAM_ENV",
    "TieringPolicy", "migration_metrics", "prometheus_lines",
    "prometheus_text",
]

# red-leg switch (scripts/rebalance_smoke.py --red): disables the
# dual-apply state while the rest of the protocol proceeds, so writes
# landing after the stop-seq capture stay source-only and are LOST at
# cutover — the harness must detect the loss, proving the referee can
ELASTIC_UNSAFE_ENV = "GEOMESA_TPU_ELASTIC_UNSAFE"

TIER_RAM_ENV = "GEOMESA_TPU_TIER_RAM"  # warm-tier budget, bytes
TIER_DIR_ENV = "GEOMESA_TPU_TIER_DIR"  # cold-tier directory (off when unset)

# the geomesa_shard_migrations_total{state} label set
MIGRATION_STATES = ("started", "cutover", "completed", "failed",
                    "rolled_back", "rolled_forward")

_MIG_LOCK = threading.Lock()
_MIG_COUNTS = dict.fromkeys(MIGRATION_STATES, 0)

# live policy / autoscaler instances, for the process-wide prometheus
# exposition (weak: an instance's metrics disappear with it)
_POLICIES: "weakref.WeakSet[TieringPolicy]" = weakref.WeakSet()
_SCALERS: "weakref.WeakSet[FederationAutoscaler]" = weakref.WeakSet()


def _count_migration(state: str) -> None:
    with _MIG_LOCK:
        _MIG_COUNTS[state] += 1


def migration_metrics() -> dict:
    with _MIG_LOCK:
        return dict(_MIG_COUNTS)


def prometheus_lines(prefix: str = "geomesa") -> list[str]:
    """The elastic plane's ``/api/metrics?format=prometheus`` series:
    migration state counters, per-tier byte gauges, autoscaler totals."""
    lines = [f"# TYPE {prefix}_shard_migrations_total counter"]
    counts = migration_metrics()
    for state in MIGRATION_STATES:
        lines.append(
            f'{prefix}_shard_migrations_total{{state="{state}"}} '
            f"{counts[state]}")
    tiers: dict[tuple, int] = {}
    for pol in list(_POLICIES):
        for tier, per_type in pol.tier_bytes().items():
            for tn, b in per_type.items():
                tiers[(tier, tn)] = tiers.get((tier, tn), 0) + b
    lines.append(f"# TYPE {prefix}_tier_bytes gauge")
    for (tier, tn), b in sorted(tiers.items()):
        lines.append(f'{prefix}_tier_bytes{{tier="{tier}",type="{tn}"}} {b}')
    ev = pr = ex = 0
    for sc in list(_SCALERS):
        snap = sc.snapshot()
        ev += snap["evals"]
        pr += snap["proposals_total"]
        ex += snap["executed_total"]
    for name, v in (("evals", ev), ("proposals", pr), ("executed", ex)):
        lines.append(f"# TYPE {prefix}_autoscaler_{name}_total counter")
        lines.append(f"{prefix}_autoscaler_{name}_total {v}")
    return lines


def prometheus_text(prefix: str = "geomesa") -> str:
    return "\n".join(prometheus_lines(prefix)) + "\n"


class MigrationError(RuntimeError):
    """A live migration could not complete; the migrator rolled the
    shard map back (or refused to start). The federation keeps serving
    from the source owner — no acked write was lost."""


class ElasticJournal:
    """The migrator's crash-recovery journal: ONE small JSON document
    holding the current phase plus everything needed to rebuild the
    shard map after a SIGKILL (members, shard cuts, the FULL assignment
    map, the in-flight migration's floors). Written atomically
    (tmp + fsync + rename) BEFORE the state transition it describes, so
    the on-disk phase is always at/ahead of the in-memory one and
    :meth:`ShardMigrator.recover` can resolve any crash point."""

    def __init__(self, path: str):
        self.path = Path(path)

    def load(self) -> dict | None:
        try:
            return json.loads(self.path.read_text())
        except FileNotFoundError:
            return None
        except ValueError as e:
            raise MigrationError(
                f"corrupt elastic journal {self.path}: {e}") from e

    def write(self, doc: dict) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_name(self.path.name + ".tmp")
        tmp.write_text(json.dumps(doc, indent=1))
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self.path)


class ShardMigrator:
    """Zero-downtime, zero-loss shard movement (module docstring has the
    protocol). One migration at a time (``_lock``); the view keeps
    serving reads and writes throughout — only the routing overlay
    changes, generation by generation."""

    def __init__(self, view, journal_path: str, workdir: str, *,
                 dual_window_s: float = 0.25,
                 catchup_timeout_s: float = 30.0,
                 drain_timeout_s: float = 10.0,
                 unsafe: bool | None = None):
        self.view = view
        self.journal = ElasticJournal(journal_path)
        self.workdir = Path(workdir)
        self.dual_window_s = float(dual_window_s)
        self.catchup_timeout_s = float(catchup_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        if unsafe is None:
            unsafe = os.environ.get(ELASTIC_UNSAFE_ENV, "") not in ("", "0")
        self.unsafe = bool(unsafe)
        self._lock = threading.Lock()
        self.history: list[dict] = []

    # -- helpers --------------------------------------------------------------
    def _store(self, member):
        return self.view.stores[member][0]

    def _doc(self, phase: str, router: ShardRouter, generation: int,
             migration: dict | None = None) -> dict:
        return {
            "phase": phase,
            "members": list(router.members),
            "n_shards": router.n_shards,
            "virtual_nodes": router.virtual_nodes,
            # the FULL map (not just ring diffs): recovery rebuilds the
            # exact ownership without re-deriving any ring state
            "assignments": {str(s): m for s, m in
                            enumerate(router.shard_member)},
            "generation": int(generation),
            "migration": migration,
        }

    def _shards_of_table(self, sft, table, router: ShardRouter) -> np.ndarray:
        """Shard id per table row — the write path's OWN keying
        (``_record_shards``), so ship/replay/drop can never place a row
        differently than the write that stored it."""
        recs = [table.record(i) for i in range(len(table))]
        fids = [str(f) for f in table.fids]
        return np.asarray(
            self.view._record_shards(sft, recs, fids, router))

    def _selector(self, router: ShardRouter, type_name: str, shard: int):
        sft = self.view.get_schema(type_name)

        def pick(table):
            if not len(table):
                return np.zeros(0, dtype=bool)
            return self._shards_of_table(sft, table, router) == shard

        return pick

    def _delete_shard_rows(self, store, router: ShardRouter, shard: int,
                           types) -> int:
        """Remove every row of ``shard`` from ``store`` — idempotent
        (re-runs after a crash remove nothing new), used for both the
        post-cutover source drop and rollback's destination cleanup."""
        removed = 0
        for t in types:
            table = store.query(t, None).table
            if not len(table):
                continue
            sft = self.view.get_schema(t)
            shards = self._shards_of_table(sft, table, router)
            fids = [str(f) for f, s in zip(table.fids, shards)
                    if int(s) == shard]
            if fids:
                removed += store.delete_features(t, fids)
        return removed

    def _anomaly(self, shard: int, src, dst, what: str,
                 t0: float) -> None:
        from geomesa_tpu.obs import flight as _flight

        _flight.record(
            op="elastic.migrate", type_name="", source="elastic",
            plan=f"shard {shard} {src}->{dst}: {what}",
            latency_ms=(time.monotonic() - t0) * 1000.0,
            anomalies=(_flight.A_MIGRATION,))

    # -- the live migration ----------------------------------------------------
    def migrate(self, shard: int, dst, types=None) -> dict:
        """Move ``shard`` from its current owner to ``dst``; returns a
        summary dict. Raises :class:`MigrationError` (after rolling the
        shard map back) when the move cannot complete — the source stays
        authoritative and no acked write is lost either way."""
        with self._lock:
            return self._migrate(int(shard), dst, types)

    def _migrate(self, shard: int, dst, types) -> dict:
        view = self.view
        gen0 = view._generation
        router = gen0.router
        src = router.member_for_shard(shard)
        if src == dst:
            raise MigrationError(
                f"shard {shard} already owned by member {dst!r}")
        if dst not in set(router.members):
            raise MigrationError(
                f"destination {dst!r} is not a member: add_member first")
        if shard in gen0.migrations:
            raise MigrationError(f"shard {shard} already migrating")
        src_store, dst_store = self._store(src), self._store(dst)
        src_wal = getattr(src_store, "_wal", None)
        if src_wal is None:
            raise MigrationError(
                "live migration requires a WAL-backed source member "
                "(the tail replay has nothing to read otherwise)")
        names = (list(types) if types is not None
                 else list(src_store.list_schemas()))
        t0 = time.monotonic()
        _count_migration("started")
        mig = ShardMigration(shard, src, dst, MIG_SHIPPING)
        mig_doc = {"shard": shard, "src": src, "dst": dst, "types": names,
                   "floors": {}}
        with obs.span("elastic.migrate", shard=shard, src=src, dst=dst):
            self.journal.write(
                self._doc("shipping", router, gen0.generation, mig_doc))
            faults.crash_point("elastic.pre_ship")
            gen1 = gen0.advance(
                migrations=(*gen0.migrations.values(), mig))
            view.swap_generation(gen1)
            # restart hygiene: a prior crashed attempt (journal already
            # rolled back) may have left partial copies on the destination
            self._delete_shard_rows(dst_store, router, shard, names)
            floors: dict[str, int | None] = {}
            for t in names:
                bundle = self.workdir / f"shard-{shard}-{t}"
                man = _persist.save_shard(
                    src_store, t, str(bundle),
                    self._selector(router, t, shard))
                floors[t] = man["wal_floor"]
                mig.rows_shipped += man["rows"]
                faults.crash_point("elastic.mid_ship")
                _persist.load_shard(dst_store, str(bundle))
            mig_doc["floors"] = floors
            self.journal.write(
                self._doc("dual_apply", router, gen1.generation, mig_doc))
            faults.crash_point("elastic.pre_dual")
            # the unsafe (red-leg) variant keeps the migration in the
            # SHIPPING state: writes stay source-only, so anything landing
            # after the stop capture below never reaches the destination
            dual = mig.with_state(
                MIG_SHIPPING if self.unsafe else MIG_DUAL)
            gen2 = gen1.advance(migrations=tuple(
                dual if m.shard == shard else m
                for m in gen1.migrations.values()))
            view.swap_generation(gen2)
            t_dual = time.monotonic()
            # drain BEFORE the stop capture: every write routed by a
            # pre-dual generation is source-only, and wait_idle returning
            # means its WAL commit (the write ack) already happened — its
            # seq is at/below the high-water we read next
            for g in (gen0, gen1):
                if not g.wait_idle(self.drain_timeout_s):
                    self._anomaly(shard, src, dst,
                                  "pre-stop drain timed out", t0)
                    self._rollback(dual, gen2, router, names,
                                   "pre-stop drain timed out")
            stop = src_wal.seq_highwater()
            # hold the dual window open: concurrent writes during the
            # sleep exercise the dual path (and, on the red leg, ARE the
            # lost window the harness must detect)
            time.sleep(self.dual_window_s)
            deadline = time.monotonic() + self.catchup_timeout_s
            try:
                for t in names:
                    self._replay_tail(src_wal, dst_store, dual, router, t,
                                      floors.get(t), stop, shard, deadline)
            except MigrationError as e:
                self._anomaly(shard, src, dst, f"catch-up: {e}", t0)
                self._rollback(dual, gen2, router, names, str(e))
            faults.crash_point("elastic.mid_catchup")
            new_router = router.with_assignment(shard, dst)
            # journal cutover BEFORE installing it: a crash in between
            # rolls FORWARD (the journal is the commit point)
            self.journal.write(self._doc(
                "cutover", new_router, gen2.generation, mig_doc))
            faults.crash_point("elastic.pre_cutover")
            gen3 = gen2.advance(router=new_router, migrations=tuple(
                m for m in gen2.migrations.values() if m.shard != shard))
            view.swap_generation(gen3)
            dual_ms = (time.monotonic() - t_dual) * 1000.0
            bad = new_router.coverage_violations()
            if bad:
                # unreachable by construction; fail loudly, not silently
                raise MigrationError(
                    f"post-cutover coverage violations: {bad}")
            _count_migration("cutover")
            if gen2.wait_idle(self.drain_timeout_s):
                faults.crash_point("elastic.pre_source_drop")
                self._delete_shard_rows(src_store, new_router, shard, names)
            else:
                # a straggling dual write could land on the source after
                # our sweep: skip the drop (the rows are unreachable —
                # reads fan to the new owner) and record the stall
                self._anomaly(shard, src, dst,
                              "cutover drain timed out; source copies "
                              "retained", t0)
            self.journal.write(
                self._doc("stable", new_router, gen3.generation))
            _count_migration("completed")
        out = {
            "shard": shard, "src": src, "dst": dst,
            # the DUAL record's counters: replay increments land on the
            # state-advanced copy (with_state copies counts by value)
            "rows_shipped": int(dual.rows_shipped),
            "rows_replayed": int(dual.rows_replayed),
            "dual_fids": len(dual.dual_fids),
            "dual_apply_ms": round(dual_ms, 3),
            "duration_s": round(time.monotonic() - t0, 3),
            "generation": gen3.generation,
        }
        self.history.append(out)
        return out

    def _replay_tail(self, wal, dst_store, mig: ShardMigration,
                     router: ShardRouter, type_name: str,
                     floor, stop: int, shard: int,
                     deadline: float) -> None:
        """Apply the source's WAL tail ``(floor, stop]`` for one type to
        the destination: shard-keyed rows only, ledger fids skipped —
        the check-then-apply runs under the migration lock so a
        concurrent dual write (or delete) can never interleave into a
        duplicate or a resurrection."""
        from geomesa_tpu.io.arrow import from_ipc_bytes

        sft = self.view.get_schema(type_name)
        topic = _walmod.topic_for(type_name)
        for _seq, hdr, body in wal.records_between(
                topic, floor if floor is not None else 0, stop):
            if time.monotonic() > deadline:
                raise MigrationError(
                    f"catch-up replay for {type_name!r} exceeded "
                    f"{self.catchup_timeout_s}s")
            op = hdr.get("op")
            if op == "write":
                table = from_ipc_bytes(sft, body)
                recs = [table.record(i) for i in range(len(table))]
                fids = [str(f) for f in table.fids]
                shards = np.asarray(self.view._record_shards(
                    sft, recs, fids, router))
                idx = [i for i in range(len(table))
                       if int(shards[i]) == shard]
                if not idx:
                    continue
                with mig.lock:
                    fresh = [i for i in idx if fids[i] not in mig.dual_fids]
                    if fresh:
                        dst_store.write(
                            type_name, [recs[i] for i in fresh],
                            fids=[fids[i] for i in fresh])
                        mig.rows_replayed += len(fresh)
            elif op == "delete":
                want = [str(f) for f in hdr.get("fids", ())]
                with mig.lock:
                    fresh = [f for f in want if f not in mig.dual_fids]
                    if fresh:
                        # fids of other shards delete nothing here
                        # (delete_features tolerates absent fids)
                        dst_store.delete_features(
                            type_name, fresh,
                            visible_to=hdr.get("visible_to"))
            elif op in ("clear", "age_off"):
                # whole-type mutations cannot be scoped to one shard's
                # replay; documented limitation — abort, roll back, retry
                # after the operation has fully applied
                raise MigrationError(
                    f"{op!r} record in the migration tail for "
                    f"{type_name!r}")

    def _rollback(self, mig: ShardMigration, gen: RouterGeneration,
                  router: ShardRouter, names, reason: str) -> None:
        """Abandon the migration: reinstall the pre-migration routing
        (source stays authoritative — it never stopped holding every
        row), drain the dual generation, drop the destination's copies,
        journal stable. Always raises :class:`MigrationError`."""
        view = self.view
        gen_r = gen.advance(router=router, migrations=tuple(
            m for m in gen.migrations.values() if m.shard != mig.shard))
        view.swap_generation(gen_r)
        # in-flight dual writes must land before the sweep, or the sweep
        # could miss a row that then lingers on the destination
        gen.wait_idle(self.drain_timeout_s)
        self._delete_shard_rows(
            self._store(mig.dst), router, mig.shard, names)
        self.journal.write(
            self._doc("stable", router, gen_r.generation))
        _count_migration("failed")
        _count_migration("rolled_back")
        raise MigrationError(
            f"migration of shard {mig.shard} rolled back: {reason}")

    # -- crash recovery --------------------------------------------------------
    def recover(self) -> dict | None:
        """Resolve whatever the journal says was in flight when the
        process died (call after reopening the member stores, before
        serving). Shipping/dual phases roll BACK — the cutover never
        committed, the source is authoritative, the destination's
        partial copies drop. A journaled cutover rolls FORWARD — its
        assignment map already names the destination; only the source's
        stale copies remain to drop. Either way the journaled shard map
        is (re)installed as a fresh generation. Returns a summary, or
        None when no journal exists."""
        doc = self.journal.load()
        if doc is None:
            return None
        router = ShardRouter(
            doc["members"], doc["n_shards"], doc["virtual_nodes"],
            assignments={int(k): v
                         for k, v in doc["assignments"].items()})
        phase = doc["phase"]
        mig = doc.get("migration") or {}
        names = mig.get("types") or []
        action = "none"
        if phase in ("shipping", "dual_apply") and mig:
            self._delete_shard_rows(
                self._store(mig["dst"]), router, int(mig["shard"]), names)
            action = "rolled_back"
            _count_migration("rolled_back")
        elif phase == "cutover" and mig:
            self._delete_shard_rows(
                self._store(mig["src"]), router, int(mig["shard"]), names)
            action = "rolled_forward"
            _count_migration("rolled_forward")
        view = self.view
        cur = view._generation
        gen = RouterGeneration(
            router, max(int(doc["generation"]) + 1, cur.generation + 1))
        view.swap_generation(gen)
        self.journal.write(self._doc("stable", router, gen.generation))
        return {"phase": phase, "action": action,
                "shard": mig.get("shard"), "src": mig.get("src"),
                "dst": mig.get("dst"), "generation": gen.generation}

    # -- membership plans ------------------------------------------------------
    def plan_membership(self, members) -> list[dict]:
        """The ordered step list a LIVE change to ``members`` needs:
        joins first (membership precedes ownership), then one migrate
        per shard whose ring-target owner differs from its current one,
        then departures of fully-drained members."""
        cur = self.view._generation.router
        target = ShardRouter(members, cur.n_shards, cur.virtual_nodes)
        have, want = set(cur.members), set(members)
        plan: list[dict] = [
            {"action": "add", "member": m} for m in members
            if m not in have
        ]
        for s in range(cur.n_shards):
            dst = target.shard_member[s]
            if cur.shard_member[s] != dst:
                plan.append({"action": "migrate", "shard": s,
                             "src": cur.shard_member[s], "dst": dst})
        plan.extend({"action": "remove", "member": m}
                    for m in cur.members if m not in want)
        return plan

    def apply_membership(self, members, types=None) -> list[dict]:
        """Execute :meth:`plan_membership` — live. ``add`` steps must
        already be done (``view.add_member`` needs the store object);
        migrates run through the full protocol, departures go through
        ``remove_member`` (which enforces drained-first)."""
        plan = self.plan_membership(members)
        for step in plan:
            if step["action"] == "add":
                raise MigrationError(
                    f"member {step['member']!r} not joined yet: call "
                    "view.add_member(store) first")
            if step["action"] == "migrate":
                self.migrate(step["shard"], step["dst"], types=types)
            else:
                self.view.remove_member(step["member"])
        return plan


@shadow_plane
class FederationAutoscaler:
    """Membership control plane: periodic evaluation of member health /
    admission pressure / HBM headroom into proposals, with gated bounded
    execution (module docstring). Sweeper-shaped thread lifecycle."""

    def __init__(self, view, migrator: ShardMigrator | None = None,
                 admission=None, pool=None, *, interval_s: float = 5.0,
                 auto_execute: bool = False, max_moves_per_eval: int = 1,
                 burn_threshold: float = 0.5, shed_threshold: float = 0.2,
                 hbm_headroom_frac: float = 0.1):
        self.view = view
        self.migrator = migrator
        self.admission = admission
        self.pool = pool
        self.interval_s = float(interval_s)
        self.auto_execute = bool(auto_execute)
        self.max_moves_per_eval = int(max_moves_per_eval)
        self.burn_threshold = float(burn_threshold)
        self.shed_threshold = float(shed_threshold)
        self.hbm_headroom_frac = float(hbm_headroom_frac)
        self._lock = threading.Lock()  # leaf: counters + last proposals
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.evals = 0
        self.proposals_total = 0
        self.executed_total = 0
        self.last_eval_ts = 0.0
        self.last_proposals: list[dict] = []
        _SCALERS.add(self)

    # -- evaluation ------------------------------------------------------------
    def evaluate(self) -> list[dict]:
        """One pass over the signals → proposals (no execution). Runs in
        audit shadow: the control plane's reads must not train the cost
        table, burn SLO budgets, or meter usage."""
        from geomesa_tpu.obs import audit as _audit

        with _audit.shadow():
            proposals = self._evaluate_inner()
        with self._lock:
            self.evals += 1
            self.proposals_total += len(proposals)
            self.last_eval_ts = time.time()
            self.last_proposals = proposals
        return proposals

    def _evaluate_inner(self) -> list[dict]:
        view = self.view
        gen = view._generation
        router = gen.router
        if gen.migrations:
            return []  # let the in-flight move settle before proposing
        proposals: list[dict] = []
        loads = {m: len(router.shards_of_member(m))
                 for m in router.members}
        health = {h["member"]: h for h in view.member_health()
                  if h["member"] in loads}
        healthy = [m for m in router.members
                   if health.get(m, {}).get("budget_remaining", 1.0)
                   >= self.burn_threshold]
        # 1) SLO burn: a member burning its error budget sheds a shard
        #    to the least-loaded healthy member
        for m in router.members:
            h = health.get(m)
            if h is None or not loads.get(m):
                continue
            if h["budget_remaining"] < self.burn_threshold:
                targets = [t for t in healthy if t != m]
                if targets:
                    dst = min(targets, key=lambda t: loads.get(t, 0))
                    proposals.append({
                        "action": "rebalance",
                        "shard": router.shards_of_member(m)[0],
                        "src": m, "dst": dst,
                        "reason": (f"member {m} SLO budget "
                                   f"{h['budget_remaining']:.2f} < "
                                   f"{self.burn_threshold}"),
                    })
        # 2) admission shed pressure → the federation needs capacity
        adm = self.admission
        if adm is not None:
            admitted = int(getattr(adm, "admitted_count", 0))
            shed = int(getattr(adm, "shed_count", 0))
            total = admitted + shed
            if total >= 20 and shed / total > self.shed_threshold:
                proposals.append({
                    "action": "add", "member": None,
                    "reason": (f"admission shedding {shed}/{total} "
                               f"(> {self.shed_threshold:.0%})"),
                })
        # 3) devmon HBM headroom against the pool budget
        pool = self.pool
        if pool is not None and pool.max_total_bytes:
            from geomesa_tpu.obs import devmon

            used = devmon.ledger().total_bytes()
            if used > (1.0 - self.hbm_headroom_frac) * pool.max_total_bytes:
                proposals.append({
                    "action": "add", "member": None,
                    "reason": (f"HBM headroom: ledger {used} B of "
                               f"{pool.max_total_bytes} B budget"),
                })
        # 4) drain onto idle members (the post-add step: a freshly joined
        #    member owns nothing until shards move to it)
        idle = [m for m in router.members if not loads.get(m)]
        if idle and not any(p["action"] == "rebalance" for p in proposals):
            donor = max(router.members, key=lambda m: loads.get(m, 0))
            if loads.get(donor, 0) >= 2:
                proposals.append({
                    "action": "rebalance",
                    "shard": router.shards_of_member(donor)[0],
                    "src": donor, "dst": idle[0],
                    "reason": f"member {idle[0]} owns no shards",
                })
        return proposals

    def step(self) -> list[dict]:
        """Evaluate, then (when ``auto_execute``) run up to
        ``max_moves_per_eval`` rebalance proposals through the migrator.
        ``add`` proposals are never auto-executed — joining a member
        needs a store object only the operator can provide."""
        proposals = self.evaluate()
        if not (self.auto_execute and self.migrator is not None):
            return proposals
        moves = 0
        for p in proposals:
            if moves >= self.max_moves_per_eval:
                break
            if p["action"] != "rebalance":
                continue
            try:
                self.migrator.migrate(p["shard"], p["dst"])
            except MigrationError:
                continue  # counted via migration metrics; keep serving
            moves += 1
            with self._lock:
                self.executed_total += 1
        return proposals

    # -- thread lifecycle ------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="geomesa-autoscaler", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the control plane must not die
                pass

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "evals": self.evals,
                "proposals_total": self.proposals_total,
                "executed_total": self.executed_total,
                "auto_execute": self.auto_execute,
                "last_eval_ts": self.last_eval_ts,
                "proposals": list(self.last_proposals),
            }


def _env_int(name: str) -> int | None:
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be an integer byte count, got {raw!r}") from None


def _to_device(a):
    """Host → device staging for promotion; plain numpy under
    ``GEOMESA_TPU_NO_JAX`` (the arrays still serve, host-side)."""
    if os.environ.get("GEOMESA_TPU_NO_JAX"):
        return np.asarray(a)
    try:
        import jax.numpy as jnp
    except Exception:  # noqa: BLE001 — jax-less environments
        return np.asarray(a)
    return jnp.asarray(a)


class _Tiered:
    """One demoted residency unit: the pool's ``_Entry`` (kept whole so
    promotion re-installs it, stats and all) plus where its bytes live
    now — the owner's ``cols`` for the warm tier, an ``.npz`` for cold."""

    __slots__ = ("entry", "nbytes", "path")

    def __init__(self, entry, nbytes: int, path: str | None = None):
        self.entry = entry
        self.nbytes = int(nbytes)
        self.path = path


class TieringPolicy:
    """HBM → pinned host RAM → disk residency ladder (module docstring).
    Attach via ``pool.attach_tiering(policy)``; the pool offers evicted
    and reclaimed entries to :meth:`demote_entry` and consults
    :meth:`take` on donation-stash misses."""

    def __init__(self, ram_budget: int | None = None,
                 disk_dir: str | None = None):
        if ram_budget is None:
            ram_budget = _env_int(TIER_RAM_ENV)
        if disk_dir is None:
            disk_dir = os.environ.get(TIER_DIR_ENV) or None
        self.ram_budget = ram_budget
        self.disk_dir = disk_dir
        self._lock = threading.Lock()  # leaf: tier maps + counters only
        # (type, index, fingerprint) -> _Tiered, LRU order
        self._warm: "OrderedDict[tuple, _Tiered]" = OrderedDict()
        self._cold: "OrderedDict[tuple, _Tiered]" = OrderedDict()
        self._pool_ref = None
        self.demotions_ram = 0
        self.demotions_disk = 0
        self.promotions = 0
        self.drops = 0
        _POLICIES.add(self)

    def bind_pool(self, pool) -> None:
        self._pool_ref = weakref.ref(pool)

    # -- cost-driven victim choice ---------------------------------------------
    @staticmethod
    def _cost(type_name: str, index: str) -> float:
        """How much the cost table says this index's plans are worth
        (strategy-level ``predict_prefix``): the RAM victim is the entry
        worth LEAST — cheap plans re-stage cheaply."""
        from geomesa_tpu.obs import devmon

        p = devmon.costs().predict_prefix(type_name, f"{index}:")
        if p is None:
            return 0.0
        return float(p.get("wall_ms_p50") or 0.0)

    # -- demotion (the pool's eviction seam) -----------------------------------
    def demote_entry(self, e) -> bool:
        """HBM → RAM: export the owner's device columns to host arrays
        IN PLACE (the owner object stays alive holding them — that is
        the pin), unregister its ledger bytes, and park it in the warm
        tier. Overflow pushes the least-valuable warm entries to disk.
        Returns False (caller frees normally) when the owner has no
        exportable columns."""
        owner = e.owner
        cols = getattr(owner, "cols", None)
        if not isinstance(cols, dict) or not cols:
            return False
        try:
            host = {k: np.asarray(v) for k, v in cols.items()}
        except Exception:  # noqa: BLE001 — unexportable arrays: free normally
            return False
        nbytes = sum(int(a.nbytes) for a in host.values())
        owner.cols = host
        # the bytes leave the device NOW (last dispatch ref notwith-
        # standing) while the owner lives on: the finalizer path cannot
        # unregister, so the explicit one must
        from geomesa_tpu.obs import devmon

        devmon.ledger().unregister_matching(e.type_name, e.index)
        key = (e.type_name, e.index, e.fingerprint)
        overflow: list[tuple] = []
        with self._lock:
            self._warm[key] = _Tiered(e, nbytes)
            self._warm.move_to_end(key)
            self.demotions_ram += 1
            if self.ram_budget is not None:
                while (sum(t.nbytes for t in self._warm.values())
                       > self.ram_budget and self._warm):
                    vk = min(
                        self._warm,
                        key=lambda k: (self._cost(k[0], k[1]),
                                       list(self._warm).index(k)))
                    overflow.append((vk, self._warm.pop(vk)))
        for vk, t in overflow:
            self._spill_to_disk(vk, t)
        return True

    def _spill_to_disk(self, key: tuple, t: _Tiered) -> None:
        """RAM → disk (or drop, when no ``GEOMESA_TPU_TIER_DIR``): the
        owner's host arrays move to an ``.npz`` and its ``cols`` empties
        — the RAM frees, the entry stays promotable."""
        if not self.disk_dir:
            with self._lock:
                self.drops += 1
            return
        type_name, index, fingerprint = key
        owner = t.entry.owner
        os.makedirs(self.disk_dir, exist_ok=True)
        path = os.path.join(
            self.disk_dir,
            f"tier-{type_name}-{index}-{fingerprint}.npz".replace(
                os.sep, "_"))
        try:
            np.savez(path, **{k: np.asarray(v)
                              for k, v in owner.cols.items()})
        except OSError:
            with self._lock:
                self.drops += 1  # a full disk degrades to a plain drop
            return
        owner.cols = {}
        with self._lock:
            self._cold[key] = _Tiered(t.entry, t.nbytes, path)
            self.demotions_disk += 1

    # -- promotion (the pool's take_donated miss seam) -------------------------
    def take(self, type_name: str, index: str, fingerprint):
        """Promote one demoted entry back to the device (disk → RAM →
        HBM as needed); returns the pool ``_Entry`` ready to re-install,
        or None. Ledger bytes re-register here — residency and reporting
        move together in both directions."""
        if fingerprint is None:
            return None
        key = (type_name, index, fingerprint)
        with self._lock:
            t = self._warm.pop(key, None)
            if t is None:
                t = self._cold.pop(key, None)
        if t is None:
            return None
        e = t.entry
        owner = e.owner
        if t.path is not None:
            try:
                with np.load(t.path) as z:
                    owner.cols = {k: _to_device(z[k]) for k in z.files}
                os.unlink(t.path)
            except OSError:
                with self._lock:
                    self.drops += 1
                return None
        else:
            owner.cols = {k: _to_device(v) for k, v in owner.cols.items()}
        from geomesa_tpu.obs import devmon

        led = devmon.ledger()
        for group, nbytes in e.groups.items():
            led.register(type_name, index, group, nbytes, owner=owner)
        with self._lock:
            self.promotions += 1
        return e

    def invalidate(self, type_name: str, keep_fingerprint=None) -> None:
        """Drop demoted entries of ``type_name`` whose fingerprint is
        not ``keep_fingerprint`` — ALL of them when it is None (the
        pool's ``release``/``purge`` discipline: a changed main tier
        makes them unpromotable)."""
        drop: list[_Tiered] = []
        with self._lock:
            for bucket in (self._warm, self._cold):
                for k in [k for k in bucket
                          if k[0] == type_name
                          and (keep_fingerprint is None
                               or k[2] != keep_fingerprint)]:
                    drop.append(bucket.pop(k))
                    self.drops += 1
        for t in drop:
            if t.path is not None:
                try:
                    os.unlink(t.path)
                except OSError:
                    pass

    # -- read surface ----------------------------------------------------------
    def tier_bytes(self) -> dict:
        """``{tier: {type: bytes}}`` for the warm and cold tiers (the
        HBM tier is the pool/ledger's to report)."""
        out: dict = {"ram": {}, "disk": {}}
        with self._lock:
            for (tn, _i, _f), t in self._warm.items():
                out["ram"][tn] = out["ram"].get(tn, 0) + t.nbytes
            for (tn, _i, _f), t in self._cold.items():
                out["disk"][tn] = out["disk"].get(tn, 0) + t.nbytes
        return out

    def coherence_violations(self) -> list[str]:
        """The invariant sweeper's tier-coherence check
        (``check_tiering``): no entry in two tiers at once, the warm
        tier inside its budget, cold files present on disk, and no
        demoted (type, index) still reporting device bytes in the
        ledger unless a FRESH load legitimately re-registered it."""
        from geomesa_tpu.obs import devmon

        out: list[str] = []
        with self._lock:
            warm = dict(self._warm)
            cold = dict(self._cold)
        for key in set(warm) & set(cold):
            out.append(f"{key}: present in both ram and disk tiers")
        if self.ram_budget is not None:
            wb = sum(t.nbytes for t in warm.values())
            if wb > self.ram_budget:
                out.append(
                    f"ram tier {wb} B over budget {self.ram_budget} B")
        for key, t in cold.items():
            if t.path is None or not os.path.exists(t.path):
                out.append(f"{key}: cold entry missing its on-disk file")
        pool = self._pool_ref() if self._pool_ref is not None else None
        live = set()
        if pool is not None:
            with pool._lock:
                live = set(pool._entries)
        res = devmon.ledger().resident()
        for (tn, idx, _f) in {*warm, *cold}:
            if (tn, idx) in live:
                continue  # a fresh load owns the ledger rows now
            if res.get(tn, {}).get(idx):
                out.append(
                    f"{tn}.{idx}: demoted but still ledgered on device")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ram_budget_bytes": self.ram_budget,
                "disk_dir": self.disk_dir,
                "warm_entries": len(self._warm),
                "warm_bytes": sum(t.nbytes for t in self._warm.values()),
                "cold_entries": len(self._cold),
                "cold_bytes": sum(t.nbytes for t in self._cold.values()),
                "demotions_ram": self.demotions_ram,
                "demotions_disk": self.demotions_disk,
                "promotions": self.promotions,
                "drops": self.drops,
            }
