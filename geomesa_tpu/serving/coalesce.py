"""Request coalescing — concurrent compatible queries share ONE device
dispatch.

Batch-parallel predicate evaluation is where the accelerator wins
(PAPERS.md: many-core geospatial processing; the same insight behind the
ISSUE 8 subscription matrix): ``DataStore.select_many`` answers N
queries in two device dispatches, but the web tier was dispatching every
concurrent HTTP query as its own device problem. The
:class:`Coalescer` closes that gap with a batch-window collector:

- the FIRST request for an idle ``(type, op, auth-scope)`` key opens a
  batch and dispatches it IMMEDIATELY — sparse traffic pays zero added
  latency;
- requests arriving while a dispatch for their key is already in
  flight gather into the NEXT batch (backpressure batching): its
  leader waits for the in-flight dispatch to complete — capped at the
  coalesce window (``~1-5 ms``, ``GEOMESA_TPU_COALESCE_MS``) — then
  runs the whole gathered batch as ONE ``select_many`` /
  ``count_many`` / ``aggregate_many`` call and demultiplexes results
  (or the error) back to every waiter. Under sustained concurrency the
  steady state is one batched dispatch per round trip, width = the
  arrival rate × dispatch time, with the window only bounding the
  worst-case added wait;
- per-query auths / hints / deadlines are preserved: queries ride the
  batch as full ``Query`` objects (the store's batched paths apply
  visibility and reduce semantics per query), and a query whose
  deadline cannot survive the window **bypasses** it and executes
  immediately;
- per-query tenant attribution survives batching: the submitter's
  request-context tenant is stamped into ``hints["tenant"]`` before the
  query joins the batch, so the store's ``_audit`` meters EACH member
  query against ITS tenant even though the dispatch runs on the
  leader's thread (pinned in tests/test_serving.py).

Observability: coalesce width rides the ``serving.coalesce.width``
histogram (dispatches = its count, queries = its sum — fewer dispatches
than queries is the win), bypasses/orphans are counters, and every
request's span gets a ``coalesced`` event with the batch width.

Locking: one leaf lock guards the open-batch table (metrics tier in
docs/concurrency.md). The leader's window sleep, the batched store
call, and every ``Event.wait`` run strictly OUTSIDE it. No jax.
"""

from __future__ import annotations

import os
import threading
from dataclasses import replace

__all__ = ["COALESCE_MS_ENV", "Coalescer", "env_window_s"]

COALESCE_MS_ENV = "GEOMESA_TPU_COALESCE_MS"
_DEFAULT_MS = 2.0
# a deadline shorter than this many windows bypasses coalescing: the
# window sleep must never be the thing that blows a tight budget
_DEADLINE_BYPASS_FACTOR = 2.0


def env_window_s() -> float:
    """The configured coalesce window in seconds (<= 0 disables)."""
    try:
        ms = float(os.environ.get(COALESCE_MS_ENV, _DEFAULT_MS))
    except ValueError:
        ms = _DEFAULT_MS
    return max(ms, 0.0) / 1000.0


class _Slot:
    __slots__ = ("q", "result", "error")

    def __init__(self, q):
        self.q = q
        self.result = None
        self.error = None


class _Batch:
    __slots__ = ("items", "done", "go", "width")

    def __init__(self):
        self.items: list[_Slot] = []
        self.done = threading.Event()
        # leader release: set at creation when the key is idle
        # (immediate dispatch), else by the in-flight dispatch
        # completing — the window caps the wait either way
        self.go = threading.Event()
        self.width = 0


class Coalescer:
    """Batch-window collector over one store.

    ``submit(type_name, op, q)`` returns exactly what the uncoalesced
    call would: op ``select`` → a ``QueryResult`` (==
    ``store.query(type_name, q)``), ``count`` → a number, ``aggregate``
    → one aggregation record or None. A store without the batched
    surface executes singly (no window sleep)."""

    OPS = ("select", "count", "aggregate")

    def __init__(self, store, window_s: float | None = None, metrics=None,
                 wait_timeout_s: float = 30.0):
        self.store = store
        self.window_s = env_window_s() if window_s is None else window_s
        if metrics is None:
            metrics = getattr(store, "metrics", None)
        if metrics is None:
            from geomesa_tpu.utils.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        self.wait_timeout_s = wait_timeout_s
        self._lock = threading.Lock()  # leaf: open-batch table only
        self._open: dict[tuple, _Batch] = {}
        self._inflight: set[tuple] = set()  # keys mid-dispatch
        # plain counters for the acceptance math (dispatches < queries)
        self.dispatch_count = 0
        self.query_count = 0
        self.max_width = 0

    # -- batch-key compatibility ----------------------------------------------
    @staticmethod
    def _key(type_name: str, op: str, q, kwargs: dict) -> tuple:
        # auth scope is part of compatibility: queries under different
        # visibility must never share a batch — a remote-backed
        # select_many fails CLOSED on mixed auths (blast radius), and
        # scope-homogeneous batches keep that contract moot
        auths = (None if q.auths is None
                 else tuple(sorted(set(q.auths))))
        if op == "count":
            return (type_name, op, auths, bool(kwargs.get("loose", True)))
        if op == "aggregate":
            gb = kwargs.get("group_by")
            return (type_name, op, auths,
                    tuple(gb) if gb else None,
                    tuple(kwargs.get("value_cols") or ()),
                    kwargs.get("now_ms"))
        return (type_name, op, auths)

    def _batch_fn(self, op: str):
        if op == "select":
            return getattr(self.store, "select_many", None)
        if op == "count":
            return getattr(self.store, "count_many", None)
        if op == "aggregate":
            return getattr(self.store, "aggregate_many", None)
        raise ValueError(f"unknown coalesce op {op!r}")

    # -- the request path -----------------------------------------------------
    def submit(self, type_name: str, op: str, q, **kwargs):
        """One request's query. Blocks until ITS result is ready (at
        most window + batched-dispatch time) and returns it; the
        leader's store error propagates to every batchmate."""
        from geomesa_tpu import obs

        fn = self._batch_fn(op)
        if fn is None or self.window_s <= 0:
            return self._single(type_name, op, q, fn, kwargs)
        deadline = q.hints.get("deadline") if q.hints else None
        if (
            deadline is not None
            and deadline.remaining_s()
            < self.window_s * _DEADLINE_BYPASS_FACTOR
        ):
            # the window would eat a meaningful slice of the remaining
            # budget: execute immediately, never coalesce
            self.metrics.counter("serving.coalesce.bypass_deadline").inc()
            obs.event("coalesce_bypass", reason="deadline")
            return self._single(type_name, op, q, fn, kwargs)
        q = self._stamp_tenant(q)
        key = self._key(type_name, op, q, kwargs)
        slot = _Slot(q)
        with self._lock:
            batch = self._open.get(key)
            leader = batch is None
            if leader:
                batch = self._open[key] = _Batch()
                if key not in self._inflight:
                    # idle key: dispatch immediately, zero added latency
                    batch.go.set()
            batch.items.append(slot)
        if leader:
            # gather while any in-flight dispatch for this key drains;
            # the window caps the wait (go fires early on completion,
            # and was pre-set when the key was idle)
            batch.go.wait(self.window_s)
            with self._lock:
                if self._open.get(key) is batch:
                    del self._open[key]
                self._inflight.add(key)
            try:
                # the leader's thread runs the batched dispatch: the
                # store's own spans (select_many + per-query children)
                # land in ITS trace tree
                self._execute(type_name, op, batch, kwargs)
            finally:
                with self._lock:
                    self._inflight.discard(key)
                    nxt = self._open.get(key)
                if nxt is not None:
                    # release the batch that gathered behind us — the
                    # backpressure handoff (outside every lock)
                    nxt.go.set()
            obs.event("coalesced", width=batch.width, op=op, leader=True)
        else:
            # the follower's tree still shows ITS query: a span whose
            # duration is the wait for the shared dispatch (this
            # request's real store latency), carrying the coalesce
            # linkage as an event
            with obs.span("query", coalesced=True, op=op):
                if not batch.done.wait(self.wait_timeout_s):
                    # defensive: a wedged leader must not strand the
                    # request — fall back to a single execution (counted)
                    self.metrics.counter("serving.coalesce.orphaned").inc()
                    return self._single(type_name, op, q, fn, kwargs)
                obs.event("coalesced", width=batch.width, op=op,
                          leader=False)
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _stamp_tenant(self, q):
        """Resolve the submitter's tenant AND trace NOW (its request
        context) and pin them on the query: the batched dispatch runs on
        the leader's thread, whose ambient tenant must not absorb the
        whole batch's usage attribution — and whose batch span must not
        claim every follower's lens exemplar (the stamped trace_id keeps
        each coalesced query's exemplar resolvable to the SUBMITTER's
        stitched tree, disjoint from the leader's)."""
        from geomesa_tpu import obs
        from geomesa_tpu.obs import usage as _usage

        extra = {}
        if not (q.hints and q.hints.get("tenant")):
            extra["tenant"] = _usage.current_tenant()
        if not (q.hints and q.hints.get("trace_id")):
            sp = obs.current()
            if sp is not None and sp.trace_id:
                extra["trace_id"] = sp.trace_id
        if not extra:
            return q
        return replace(q, hints={**(q.hints or {}), **extra})

    def _single(self, type_name: str, op: str, q, fn, kwargs):
        """Uncoalesced execution (store lacks the batched op, window
        off, deadline bypass, or orphaned waiter)."""
        if op == "select":
            # the ordinary query path: full individual plan/audit
            return self.store.query(type_name, q)
        if fn is not None:
            return self._dispatch(type_name, op, fn, [q], kwargs)[0]
        if op == "count":
            return self.store.query(type_name, q).count
        raise ValueError(
            f"store has no batched surface for op {op!r}")

    def _dispatch(self, type_name: str, op: str, fn, qs: list, kwargs):
        if op == "select":
            return fn(type_name, qs)
        if op == "count":
            return fn(type_name, qs, loose=bool(kwargs.get("loose", True)))
        return fn(
            type_name, qs,
            group_by=kwargs.get("group_by"),
            value_cols=kwargs.get("value_cols", ()),
            now_ms=kwargs.get("now_ms"),
        )

    def _execute(self, type_name: str, op: str, batch: _Batch,
                 kwargs: dict) -> None:
        """The leader's half: ONE batched store call, results (or the
        error) demultiplexed to every slot. Runs outside every lock."""
        batch.width = len(batch.items)
        self.metrics.histogram("serving.coalesce.width").update(batch.width)
        self.metrics.counter("serving.coalesce.dispatches").inc()
        self.metrics.counter("serving.coalesce.queries").inc(batch.width)
        with self._lock:
            self.dispatch_count += 1
            self.query_count += batch.width
            if batch.width > self.max_width:
                self.max_width = batch.width
        fn = self._batch_fn(op)
        try:
            if op == "select" and batch.width == 1:
                # nothing coalesced: run the ordinary query path so the
                # single query keeps its full individual plan/audit
                # (batched dispatches deliberately don't feed the
                # adaptive planner's cost table — a width-1 batch must
                # not starve it). Results are identical either way.
                results = [self.store.query(type_name, batch.items[0].q)]
            else:
                results = self._dispatch(
                    type_name, op, fn, [s.q for s in batch.items], kwargs)
            for slot, r in zip(batch.items, results):
                slot.result = r
        except BaseException as e:  # noqa: BLE001 — every waiter gets it
            for slot in batch.items:
                slot.error = e
        finally:
            batch.done.set()
        if batch.items and batch.items[0].error is not None:
            raise batch.items[0].error
