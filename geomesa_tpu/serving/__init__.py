"""geomesa_tpu.serving — the production serving plane (ROADMAP item 4).

Three cooperating pieces in front of the store tier (docs/serving.md):

- :mod:`~geomesa_tpu.serving.admission` — per-tenant admission control:
  token buckets whose refill rate is tied to the tenant's SLO error
  budget (read live from the :mod:`geomesa_tpu.obs.usage` meter's
  ``tenant.query`` objective), with priority classes so the lowest
  priority sheds first under burn. Rejected requests answer
  ``429 Too Many Requests`` + ``Retry-After``.
- :mod:`~geomesa_tpu.serving.coalesce` — request coalescing: a
  batch-window collector that groups concurrent compatible queries per
  ``(type, op)`` into ONE ``DataStore.select_many`` / ``count_many`` /
  ``aggregate_many`` device dispatch and demultiplexes the results back
  to each waiting request thread — batch-parallel predicate evaluation
  is where the accelerator wins (PAPERS.md), so N concurrent HTTP
  queries should share one dispatch, not pay N serialized ones.
- :mod:`~geomesa_tpu.serving.shards` — sharded federation: a
  consistent-hash shard router keyed by Z-prefix (reusing
  :mod:`geomesa_tpu.store.splitter` splits) over N federated members,
  so writes AND reads both partition; reads fan out only to the members
  whose shards a plan's ranges intersect and merge through the
  :class:`~geomesa_tpu.store.merged.MergedDataStoreView` machinery
  (resilience / degraded semantics intact). Routing is generational
  (:class:`~geomesa_tpu.serving.shards.RouterGeneration`): every shard
  map is immutable and changes install atomically as a new generation.
- :mod:`~geomesa_tpu.serving.elastic` — the elasticity plane on top of
  the federation: :class:`~geomesa_tpu.serving.elastic.ShardMigrator`
  (WAL-backed zero-downtime live shard movement),
  :class:`~geomesa_tpu.serving.elastic.FederationAutoscaler`
  (SLO/admission/HBM-driven membership proposals), and
  :class:`~geomesa_tpu.serving.elastic.TieringPolicy` (HBM → host RAM →
  disk buffer demotion for the buffer pool).

Admission and coalescing import no jax (``GEOMESA_TPU_NO_JAX=1`` safe);
the shard router sits on the store tier. All serving locks are leaves of
the canonical hierarchy (docs/concurrency.md) except the migrator lock,
which nests above the store locks it drives.
"""

from geomesa_tpu.serving.admission import (  # noqa: F401 — public surface
    AdmissionController,
    AdmissionDecision,
    PRIORITIES,
    PRIORITY_HEADER,
)
from geomesa_tpu.serving.coalesce import Coalescer  # noqa: F401

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "Coalescer",
    "PRIORITIES",
    "PRIORITY_HEADER",
]
