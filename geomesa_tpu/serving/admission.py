"""Per-tenant admission control — the serving plane's front gate.

The north star is heavy multi-tenant traffic; until now the web tier
admitted everything and let deadlines blow downstream. This module sheds
at the door instead: every query-serving request passes one
:class:`AdmissionController` check keyed by the caller's tenant
(``X-Geomesa-Tenant``) and priority class (``X-Geomesa-Priority``),
answering ``429 Too Many Requests`` + ``Retry-After`` when the tenant is
over its rate.

Mechanics (docs/serving.md § Admission):

- One token bucket per tenant: capacity ``burst`` tokens, refilled at
  ``rate_qps`` tokens/second **scaled by the tenant's live SLO error
  budget** — ``effective_rate = max(min_rate_qps, rate_qps *
  budget_remaining)`` where ``budget_remaining`` is the ``tenant.query``
  objective's 5-minute error budget read from the usage meter's SLO
  engine (the ISSUE 11 substrate). A tenant burning its budget refills
  slowly and sheds under load; a healthy tenant refills at full rate.
  The feedback loop is stable by construction: sheds do NOT burn the
  tenant's SLO (they are metered with ``slo=False``), so a shed tenant's
  budget recovers as its bad queries age out of the window.
- Priority classes ``high`` / ``normal`` / ``low``: each class reserves
  a fraction of the bucket it may not draw below (``low`` 30 %,
  ``normal`` 10 %, ``high`` 0 %), so under pressure the lowest-priority
  traffic sheds FIRST and a high-priority request is never shed while
  low-priority traffic is still being admitted — shedding order is a
  structural property of the thresholds, not a scheduling race.
- Every decision lands in the metrics registry
  (``serving.admission.{admitted,shed}[.<priority>]`` counters), shed
  decisions additionally land in the usage meter (signature
  ``admission.shed``, no SLO burn) and the flight recorder (anomaly
  ``shed``), and the controller's own labeled exposition
  (``geomesa_admission_*`` series, tenant labels bounded to the top-K
  shedders + an ``other`` rollup) rides
  ``GET /api/metrics?format=prometheus``.

Determinism: ``clock`` is injectable (monotonic seconds), so refill and
Retry-After math is testable without real sleeps.

Locking: one leaf lock guards the bucket table and counters (metrics
tier in docs/concurrency.md). The SLO budget read happens strictly
BEFORE the lock is taken (the engine owns its own leaf lock); nothing
blocking ever runs under ours. No jax anywhere.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

__all__ = [
    "ADMIT_BURST_ENV", "ADMIT_MIN_RATE_ENV", "ADMIT_RATE_ENV",
    "AdmissionController", "AdmissionDecision", "PRIORITIES",
    "PRIORITY_HEADER",
]

# the caller's priority-class assertion (same proxy-trust posture as
# X-Geomesa-Tenant: the fronting proxy owns it); WSGI spells it
# HTTP_X_GEOMESA_PRIORITY
PRIORITY_HEADER = "X-Geomesa-Priority"

PRIORITIES = ("high", "normal", "low")
# fraction of the bucket each class may not draw below: low sheds first,
# high drains the bucket to zero before it ever sheds
_RESERVE = {"high": 0.0, "normal": 0.10, "low": 0.30}

ADMIT_RATE_ENV = "GEOMESA_TPU_ADMIT_RATE"        # tokens/s per tenant
ADMIT_BURST_ENV = "GEOMESA_TPU_ADMIT_BURST"      # bucket capacity
ADMIT_MIN_RATE_ENV = "GEOMESA_TPU_ADMIT_MIN_RATE"  # refill floor under burn


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass(frozen=True)
class AdmissionDecision:
    """One admit/shed verdict. ``retry_after_s`` is meaningful only when
    shed: the time until the caller's priority class crosses back over
    its reserve threshold at the CURRENT refill rate."""

    admitted: bool
    tenant: str
    priority: str
    retry_after_s: float = 0.0
    reason: str = "ok"  # "ok" | "rate" (bucket below the class reserve)
    tokens: float = 0.0


class _Bucket:
    """One tenant's token bucket. Mutation is guarded by the OWNING
    controller's lock."""

    __slots__ = ("tokens", "refilled_at", "last_seen", "admitted", "shed")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.refilled_at = now
        self.last_seen = now
        self.admitted = 0
        self.shed = 0


class AdmissionController:
    """Process-wide per-tenant admission control.

    ``meter``: the :class:`~geomesa_tpu.obs.usage.UsageMeter` whose
    ``tenant.query`` SLO objective supplies the live budget signal
    (default: the process meter). ``admit`` is the hot path: one SLO
    budget read (the engine's own leaf lock) + one lock acquisition for
    the bucket update; shed side effects (flight record, usage counter)
    run strictly outside the lock.
    """

    def __init__(self, rate_qps: float | None = None,
                 burst: float | None = None,
                 min_rate_qps: float | None = None,
                 meter=None, metrics=None, max_tenants: int = 256,
                 slo_window_s: float = 300.0, clock=time.monotonic):
        self.rate_qps = (rate_qps if rate_qps is not None
                         else _env_float(ADMIT_RATE_ENV, 50.0))
        self.burst = (burst if burst is not None
                      else _env_float(ADMIT_BURST_ENV, 2.0 * self.rate_qps))
        self.min_rate_qps = (min_rate_qps if min_rate_qps is not None
                             else _env_float(ADMIT_MIN_RATE_ENV, 1.0))
        if self.rate_qps <= 0 or self.burst <= 0:
            raise ValueError("rate_qps and burst must be > 0")
        self.min_rate_qps = min(max(self.min_rate_qps, 1e-6), self.rate_qps)
        if meter is None:
            from geomesa_tpu.obs import usage as _usage

            meter = _usage.get()
        self.meter = meter
        self.metrics = metrics
        self.max_tenants = max(int(max_tenants), 2)
        self.slo_window_s = slo_window_s
        self._clock = clock
        self._lock = threading.Lock()  # leaf: bucket table + counters
        self._buckets: dict[str, _Bucket] = {}
        # evicted tenants' decision totals fold here (bounded exposition)
        self._other_admitted = 0
        self._other_shed = 0
        self.admitted_count = 0
        self.shed_count = 0
        # per-priority totals owned HERE (not read back from an optional
        # external registry: the exposition must stay internally
        # consistent with the unlabeled totals on the same scrape)
        self._pri_admitted = dict.fromkeys(PRIORITIES, 0)
        self._pri_shed = dict.fromkeys(PRIORITIES, 0)

    # -- the live SLO signal --------------------------------------------------
    def budget_remaining(self, tenant: str) -> float:
        """The tenant's ``tenant.query`` error budget left in the
        controller's window, in [0, 1] (1.0 = untouched)."""
        tk = self.meter.slo.tracker("tenant.query", tenant)
        return tk.budget_remaining(self.slo_window_s)

    def effective_rate(self, tenant: str) -> float:
        """Refill rate for this tenant right now: full rate scaled by
        budget remaining, floored at ``min_rate_qps`` so a fully burned
        tenant still trickles back instead of locking out forever."""
        return max(self.min_rate_qps, self.rate_qps
                   * self.budget_remaining(tenant))

    # -- the hot path ---------------------------------------------------------
    def admit(self, tenant: str | None, priority: str = "normal",
              cost: float = 1.0) -> AdmissionDecision:
        """Gate one request. Unknown priorities are treated as
        ``normal`` (a bad header must not become a privilege escalation
        OR a denial)."""
        from geomesa_tpu.obs.usage import DEFAULT_TENANT

        t = str(tenant) if tenant else DEFAULT_TENANT
        p = priority.strip().lower() if priority else "normal"
        if p not in _RESERVE:
            p = "normal"
        # SLO budget read BEFORE our lock (the engine owns its own leaf)
        rate = self.effective_rate(t)
        reserve = _RESERVE[p] * self.burst
        now = self._clock()
        with self._lock:
            b = self._buckets.get(t)
            if b is None:
                b = self._buckets[t] = _Bucket(self.burst, now)
                if len(self._buckets) > self.max_tenants:
                    self._evict_locked(keep=t)
            dt = now - b.refilled_at
            if dt > 0:
                b.tokens = min(self.burst, b.tokens + dt * rate)
                b.refilled_at = now
            b.last_seen = now
            if b.tokens - cost >= reserve:
                b.tokens -= cost
                b.admitted += 1
                self.admitted_count += 1
                self._pri_admitted[p] += 1
                decision = AdmissionDecision(True, t, p, tokens=b.tokens)
            else:
                b.shed += 1
                self.shed_count += 1
                self._pri_shed[p] += 1
                retry = (reserve + cost - b.tokens) / rate
                decision = AdmissionDecision(
                    False, t, p, retry_after_s=max(retry, 1e-3),
                    reason="rate", tokens=b.tokens)
        # side effects strictly OUTSIDE the lock
        self._note(decision)
        return decision

    def _evict_locked(self, keep: str) -> None:
        """Fold the least-recently-seen bucket (never ``keep``) into the
        ``other`` rollup — an unbounded tenant-id stream cannot grow the
        table or the exposition."""
        victim_t = min(
            (t for t in self._buckets if t != keep),
            key=lambda t: self._buckets[t].last_seen,
            default=None,
        )
        if victim_t is not None:
            v = self._buckets.pop(victim_t)
            self._other_admitted += v.admitted
            self._other_shed += v.shed

    def _note(self, d: AdmissionDecision) -> None:
        m = self.metrics
        if m is not None:
            if d.admitted:
                m.counter("serving.admission.admitted").inc()
                m.counter(f"serving.admission.admitted.{d.priority}").inc()
            else:
                m.counter("serving.admission.shed").inc()
                m.counter(f"serving.admission.shed.{d.priority}").inc()
        if d.admitted:
            return
        # a shed decision is an operator-facing anomaly AND a usage
        # signal: meter it against the tenant WITHOUT burning its SLO
        # (shed feedback into the budget would lock the tenant out)
        self.meter.observe(
            d.tenant, "", "admission.shed", rows=0, wall_ms=0.0,
            slo=False,
        )
        from geomesa_tpu.obs import flight as _flight

        _flight.record(
            op="admission", type_name="", source="serving",
            plan=f"shed priority={d.priority} "
                 f"retry_after={d.retry_after_s:.3f}s",
            latency_ms=0.0, rows=0, tenant=d.tenant,
            anomalies=(_flight.A_SHED,),
        )

    # -- read surfaces --------------------------------------------------------
    def snapshot(self, limit: int | None = None) -> dict:
        """The JSON surface (``/api/metrics`` ``admission`` section)."""
        with self._lock:
            rows = sorted(
                self._buckets.items(),
                key=lambda kv: (-kv[1].shed, -kv[1].admitted, kv[0]),
            )
            if limit is not None:
                rows = rows[:limit]
            tenants = [
                {"tenant": t, "admitted": b.admitted, "shed": b.shed,
                 "tokens": round(b.tokens, 3)}
                for t, b in rows
            ]
            out = {
                "rate_qps": self.rate_qps,
                "burst": self.burst,
                "min_rate_qps": self.min_rate_qps,
                "admitted": self.admitted_count,
                "shed": self.shed_count,
                "tenant_count": len(self._buckets),
                "other": {"admitted": self._other_admitted,
                          "shed": self._other_shed},
                "tenants": tenants,
            }
        for t in out["tenants"]:
            t["budget_remaining"] = round(
                self.budget_remaining(t["tenant"]), 4)
        return out

    def prometheus_lines(self, prefix: str = "geomesa", k: int = 16) -> list:
        """``geomesa_admission_*`` series: per-priority totals (3 label
        values each) plus per-tenant shed counters bounded to the top-K
        shedders + an ``other`` rollup (the usage meter's cardinality
        posture)."""
        from geomesa_tpu.obs.usage import escape_label

        with self._lock:
            if not self._buckets and not (self._other_admitted
                                          or self._other_shed):
                return []
            per_pri_admit = dict(self._pri_admitted)
            per_pri_shed = dict(self._pri_shed)
            ranked = sorted(self._buckets.items(),
                            key=lambda kv: (-kv[1].shed, kv[0]))
            top, rest = ranked[:k], ranked[k:]
            shed_rows = [(t, b.shed) for t, b in top]
            other_shed = self._other_shed + sum(b.shed for _, b in rest)
            admitted, shed = self.admitted_count, self.shed_count
        lines = [f"# TYPE {prefix}_admission_admitted_total counter"]
        lines.append(f"{prefix}_admission_admitted_total {admitted}")
        for p in PRIORITIES:
            lines.append(
                f'{prefix}_admission_admitted_priority_total'
                f'{{priority="{p}"}} {per_pri_admit[p]}')
        lines.append(f"# TYPE {prefix}_admission_shed_total counter")
        lines.append(f"{prefix}_admission_shed_total {shed}")
        for p in PRIORITIES:
            lines.append(
                f'{prefix}_admission_shed_priority_total'
                f'{{priority="{p}"}} {per_pri_shed[p]}')
        lines.append(f"# TYPE {prefix}_admission_shed_tenant_total counter")
        for t, n in shed_rows:
            lines.append(
                f'{prefix}_admission_shed_tenant_total'
                f'{{tenant="{escape_label(t)}"}} {n}')
        lines.append(
            f'{prefix}_admission_shed_tenant_total{{tenant="other"}} '
            f'{other_shed}')
        return lines

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        lines = self.prometheus_lines(prefix)
        return "\n".join(lines) + "\n" if lines else ""
