"""Sharded federation — a consistent-hash shard router keyed by Z-prefix.

ROADMAP item 4's horizontal story: one store per host stops scaling when
the working set outgrows one device's HBM. This module partitions a
feature type across N federated members by Z2 key prefix (the same key
domain :mod:`geomesa_tpu.store.splitter` seeds device shard boundaries
from), so **writes and reads both partition**:

- :class:`ShardRouter` cuts the 62-bit Z2 domain into ``n_shards``
  contiguous key ranges (``splitter.default_splits``) and assigns each
  shard to a member via a consistent-hash ring (members × virtual
  nodes): resizing the member set moves only the departed/arrived
  member's shards, never reshuffles the survivors (docs/serving.md
  § Shard-map lifecycle). An explicit ``assignments`` override pins
  individual shards to members regardless of the ring — the live
  migrator's intermediate maps (``with_assignment``) and the recovery
  journal round-trip through it.
- :class:`RouterGeneration` wraps one immutable router in an epoch: the
  view holds exactly ONE current generation and swaps it atomically
  (``swap_generation``); every operation snapshots the generation ONCE
  and routes entirely off that snapshot, so a concurrent swap can never
  split one write batch (or one query's fan-out + merge) across two
  shard maps. A generation also carries the in-flight
  :class:`ShardMigration` records that make reads fan to the UNION of
  old and new owners and writes dual-apply during a live migration
  (``serving/elastic.py``).
- :class:`ShardedDataStoreView` subclasses
  :class:`~geomesa_tpu.store.merged.MergedDataStoreView`, so the merge,
  resilience (``on_member_error="partial"`` degraded answers), SLO and
  flight-recorder semantics are LITERALLY the merged view's — it only
  narrows the fan-out: a query runs against exactly the members whose
  shards its plan's Z-ranges intersect (``_member_subset``), and writes
  split records by their geometry's Z2 key (fid hash for geometry-less
  rows) so each row lives on exactly ONE member.

Member dedup is load-bearing: several shards routinely map to the same
member (n_shards > n_members by design), and two overlapping Z-prefix
ranges landing on one member must fan out to it ONCE — a per-shard
fan-out would double-count every matching row on that member
(red/green pinned in tests/test_serving.py). During a migration's
dual-apply window the same machinery absorbs the old/new-owner union
fan: row results additionally dedup by fid at the merge (both owners
hold the dual-applied rows), while additive reads (counts, stats,
aggregations, density) keep fanning to the AUTHORITATIVE owner only —
a union would double-count every dual-applied row.

Fid- and attribute-only filters extract no spatial bounds → they fan
out to ALL members (deterministically — rows are spatially placed, a
fid could live anywhere); disjoint filters fan out to NONE.

The router is immutable after construction (no locks); a generation
adds one Condition guarding its in-flight write refcount (the
migrator's drain barrier, docs/concurrency.md § elastic plane).
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid
from bisect import bisect_right
from contextlib import contextmanager
from dataclasses import replace

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.curve.sfc import Z2SFC
from geomesa_tpu.filter import ast
from geomesa_tpu.filter.bounds import extract
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.resilience.policy import MemberDrainingError
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.store.merged import MergedDataStoreView
from geomesa_tpu.store.splitter import default_splits, shard_of

__all__ = [
    "MIG_DUAL", "MIG_SHIPPING", "RouterGeneration", "ShardMigration",
    "ShardRouter", "ShardedDataStoreView",
]

_Z2_BITS = 62  # 31 bits/dim Morton — the splitter's z2 key domain

# live-migration states carried on a RouterGeneration (docs/serving.md
# § Shard-map lifecycle: stable → shipping → dual-apply → cutover)
MIG_SHIPPING = "shipping"      # snapshot in flight; routing unchanged
MIG_DUAL = "dual_apply"        # writes dual-apply, row reads union-fan


def _hash64(s: str) -> int:
    """Stable 64-bit hash (sha1 prefix): ring placement must not depend
    on PYTHONHASHSEED."""
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8], "big")


class ShardRouter:
    """Z-prefix shard map + consistent-hash member assignment.

    ``members``: hashable member ids (the sharded view uses positional
    indices). ``n_shards`` contiguous Z2 key ranges; each shard's id
    hashes onto the ring and is owned by the first member clockwise.
    ``assignments`` ({shard → member}) pins individual shards over the
    ring's choice — only overrides that actually differ from the ring
    are retained (``self.assignments``), so a pure-ring router always
    reports ``assignments == {}`` no matter how it was built.
    """

    def __init__(self, members, n_shards: int | None = None,
                 virtual_nodes: int = 32, assignments=None):
        self.members = list(members)
        if not self.members:
            raise ValueError("shard router needs at least one member")
        if n_shards is None:
            n_shards = max(8, 4 * len(self.members))
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self.virtual_nodes = int(virtual_nodes)
        self._pos = {m: i for i, m in enumerate(self.members)}
        # shard boundaries: n_shards-1 evenly spaced keys in the 62-bit
        # z2 domain (the device shard-boundary seeding reused at the
        # federation tier)
        self.splits = default_splits("z2", self.n_shards, bits=_Z2_BITS)
        ring = sorted(
            (_hash64(f"{m!r}#{v}"), i)
            for i, m in enumerate(self.members)
            for v in range(self.virtual_nodes)
        )
        self._ring_keys = [h for h, _ in ring]
        self._ring_pos = [i for _, i in ring]
        self.shard_member = [
            self.members[self._locate(_hash64(f"shard:{s}"))]
            for s in range(self.n_shards)
        ]
        self.assignments: dict[int, object] = {}
        if assignments:
            live = set(self.members)
            for s, m in assignments.items():
                s = int(s)
                if not (0 <= s < self.n_shards):
                    raise ValueError(
                        f"assignment for shard {s} outside "
                        f"[0, {self.n_shards})")
                if m not in live:
                    raise ValueError(
                        f"shard {s} assigned to non-member {m!r}")
                if m != self.shard_member[s]:
                    self.shard_member[s] = m
                    self.assignments[s] = m
        self._sfc = Z2SFC()

    def _locate(self, h: int) -> int:
        i = bisect_right(self._ring_keys, h) % len(self._ring_keys)
        return self._ring_pos[i]

    def with_members(self, members) -> "ShardRouter":
        """A new router over a resized member set, same shard cuts: the
        consistent-hash ring guarantees only shards owned by departed
        (or claimed by arrived) members move (pinned in tests). Drops
        any pinned assignments — this is the OFFLINE membership change
        (data does not follow); the migrator composes
        ``with_assignment`` steps for the live one."""
        return ShardRouter(members, self.n_shards, self.virtual_nodes)

    def with_assignment(self, shard: int, member) -> "ShardRouter":
        """Copy with ONE shard reassigned and every other shard pinned
        to its current owner — the migrator's cutover step: exactly one
        shard moves per generation, never a ring reshuffle."""
        pinned = dict(enumerate(self.shard_member))
        pinned[int(shard)] = member
        return ShardRouter(self.members, self.n_shards,
                           self.virtual_nodes, assignments=pinned)

    def with_member_added(self, member) -> "ShardRouter":
        """Copy with one member joined but owning NOTHING yet (every
        shard pinned to its current owner): membership precedes
        ownership, so the autoscaler can add a member and then migrate
        shards onto it one generation at a time."""
        if member in self._pos:
            raise ValueError(f"member {member!r} already present")
        pinned = dict(enumerate(self.shard_member))
        return ShardRouter(self.members + [member], self.n_shards,
                           self.virtual_nodes, assignments=pinned)

    def with_member_removed(self, member) -> "ShardRouter":
        """Copy with one drained member departed. The member must own
        no shards (drain first — the migrator's job); ownership of
        every shard is pinned, so survivors never reshuffle."""
        if member not in self._pos:
            raise ValueError(f"member {member!r} not present")
        owned = [s for s, m in enumerate(self.shard_member) if m == member]
        if owned:
            raise ValueError(
                f"member {member!r} still owns shards {owned}: "
                "drain (migrate) before removal")
        pinned = dict(enumerate(self.shard_member))
        return ShardRouter([m for m in self.members if m != member],
                           self.n_shards, self.virtual_nodes,
                           assignments=pinned)

    def coverage_violations(self) -> list[str]:
        """Invariant-sweep surface (obs/audit.py): the shard cuts must
        partition the 62-bit Z2 domain — strictly increasing in-range
        splits (disjoint AND total by construction of contiguous
        ranges) — and every shard must be owned by exactly one LIVE
        member. Returns violation strings, empty when healthy."""
        out: list[str] = []
        splits = np.asarray(self.splits, dtype=np.int64)
        if len(splits) != self.n_shards - 1:
            out.append(f"{len(splits)} splits for {self.n_shards} shards")
        if len(splits) and not (np.diff(splits) > 0).all():
            out.append("shard splits not strictly increasing "
                       "(ranges overlap or are empty)")
        if len(splits) and (splits[0] < 0
                            or int(splits[-1]) >= (1 << _Z2_BITS)):
            out.append("shard splits outside the 62-bit Z2 domain")
        if len(self.shard_member) != self.n_shards:
            out.append(f"{len(self.shard_member)} owners for "
                       f"{self.n_shards} shards")
        live = set(self.members)
        for s, m in enumerate(self.shard_member):
            if m not in live:
                out.append(f"shard {s} owned by departed member {m!r}")
        for s in self.assignments:
            if not (0 <= int(s) < self.n_shards):
                out.append(f"pinned assignment for unknown shard {s}")
        return out

    # -- key → shard → member -------------------------------------------------
    def keys_for(self, x, y) -> np.ndarray:
        """Z2 keys for point coordinates (the write-partition keying)."""
        return self._sfc.index(np.asarray(x, dtype=np.float64),
                               np.asarray(y, dtype=np.float64))

    def fid_key(self, fid: str) -> int:
        """Deterministic key for a geometry-less row: fid hash folded
        into the 62-bit shard domain."""
        return _hash64(f"fid:{fid}") >> 2

    def shards_of_keys(self, keys) -> np.ndarray:
        z = np.asarray(keys, dtype=np.uint64).astype(np.int64)
        return shard_of(z, self.splits)

    def member_for_shard(self, shard: int):
        return self.shard_member[int(shard)]

    def shards_of_member(self, member) -> list[int]:
        return [s for s, m in enumerate(self.shard_member) if m == member]

    # -- plan-range → shard intersection --------------------------------------
    def shards_for_boxes(self, boxes) -> list[int]:
        """Shard ids whose key range any of the boxes' Z-range covering
        intersects (each z-interval covers a contiguous shard run)."""
        zr = self._sfc.ranges(list(boxes))
        shards: set[int] = set()
        for lo, hi in zr:
            s_lo = int(np.searchsorted(self.splits, np.int64(lo),
                                       side="right"))
            s_hi = int(np.searchsorted(self.splits, np.int64(hi),
                                       side="right"))
            shards.update(range(s_lo, s_hi + 1))
        return sorted(shards)

    def shards_for_filter(self, f, sft) -> list | None:
        """Shard ids a query with this filter can touch: ``None`` = all
        shards (no spatial bounds — fid/attribute-only filters, or
        extended-geometry types whose rows partition by envelope CENTER
        while a query box can intersect a geometry centered far outside
        it); ``[]`` = provably disjoint. The shard-level half of
        :meth:`members_for_filter`, shared with the generation's
        union-read routing so the two can never diverge."""
        if f is None or isinstance(f, ast.Include):
            return None
        e = extract(f, sft.geom_field, sft.dtg_field)
        if e.disjoint:
            return []
        if sft.geom_field and not sft.geom_is_points:
            return None
        if not e.boxes:
            return None
        return self.shards_for_boxes(e.boxes)

    def members_for_filter(self, f, sft) -> list | None:
        """Member ids a query with this filter must fan out to, DEDUPED
        (the double-count fix: overlapping Z-prefix ranges on one member
        fan out to it once). ``None`` = all members (no spatial bounds:
        fid/attribute-only filters fan out everywhere, deterministically);
        ``[]`` = provably disjoint (no fan-out at all).

        Extended-geometry types (non-point: polygons, lines) fan out to
        ALL members when any constraint survives: rows partition by
        their envelope CENTER's key, but a query box can intersect a
        geometry whose center key lies far outside the box's Z-ranges —
        pruning by the box would silently drop matching rows (red/green
        pinned in tests/test_serving.py). A disjoint filter still fans
        nowhere: it matches nothing regardless of geometry extent."""
        shards = self.shards_for_filter(f, sft)
        if shards is None:
            return None
        if not shards:
            return []
        seen: set = set()
        out: list = []
        for s in shards:
            m = self.shard_member[s]
            if m not in seen:
                seen.add(m)
                out.append(m)
        # stable member order (declaration order), not shard order
        out.sort(key=self._pos.__getitem__)
        return out


class ShardMigration:
    """One in-flight shard migration's MUTABLE record, shared between
    the generations that carry it and the migrator
    (``serving/elastic.py``).

    ``dual_fids`` is the exactly-once ledger of the dual-apply window:
    a writer records a row's fid here BEFORE the source apply commits
    it to the WAL, so when the migrator's tail replay later sees that
    record it knows the destination already has (or is about to get)
    the row via the dual path and skips it — and a dual-applied DELETE
    recorded here can never be resurrected on the destination by an
    older replayed write. ``lock`` serializes destination applies for
    this shard between the dual-write path and the replay loop (the
    check-then-apply pairs must not interleave); it is held only for
    the dual window of one shard and nests ABOVE the member stores'
    locks (docs/concurrency.md § elastic plane).
    """

    __slots__ = ("shard", "src", "dst", "state", "dual_fids", "lock",
                 "rows_shipped", "rows_replayed", "started_ts")

    def __init__(self, shard: int, src, dst, state: str = MIG_SHIPPING):
        self.shard = int(shard)
        self.src = src
        self.dst = dst
        self.state = state
        self.dual_fids: set[str] = set()
        self.lock = threading.Lock()
        self.rows_shipped = 0
        self.rows_replayed = 0
        self.started_ts = time.time()

    def with_state(self, state: str) -> "ShardMigration":
        """A copy sharing the dual ledger/lock — the migrator advances
        state by installing a NEW generation carrying the new record,
        never by mutating one visible to in-flight snapshots."""
        m = ShardMigration(self.shard, self.src, self.dst, state)
        m.dual_fids = self.dual_fids
        m.lock = self.lock
        m.rows_shipped = self.rows_shipped
        m.rows_replayed = self.rows_replayed
        m.started_ts = self.started_ts
        return m

    def snapshot(self) -> dict:
        return {
            "shard": self.shard,
            "src": self.src,
            "dst": self.dst,
            "state": self.state,
            "rows_shipped": int(self.rows_shipped),
            "rows_replayed": int(self.rows_replayed),
            "dual_fids": len(self.dual_fids),
            "age_s": round(time.time() - self.started_ts, 3),
        }


class RouterGeneration:
    """One epoch of the shard map: an immutable router + the in-flight
    migrations riding it + an in-flight WRITE refcount.

    The view reads ``view._generation`` exactly once per operation and
    routes entirely off the snapshot — the satellite fix for the torn
    mid-swap read — and write operations bracket themselves with
    :meth:`op` so the migrator can ``wait_idle`` a superseded
    generation before capturing the tail-replay stop seq (every write
    routed by the OLD map is durably in the WAL below the stop)."""

    def __init__(self, router: ShardRouter, generation: int = 0,
                 migrations=()):
        self.router = router
        self.generation = int(generation)
        self.migrations: dict[int, ShardMigration] = {
            int(m.shard): m for m in migrations
        }
        self._cv = threading.Condition()
        self._inflight = 0

    def advance(self, router: ShardRouter | None = None,
                migrations=None) -> "RouterGeneration":
        return RouterGeneration(
            router if router is not None else self.router,
            self.generation + 1,
            tuple(self.migrations.values())
            if migrations is None else migrations,
        )

    # -- write drain barrier --------------------------------------------------
    @contextmanager
    def op(self):
        """Bracket one write operation routed by this generation."""
        with self._cv:
            self._inflight += 1
        try:
            yield
        finally:
            with self._cv:
                self._inflight -= 1
                if self._inflight == 0:
                    self._cv.notify_all()

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until no write routed by this generation is in flight
        (the migrator's drain before stop-seq capture / source drop).
        Returns False on timeout."""
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        with self._cv:
            while self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    # -- routing with migrations overlaid -------------------------------------
    def dual_migration(self, shard: int) -> ShardMigration | None:
        m = self.migrations.get(int(shard))
        return m if m is not None and m.state == MIG_DUAL else None

    def write_members_for_shard(self, shard: int) -> tuple:
        """(authoritative, *extras): writes always apply to the owner;
        a dual-apply migration adds the destination."""
        owner = self.router.member_for_shard(shard)
        mig = self.dual_migration(shard)
        if mig is not None and mig.dst != owner:
            return (owner, mig.dst)
        return (owner,)

    def read_members_for_shards(self, shards) -> list:
        """Deduped UNION of old and new owners over ``shards`` (None =
        every shard) — the row-read fan during dual-apply. Merge-side
        fid dedup makes the double fan safe (both owners hold the
        dual-applied rows)."""
        router = self.router
        if shards is None:
            shards = range(router.n_shards)
        seen: set = set()
        out: list = []
        for s in shards:
            for m in self.write_members_for_shard(s):
                if m not in seen:
                    seen.add(m)
                    out.append(m)
        out.sort(key=router._pos.__getitem__)
        return out

    def authoritative_members_for_shards(self, shards) -> list:
        """Deduped CURRENT owners only — the additive-read fan (counts,
        stats, aggregations, density sum across members; a union fan
        would double-count every dual-applied row)."""
        router = self.router
        if shards is None:
            shards = range(router.n_shards)
        seen: set = set()
        out: list = []
        for s in shards:
            m = router.member_for_shard(s)
            if m not in seen:
                seen.add(m)
                out.append(m)
        out.sort(key=router._pos.__getitem__)
        return out

    def snapshot(self) -> dict:
        router = self.router
        with self._cv:
            inflight = self._inflight
        return {
            "generation": self.generation,
            "members": list(router.members),
            "n_shards": router.n_shards,
            "virtual_nodes": router.virtual_nodes,
            "assignments": {
                str(s): m for s, m in sorted(router.assignments.items())
            },
            "shard_member": list(router.shard_member),
            "migrations": [
                m.snapshot() for _, m in sorted(self.migrations.items())
            ],
            "inflight_writes": inflight,
        }


class ShardedDataStoreView(MergedDataStoreView):
    """Shard-partitioned federation over ``[store, ...]``.

    Reads: the merged view's fan-out/merge/resilience machinery, fanned
    only to the members the plan's Z-ranges intersect. Writes: schema
    CRUD applies to every member; ``write`` partitions records by Z2 key
    so each row lands on exactly one member (write failures raise — a
    partial write is a correctness error, not a degraded answer).

    The shard map lives in ONE atomic slot (``_generation``); every
    operation snapshots it once. ``router`` stays available as a
    property for the sweeper/ops surfaces (assigning it swaps in a
    fresh generation — the pre-elastic offline idiom keeps working).
    """

    def __init__(self, stores, n_shards: int | None = None,
                 on_member_error: str = "fail", metrics=None, slo=None,
                 slo_target: float = 0.999, virtual_nodes: int = 32):
        super().__init__(stores, on_member_error=on_member_error,
                         metrics=metrics, slo=slo, slo_target=slo_target)
        self._gen_lock = threading.Lock()  # swap serialization only
        self._generation = RouterGeneration(ShardRouter(
            list(range(len(self.stores))), n_shards=n_shards,
            virtual_nodes=virtual_nodes))
        # per-operation generation pin: the merge step must dedup with
        # the SAME generation that computed the fan-out, not whatever is
        # current by merge time (same thread: fan-out and merge both run
        # on the caller's thread inside one _query_fanout)
        self._op_gen = threading.local()

    # -- generation surface ---------------------------------------------------
    @property
    def router(self) -> ShardRouter:
        return self._generation.router

    @router.setter
    def router(self, r: ShardRouter) -> None:
        self.swap_generation(RouterGeneration(
            r, self._generation.generation + 1))

    def swap_generation(self, gen: RouterGeneration) -> RouterGeneration:
        """Install a new shard-map generation atomically; returns the
        superseded one (the migrator drains it). Generations must move
        forward — a stale swap is a migrator bug, not a race to absorb."""
        with self._gen_lock:
            prev = self._generation
            if gen.generation <= prev.generation:
                raise ValueError(
                    f"generation must advance: {gen.generation} after "
                    f"{prev.generation}")
            self._generation = gen
            return prev

    def with_members(self, members) -> RouterGeneration:
        """OFFLINE membership change (assignment only — data does not
        follow; ``serving.elastic.ShardMigrator.apply_membership`` is
        the live one). Returns the new generation."""
        gen = self._generation
        new = gen.advance(router=gen.router.with_members(members),
                          migrations=())
        self.swap_generation(new)
        return new

    def add_member(self, store, scope=None) -> int:
        """Join one store to the federation without granting it any
        shards (membership precedes ownership): the migrator moves data
        onto it one shard-generation at a time. Returns the new member
        index."""
        from geomesa_tpu.filter.cql import parse

        if scope is not None and not isinstance(scope, ast.Filter):
            scope = parse(scope)
        with self._gen_lock:
            self.stores.append((store, scope))
            m = len(self.stores) - 1
            prev = self._generation
            self._generation = prev.advance(
                router=prev.router.with_member_added(m))
        return m

    def remove_member(self, member: int) -> None:
        """Depart one DRAINED member from the shard map. The store stays
        in ``self.stores`` positionally (member indices are stable SLO /
        metrics keys); it simply owns nothing and receives no fan-out."""
        gen = self._generation
        self.swap_generation(gen.advance(
            router=gen.router.with_member_removed(member)))

    def shards_snapshot(self) -> dict:
        """The ops surface behind GET /api/obs/shards and
        ``geomesa-tpu obs shards``."""
        gen = self._generation
        snap = gen.snapshot()
        snap["coverage_violations"] = gen.router.coverage_violations()
        snap["n_stores"] = len(self.stores)
        return snap

    # -- the fan-out narrowing hooks (store/merged.py) ------------------------
    def _member_subset(self, type_name: str, f) -> list | None:
        """Additive-read fan: AUTHORITATIVE owners only (counts, stats,
        aggregations and density SUM across members — a union fan would
        double-count every dual-applied row)."""
        gen = self._generation
        self._op_gen.gen = gen
        shards = self._shards_for(gen, type_name, f)
        if shards is not None and not shards:
            return []
        return gen.authoritative_members_for_shards(shards)

    def _member_subset_rows(self, type_name: str, f) -> list | None:
        """Row-read fan: the UNION of old and new owners during a
        dual-apply migration (fid dedup at the merge makes the double
        fan safe); identical to the authoritative fan otherwise."""
        gen = self._generation
        self._op_gen.gen = gen
        shards = self._shards_for(gen, type_name, f)
        if shards is not None and not shards:
            return []
        return gen.read_members_for_shards(shards)

    def _shards_for(self, gen: RouterGeneration, type_name: str, f):
        try:
            sft = self.get_schema(type_name)
        except Exception:  # noqa: BLE001 — let the member call surface it
            return None
        return gen.router.shards_for_filter(f, sft)

    def _merge_member_tables(self, tables: list) -> FeatureTable:
        """Merge seam override: during a dual-apply window both owners
        return the dual-applied rows — dedup by fid (first occurrence
        wins; the copies are identical) using the SAME generation the
        fan-out snapshotted."""
        table = super()._merge_member_tables(tables)
        gen = getattr(self._op_gen, "gen", None)
        if gen is None or not gen.migrations or len(tables) < 2:
            return table
        return self._dedup_fids(table)

    @staticmethod
    def _dedup_fids(table: FeatureTable) -> FeatureTable:
        fids = np.asarray(table.fids)
        if len(fids) < 2:
            return table
        _, first = np.unique(fids, return_index=True)
        if len(first) == len(fids):
            return table
        return table.take(np.sort(first))

    # -- write surface --------------------------------------------------------
    def create_schema(self, name_or_sft, spec: str | None = None) -> None:
        for store, _ in self.stores:
            store.create_schema(name_or_sft, spec)

    def delete_schema(self, name: str) -> None:
        for store, _ in self.stores:
            store.delete_schema(name)

    def update_schema(self, name: str, **changes):
        out = None
        for store, _ in self.stores:
            out = store.update_schema(name, **changes)
        return out

    def compact(self, type_name: str) -> None:
        """Compact every member that supports it (remote members run
        their own compactions — the method is absent on the client)."""
        for store, _ in self.stores:
            fn = getattr(store, "compact", None)
            if fn is not None:
                fn(type_name)

    def _record_shards(self, sft, records, fids,
                       router: ShardRouter) -> np.ndarray:
        """Shard id per record: geometry rows key by their envelope
        center's Z2 code, geometry-less rows by fid hash (row index
        when fids are auto-generated) — deterministic either way.
        ``router`` is the operation's snapshot: keying and placement
        MUST come from one generation (the torn-read satellite fix)."""
        from geomesa_tpu.geometry.types import Geometry
        from geomesa_tpu.geometry.wkt import from_wkt

        n = len(records)
        keys = np.zeros(n, dtype=np.uint64)
        xs, ys, geom_rows = [], [], []
        for i, rec in enumerate(records):
            g = rec.get(sft.geom_field) if sft.geom_field else None
            if isinstance(g, str):
                # WKT accepted anywhere a geometry is (the columnar
                # tier's GeoTools convention) — it must place by its
                # COORDINATES, not the fid hash, or point-schema reads
                # (which prune fan-out by the query box) can never
                # reach the row
                g = from_wkt(g)
            if isinstance(g, Geometry):
                x0, y0, x1, y1 = g.bbox
                xs.append((x0 + x1) / 2.0)
                ys.append((y0 + y1) / 2.0)
                geom_rows.append(i)
            else:
                basis = str(fids[i]) if fids is not None else str(i)
                keys[i] = np.uint64(router.fid_key(basis))
        if geom_rows:
            keys[np.asarray(geom_rows)] = router.keys_for(xs, ys)
        return router.shards_of_keys(keys)

    def _record_members(self, sft, records, fids) -> np.ndarray:
        """Member position per record (kept for callers/tests that pin
        the placement contract; routes off one generation snapshot)."""
        router = self._generation.router
        shards = self._record_shards(sft, records, fids, router)
        return np.asarray(
            [router.member_for_shard(s) for s in shards], dtype=np.int64)

    def write(self, type_name: str, data, fids=None) -> int:
        sft = self.get_schema(type_name)
        if isinstance(data, FeatureTable):
            if fids is None:
                fids = list(data.fids)
            data = [data.record(i) for i in range(len(data))]
        records = list(data)
        if fids is not None:
            fids = [str(f) for f in fids]
            if len(fids) != len(records):
                raise ValueError("fids length must match records")
        gen = self._generation  # ONE snapshot: keying, placement, dual
        if fids is None and gen.migrations:
            # dual-apply needs the SAME fid on both owners; member-side
            # auto-generation would mint two different ones, and the
            # tail replay's fid ledger could match neither
            fids = [uuid.uuid4().hex for _ in records]
        with gen.op():
            shards = self._record_shards(sft, records, fids, gen.router)
            owners = np.asarray(
                [gen.router.member_for_shard(s) for s in shards],
                dtype=np.int64)
            total = 0
            with obs.span("federation.write", type=type_name,
                          rows=len(records)):
                # exactly-once ledger FIRST: a dual row's fid must be in
                # dual_fids before the source apply commits it to the
                # WAL, or the tail replay could double-apply it
                dual: dict[int, list[int]] = {}
                for shard, mig in gen.migrations.items():
                    if mig.state != MIG_DUAL:
                        continue
                    idx = np.nonzero(shards == shard)[0]
                    if len(idx):
                        dual[shard] = idx.tolist()
                        with mig.lock:
                            mig.dual_fids.update(fids[i] for i in idx)
                rerouted: list[int] = []
                for m in sorted(set(owners.tolist())):
                    idx = np.nonzero(owners == m)[0]
                    store, _ = self.stores[m]
                    try:
                        total += store.write(
                            type_name, [records[i] for i in idx],
                            fids=[fids[i] for i in idx] if fids is not None
                            else None,
                        )
                    except MemberDrainingError:
                        # the member declared a drain (503 + Retry-After):
                        # its shards are moving — re-route this slice
                        # through a FRESH generation instead of retrying
                        # against the draining owner. One re-route only:
                        # if the map has not advanced, the drain signal
                        # is ahead of the control plane and must surface.
                        if self._generation is gen:
                            raise
                        rerouted.extend(idx.tolist())
                # dual extras: apply to each migration destination under
                # the migration lock (serialized against the tail
                # replay's check-then-apply)
                for shard, idx in dual.items():
                    mig = gen.migrations[shard]
                    dst_store, _ = self.stores[mig.dst]
                    if mig.dst in set(owners[idx].tolist()):
                        continue  # destination already the owner
                    with mig.lock:
                        dst_store.write(
                            type_name, [records[i] for i in idx],
                            fids=[fids[i] for i in idx])
        if rerouted:
            total += self.write(
                type_name, [records[i] for i in rerouted],
                fids=[fids[i] for i in rerouted] if fids is not None
                else None)
        return total

    def delete_features(self, type_name: str, fids, visible_to=None) -> int:
        """Federation-level delete: a fid alone cannot be mapped back to
        a shard (geometry rows key by their coordinates), so the delete
        fans to EVERY live member — each removes what it holds. During a
        dual-apply window the fids are recorded in every active
        migration's ledger first (a replayed older write must never
        resurrect a deleted row on the destination)."""
        self.get_schema(type_name)
        gen = self._generation
        want = [str(f) for f in fids]
        with gen.op():
            duals = [m for m in gen.migrations.values()
                     if m.state == MIG_DUAL]
            for mig in duals:
                with mig.lock:
                    mig.dual_fids.update(want)
            members = gen.authoritative_members_for_shards(None)
            removed = 0
            with obs.span("federation.delete", type=type_name,
                          fids=len(want)):
                for m in members:
                    store, _ = self.stores[m]
                    removed += store.delete_features(
                        type_name, want, visible_to=visible_to)
                for mig in duals:
                    if mig.dst in members:
                        continue
                    dst_store, _ = self.stores[mig.dst]
                    with mig.lock:
                        dst_store.delete_features(
                            type_name, want, visible_to=visible_to)
        return removed

    # -- batched read surface -------------------------------------------------
    def _normalize(self, queries) -> list:
        return [
            Query(filter=q)
            if isinstance(q, (str, ast.Filter)) or q is None else q
            for q in queries
        ]

    def _fan_plan(self, gen: RouterGeneration, type_name: str, qs: list,
                  rows: bool):
        """Per-query member subsets + the member → query-index map, all
        routed off ONE generation snapshot (the torn-read satellite
        fix). ``rows`` picks the union fan (row reads) vs the
        authoritative fan (sums)."""
        subs = []
        for q in qs:
            f = q.resolved_filter()
            shards = self._shards_for(gen, type_name, f)
            if shards is not None and not shards:
                subs.append([])
            elif rows:
                subs.append(gen.read_members_for_shards(shards))
            else:
                subs.append(gen.authoritative_members_for_shards(shards))
        per_member: dict[int, list[int]] = {}
        for i, sub in enumerate(subs):
            for m in sub:
                per_member.setdefault(m, []).append(i)
        return subs, per_member

    def _member_sub_query(self, q: Query, scope):
        f = q.resolved_filter()
        if scope is not None:
            f = ast.And((f, scope))
        return replace(q, filter=f, sort_by=None, limit=None,
                       start_index=None)

    def select_many(self, type_name: str, queries) -> list:
        """Batched row retrieval across the shard set: each member runs
        ITS OWN batched ``select_many`` over the queries that intersect
        it (one device-dispatch pair per member), and per-query tables
        merge at the view with sort/limit re-applied — the merged view's
        query-path semantics, batch-shaped."""
        from geomesa_tpu.store.datastore import QueryResult
        from geomesa_tpu.store.reduce import sort_limit

        qs = self._normalize(queries)
        sft = self.get_schema(type_name)
        gen = self._generation
        subs, per_member = self._fan_plan(gen, type_name, qs, rows=True)
        tables: list[list] = [[] for _ in qs]
        failed: list[list] = [[] for _ in qs]
        errors: list = []
        with obs.span("federation.select_many", type=type_name,
                      n_queries=len(qs), members=len(per_member)):
            for m in sorted(per_member):
                store, scope = self.stores[m]
                idxs = per_member[m]
                subqs = [self._member_sub_query(qs[i], scope)
                         for i in idxs]
                sm = getattr(store, "select_many", None)
                if sm is not None:
                    fn = lambda s=sm, sq=subqs: s(type_name, sq)  # noqa: E731
                else:
                    fn = lambda s=store, sq=subqs: [  # noqa: E731
                        s.query(type_name, q1) for q1 in sq]
                ok, res = self._member_run(
                    m, fn, errors, cost=(type_name, "select_many"))
                if not ok:
                    for i in idxs:
                        failed[i].append(m)
                    continue
                for i, r in zip(idxs, res):
                    tables[i].append(r.table)
        if errors and len(errors) == len(per_member):
            raise errors[-1][1]
        if errors:
            self._note_degraded(errors, "select_many")
        out: list = []
        for i, q in enumerate(qs):
            parts = tables[i]
            if not parts:
                table = FeatureTable.from_records(sft, [])
            elif len(parts) == 1:
                table = parts[0]
            else:
                table = FeatureTable.concat(parts)
                if gen.migrations:
                    table = self._dedup_fids(table)
            rows = np.arange(len(table), dtype=np.int64)
            table, rows = sort_limit(table, rows, q.sort_by, q.limit,
                                     q.start_index)
            degraded = bool(failed[i])
            out.append(QueryResult(
                table, rows, degraded=degraded,
                member_errors=self._error_details(
                    [e for e in errors if e[0] in failed[i]])
                if degraded else None,
            ))
        return out

    def count_many(self, type_name: str, queries, loose: bool = True):
        """Batched counts across the shard set: member counts sum per
        query (rows partition — each row counts on exactly one member,
        so the fan is AUTHORITATIVE owners only even mid-migration).
        In partial mode a failed member contributes zero (undercount,
        recorded), the merged view's ``stats_count`` posture."""
        qs = self._normalize(queries)
        self.get_schema(type_name)  # surface missing types uniformly
        gen = self._generation
        subs, per_member = self._fan_plan(gen, type_name, qs, rows=False)
        totals = [0] * len(qs)
        errors: list = []
        with obs.span("federation.count_many", type=type_name,
                      n_queries=len(qs), members=len(per_member)):
            for m in sorted(per_member):
                store, scope = self.stores[m]
                idxs = per_member[m]
                subqs = [self._member_sub_query(qs[i], scope)
                         for i in idxs]
                cm = getattr(store, "count_many", None)
                if cm is not None:
                    fn = lambda s=cm, sq=subqs: s(  # noqa: E731
                        type_name, sq, loose=loose)
                else:
                    fn = lambda s=store, sq=subqs: [  # noqa: E731
                        s.query(type_name, q1).count for q1 in sq]
                ok, res = self._member_run(
                    m, fn, errors, cost=(type_name, "count_many"))
                if not ok:
                    continue
                for i, c in zip(idxs, res):
                    totals[i] += int(c)
        if errors and len(errors) == len(per_member):
            raise errors[-1][1]
        if errors:
            self._note_degraded(errors, "count_many")
        return totals
