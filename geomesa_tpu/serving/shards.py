"""Sharded federation — a consistent-hash shard router keyed by Z-prefix.

ROADMAP item 4's horizontal story: one store per host stops scaling when
the working set outgrows one device's HBM. This module partitions a
feature type across N federated members by Z2 key prefix (the same key
domain :mod:`geomesa_tpu.store.splitter` seeds device shard boundaries
from), so **writes and reads both partition**:

- :class:`ShardRouter` cuts the 62-bit Z2 domain into ``n_shards``
  contiguous key ranges (``splitter.default_splits``) and assigns each
  shard to a member via a consistent-hash ring (members × virtual
  nodes): resizing the member set moves only the departed/arrived
  member's shards, never reshuffles the survivors (docs/serving.md
  § Shard-map lifecycle).
- :class:`ShardedDataStoreView` subclasses
  :class:`~geomesa_tpu.store.merged.MergedDataStoreView`, so the merge,
  resilience (``on_member_error="partial"`` degraded answers), SLO and
  flight-recorder semantics are LITERALLY the merged view's — it only
  narrows the fan-out: a query runs against exactly the members whose
  shards its plan's Z-ranges intersect (``_member_subset``), and writes
  split records by their geometry's Z2 key (fid hash for geometry-less
  rows) so each row lives on exactly ONE member.

Member dedup is load-bearing: several shards routinely map to the same
member (n_shards > n_members by design), and two overlapping Z-prefix
ranges landing on one member must fan out to it ONCE — a per-shard
fan-out would double-count every matching row on that member
(red/green pinned in tests/test_serving.py).

Fid- and attribute-only filters extract no spatial bounds → they fan
out to ALL members (deterministically — rows are spatially placed, a
fid could live anywhere); disjoint filters fan out to NONE.

The router is immutable after construction (no locks); the view adds no
locks beyond the merged view's.
"""

from __future__ import annotations

import hashlib
from bisect import bisect_right
from dataclasses import replace

import numpy as np

from geomesa_tpu import obs
from geomesa_tpu.curve.sfc import Z2SFC
from geomesa_tpu.filter import ast
from geomesa_tpu.filter.bounds import extract
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.store.merged import MergedDataStoreView
from geomesa_tpu.store.splitter import default_splits, shard_of

__all__ = ["ShardRouter", "ShardedDataStoreView"]

_Z2_BITS = 62  # 31 bits/dim Morton — the splitter's z2 key domain


def _hash64(s: str) -> int:
    """Stable 64-bit hash (sha1 prefix): ring placement must not depend
    on PYTHONHASHSEED."""
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8], "big")


class ShardRouter:
    """Z-prefix shard map + consistent-hash member assignment.

    ``members``: hashable member ids (the sharded view uses positional
    indices). ``n_shards`` contiguous Z2 key ranges; each shard's id
    hashes onto the ring and is owned by the first member clockwise.
    """

    def __init__(self, members, n_shards: int | None = None,
                 virtual_nodes: int = 32):
        self.members = list(members)
        if not self.members:
            raise ValueError("shard router needs at least one member")
        if n_shards is None:
            n_shards = max(8, 4 * len(self.members))
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)
        self.virtual_nodes = int(virtual_nodes)
        self._pos = {m: i for i, m in enumerate(self.members)}
        # shard boundaries: n_shards-1 evenly spaced keys in the 62-bit
        # z2 domain (the device shard-boundary seeding reused at the
        # federation tier)
        self.splits = default_splits("z2", self.n_shards, bits=_Z2_BITS)
        ring = sorted(
            (_hash64(f"{m!r}#{v}"), i)
            for i, m in enumerate(self.members)
            for v in range(self.virtual_nodes)
        )
        self._ring_keys = [h for h, _ in ring]
        self._ring_pos = [i for _, i in ring]
        self.shard_member = [
            self.members[self._locate(_hash64(f"shard:{s}"))]
            for s in range(self.n_shards)
        ]
        self._sfc = Z2SFC()

    def _locate(self, h: int) -> int:
        i = bisect_right(self._ring_keys, h) % len(self._ring_keys)
        return self._ring_pos[i]

    def with_members(self, members) -> "ShardRouter":
        """A new router over a resized member set, same shard cuts: the
        consistent-hash ring guarantees only shards owned by departed
        (or claimed by arrived) members move (pinned in tests)."""
        return ShardRouter(members, self.n_shards, self.virtual_nodes)

    def coverage_violations(self) -> list[str]:
        """Invariant-sweep surface (obs/audit.py): the shard cuts must
        partition the 62-bit Z2 domain — strictly increasing in-range
        splits (disjoint AND total by construction of contiguous
        ranges) — and every shard must be owned by exactly one LIVE
        member. Returns violation strings, empty when healthy."""
        out: list[str] = []
        splits = np.asarray(self.splits, dtype=np.int64)
        if len(splits) != self.n_shards - 1:
            out.append(f"{len(splits)} splits for {self.n_shards} shards")
        if len(splits) and not (np.diff(splits) > 0).all():
            out.append("shard splits not strictly increasing "
                       "(ranges overlap or are empty)")
        if len(splits) and (splits[0] < 0
                            or int(splits[-1]) >= (1 << _Z2_BITS)):
            out.append("shard splits outside the 62-bit Z2 domain")
        if len(self.shard_member) != self.n_shards:
            out.append(f"{len(self.shard_member)} owners for "
                       f"{self.n_shards} shards")
        live = set(self.members)
        for s, m in enumerate(self.shard_member):
            if m not in live:
                out.append(f"shard {s} owned by departed member {m!r}")
        return out

    # -- key → shard → member -------------------------------------------------
    def keys_for(self, x, y) -> np.ndarray:
        """Z2 keys for point coordinates (the write-partition keying)."""
        return self._sfc.index(np.asarray(x, dtype=np.float64),
                               np.asarray(y, dtype=np.float64))

    def fid_key(self, fid: str) -> int:
        """Deterministic key for a geometry-less row: fid hash folded
        into the 62-bit shard domain."""
        return _hash64(f"fid:{fid}") >> 2

    def shards_of_keys(self, keys) -> np.ndarray:
        z = np.asarray(keys, dtype=np.uint64).astype(np.int64)
        return shard_of(z, self.splits)

    def member_for_shard(self, shard: int):
        return self.shard_member[int(shard)]

    # -- plan-range → shard intersection --------------------------------------
    def shards_for_boxes(self, boxes) -> list[int]:
        """Shard ids whose key range any of the boxes' Z-range covering
        intersects (each z-interval covers a contiguous shard run)."""
        zr = self._sfc.ranges(list(boxes))
        shards: set[int] = set()
        for lo, hi in zr:
            s_lo = int(np.searchsorted(self.splits, np.int64(lo),
                                       side="right"))
            s_hi = int(np.searchsorted(self.splits, np.int64(hi),
                                       side="right"))
            shards.update(range(s_lo, s_hi + 1))
        return sorted(shards)

    def members_for_filter(self, f, sft) -> list | None:
        """Member ids a query with this filter must fan out to, DEDUPED
        (the double-count fix: overlapping Z-prefix ranges on one member
        fan out to it once). ``None`` = all members (no spatial bounds:
        fid/attribute-only filters fan out everywhere, deterministically);
        ``[]`` = provably disjoint (no fan-out at all).

        Extended-geometry types (non-point: polygons, lines) fan out to
        ALL members when any constraint survives: rows partition by
        their envelope CENTER's key, but a query box can intersect a
        geometry whose center key lies far outside the box's Z-ranges —
        pruning by the box would silently drop matching rows (red/green
        pinned in tests/test_serving.py). A disjoint filter still fans
        nowhere: it matches nothing regardless of geometry extent."""
        if f is None or isinstance(f, ast.Include):
            return None
        e = extract(f, sft.geom_field, sft.dtg_field)
        if e.disjoint:
            return []
        if sft.geom_field and not sft.geom_is_points:
            return None
        if not e.boxes:
            return None
        shards = self.shards_for_boxes(e.boxes)
        seen: set = set()
        out: list = []
        for s in shards:
            m = self.shard_member[s]
            if m not in seen:
                seen.add(m)
                out.append(m)
        # stable member order (declaration order), not shard order
        out.sort(key=self._pos.__getitem__)
        return out


class ShardedDataStoreView(MergedDataStoreView):
    """Shard-partitioned federation over ``[store, ...]``.

    Reads: the merged view's fan-out/merge/resilience machinery, fanned
    only to the members the plan's Z-ranges intersect. Writes: schema
    CRUD applies to every member; ``write`` partitions records by Z2 key
    so each row lands on exactly one member (write failures raise — a
    partial write is a correctness error, not a degraded answer).
    """

    def __init__(self, stores, n_shards: int | None = None,
                 on_member_error: str = "fail", metrics=None, slo=None,
                 slo_target: float = 0.999, virtual_nodes: int = 32):
        super().__init__(stores, on_member_error=on_member_error,
                         metrics=metrics, slo=slo, slo_target=slo_target)
        self.router = ShardRouter(
            list(range(len(self.stores))), n_shards=n_shards,
            virtual_nodes=virtual_nodes)

    # -- the fan-out narrowing hook (store/merged.py) -------------------------
    def _member_subset(self, type_name: str, f) -> list | None:
        try:
            sft = self.get_schema(type_name)
        except Exception:  # noqa: BLE001 — let the member call surface it
            return None
        return self.router.members_for_filter(f, sft)

    # -- write surface --------------------------------------------------------
    def create_schema(self, name_or_sft, spec: str | None = None) -> None:
        for store, _ in self.stores:
            store.create_schema(name_or_sft, spec)

    def delete_schema(self, name: str) -> None:
        for store, _ in self.stores:
            store.delete_schema(name)

    def update_schema(self, name: str, **changes):
        out = None
        for store, _ in self.stores:
            out = store.update_schema(name, **changes)
        return out

    def compact(self, type_name: str) -> None:
        """Compact every member that supports it (remote members run
        their own compactions — the method is absent on the client)."""
        for store, _ in self.stores:
            fn = getattr(store, "compact", None)
            if fn is not None:
                fn(type_name)

    def _record_members(self, sft, records, fids) -> np.ndarray:
        """Member position per record: geometry rows key by their
        envelope center's Z2 code, geometry-less rows by fid hash (row
        index when fids are auto-generated) — deterministic either way."""
        from geomesa_tpu.geometry.types import Geometry
        from geomesa_tpu.geometry.wkt import from_wkt

        n = len(records)
        keys = np.zeros(n, dtype=np.uint64)
        xs, ys, geom_rows = [], [], []
        for i, rec in enumerate(records):
            g = rec.get(sft.geom_field) if sft.geom_field else None
            if isinstance(g, str):
                # WKT accepted anywhere a geometry is (the columnar
                # tier's GeoTools convention) — it must place by its
                # COORDINATES, not the fid hash, or point-schema reads
                # (which prune fan-out by the query box) can never
                # reach the row
                g = from_wkt(g)
            if isinstance(g, Geometry):
                x0, y0, x1, y1 = g.bbox
                xs.append((x0 + x1) / 2.0)
                ys.append((y0 + y1) / 2.0)
                geom_rows.append(i)
            else:
                basis = str(fids[i]) if fids is not None else str(i)
                keys[i] = np.uint64(self.router.fid_key(basis))
        if geom_rows:
            keys[np.asarray(geom_rows)] = self.router.keys_for(xs, ys)
        shards = self.router.shards_of_keys(keys)
        return np.asarray(
            [self.router.member_for_shard(s) for s in shards],
            dtype=np.int64)

    def write(self, type_name: str, data, fids=None) -> int:
        sft = self.get_schema(type_name)
        if isinstance(data, FeatureTable):
            if fids is None:
                fids = list(data.fids)
            data = [data.record(i) for i in range(len(data))]
        records = list(data)
        if fids is not None:
            fids = [str(f) for f in fids]
            if len(fids) != len(records):
                raise ValueError("fids length must match records")
        members = self._record_members(sft, records, fids)
        total = 0
        with obs.span("federation.write", type=type_name,
                      rows=len(records)):
            for m in sorted(set(members.tolist())):
                idx = np.nonzero(members == m)[0]
                store, _ = self.stores[m]
                total += store.write(
                    type_name, [records[i] for i in idx],
                    fids=[fids[i] for i in idx] if fids is not None
                    else None,
                )
        return total

    # -- batched read surface -------------------------------------------------
    def _normalize(self, queries) -> list:
        return [
            Query(filter=q)
            if isinstance(q, (str, ast.Filter)) or q is None else q
            for q in queries
        ]

    def _fan_plan(self, type_name: str, qs: list):
        """Per-query member subsets + the member → query-index map."""
        subs = [
            self._member_subset(type_name, q.resolved_filter()) for q in qs
        ]
        per_member: dict[int, list[int]] = {}
        for i, sub in enumerate(subs):
            targets = range(len(self.stores)) if sub is None else sub
            for m in targets:
                per_member.setdefault(m, []).append(i)
        return subs, per_member

    def _member_sub_query(self, q: Query, scope):
        f = q.resolved_filter()
        if scope is not None:
            f = ast.And((f, scope))
        return replace(q, filter=f, sort_by=None, limit=None,
                       start_index=None)

    def select_many(self, type_name: str, queries) -> list:
        """Batched row retrieval across the shard set: each member runs
        ITS OWN batched ``select_many`` over the queries that intersect
        it (one device-dispatch pair per member), and per-query tables
        merge at the view with sort/limit re-applied — the merged view's
        query-path semantics, batch-shaped."""
        from geomesa_tpu.store.datastore import QueryResult
        from geomesa_tpu.store.reduce import sort_limit

        qs = self._normalize(queries)
        sft = self.get_schema(type_name)
        subs, per_member = self._fan_plan(type_name, qs)
        tables: list[list] = [[] for _ in qs]
        failed: list[list] = [[] for _ in qs]
        errors: list = []
        with obs.span("federation.select_many", type=type_name,
                      n_queries=len(qs), members=len(per_member)):
            for m in sorted(per_member):
                store, scope = self.stores[m]
                idxs = per_member[m]
                subqs = [self._member_sub_query(qs[i], scope)
                         for i in idxs]
                sm = getattr(store, "select_many", None)
                if sm is not None:
                    fn = lambda s=sm, sq=subqs: s(type_name, sq)  # noqa: E731
                else:
                    fn = lambda s=store, sq=subqs: [  # noqa: E731
                        s.query(type_name, q1) for q1 in sq]
                ok, res = self._member_run(
                    m, fn, errors, cost=(type_name, "select_many"))
                if not ok:
                    for i in idxs:
                        failed[i].append(m)
                    continue
                for i, r in zip(idxs, res):
                    tables[i].append(r.table)
        if errors and len(errors) == len(per_member):
            raise errors[-1][1]
        if errors:
            self._note_degraded(errors, "select_many")
        out: list = []
        for i, q in enumerate(qs):
            parts = tables[i]
            if not parts:
                table = FeatureTable.from_records(sft, [])
            elif len(parts) == 1:
                table = parts[0]
            else:
                table = FeatureTable.concat(parts)
            rows = np.arange(len(table), dtype=np.int64)
            table, rows = sort_limit(table, rows, q.sort_by, q.limit,
                                     q.start_index)
            degraded = bool(failed[i])
            out.append(QueryResult(
                table, rows, degraded=degraded,
                member_errors=self._error_details(
                    [e for e in errors if e[0] in failed[i]])
                if degraded else None,
            ))
        return out

    def count_many(self, type_name: str, queries, loose: bool = True):
        """Batched counts across the shard set: member counts sum per
        query (rows partition — each row counts on exactly one member).
        In partial mode a failed member contributes zero (undercount,
        recorded), the merged view's ``stats_count`` posture."""
        qs = self._normalize(queries)
        self.get_schema(type_name)  # surface missing types uniformly
        subs, per_member = self._fan_plan(type_name, qs)
        totals = [0] * len(qs)
        errors: list = []
        with obs.span("federation.count_many", type=type_name,
                      n_queries=len(qs), members=len(per_member)):
            for m in sorted(per_member):
                store, scope = self.stores[m]
                idxs = per_member[m]
                subqs = [self._member_sub_query(qs[i], scope)
                         for i in idxs]
                cm = getattr(store, "count_many", None)
                if cm is not None:
                    fn = lambda s=cm, sq=subqs: s(  # noqa: E731
                        type_name, sq, loose=loose)
                else:
                    fn = lambda s=store, sq=subqs: [  # noqa: E731
                        s.query(type_name, q1).count for q1 in sq]
                ok, res = self._member_run(
                    m, fn, errors, cost=(type_name, "count_many"))
                if not ok:
                    continue
                for i, c in zip(idxs, res):
                    totals[i] += int(c)
        if errors and len(errors) == len(per_member):
            raise errors[-1][1]
        if errors:
            self._note_degraded(errors, "count_many")
        return totals
