"""Spatial join: points × polygons (the ``JoinProcess`` / batched ST_Within role).

Two paths (SURVEY.md §2.14 TPU mapping):

- :func:`join_within` — exact: per-polygon index-planned scan (z2 ranges) +
  f64 residual predicate. The oracle-parity path.
- :func:`join_within_device` — bulk: whole point store against all polygons
  via the f32 device kernel (:mod:`geomesa_tpu.ops.join`), returning counts;
  ~1e-5 deg edge tolerance (BASELINE config #4's throughput shape).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query


def join_scan(ds, type_name: str, geoms, pred: str = "within", filter=None,
              auths=None):
    """Per-geometry index-planned scans: yields (geom_index, result table).

    The shared core of the exact join paths (JoinProcess and the SQL
    engine's spatial JOIN): each right-side geometry becomes ONE planned
    query of the left store — Z/XZ ranges + residual — never a cartesian
    pass. ``pred`` is the predicate applied to the LEFT geometry column
    (within/contains/intersects); ``None`` geometries yield empty results.
    ``auths`` scopes every planned query to the caller's row visibility.
    """
    sft = ds.get_schema(type_name)
    base = None
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter
    for i, g in enumerate(geoms):
        if g is None:
            yield i, None
            continue
        f = ast.SpatialOp(pred, sft.geom_field, g)
        if base is not None:
            f = ast.And([f, base])
        yield i, ds.query(type_name, Query(filter=f, auths=auths)).table


def join_within(ds, type_name: str, polygons, filter=None):
    """Exact join: returns list of (polygon_index, row fids ndarray)."""
    return [
        (i, t.fids if t is not None else np.empty(0, dtype=object))
        for i, t in join_scan(ds, type_name, polygons, "within", filter)
    ]


def join_rows_device(ds, type_name: str, geoms, pred: str = "within",
                     chunk_budget: int = 32_000_000):
    """Distributed EXACT spatial join returning row sets per right geometry.

    The mesh path of the SQL engine's spatial JOIN (``GeoMesaRelation.scala:
    94`` / ``SQLRules.scala`` role, VERDICT r2 item 6): the z2-sorted device
    layout is cut into fixed blocks; per geometry, only the blocks its bbox
    z-ranges touch are tested (host planning, ``polygon_block_plan``), an
    int-domain bbox gather compacts candidate rows on device (a SUPERSET —
    normalize is monotone), and the exact f64 predicate runs host-side on
    the few candidates. One device dispatch per chunk, not per geometry.

    Returns ``(snapshot_table, [(i, rows), ...])`` — the coherent snapshot
    table the row indices refer to (main tier, plus pending delta rows
    appended when the store is live; a racing compaction cannot skew them)
    and, per geometry ``i`` in order, the matching row indices. TTL-expired
    rows are filtered host-side on the candidates, and pending hot-tier
    rows are predicate-tested host-side and spliced in — live stores stay
    on the mesh path. Raises ValueError when the store/layout cannot take
    the device path (caller falls back to :func:`join_scan`); device
    errors propagate for the caller's circuit breaker.

    ``chunk_budget``: max int32 lanes per gather dispatch (bounds HBM).
    """
    import jax.numpy as jnp

    from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon
    from geomesa_tpu.geometry import predicates as P
    from geomesa_tpu.ops.join import (
        make_block_bbox_count_step,
        make_block_bbox_gather_step,
        polygon_block_plan,
    )
    from geomesa_tpu.parallel.mesh import data_shards
    from geomesa_tpu.store.backends import JOIN_BLOCK, REFINE_PRECISION, TpuBackend

    if pred not in ("within", "intersects"):
        raise ValueError(f"device join: unsupported predicate {pred!r}")
    if not isinstance(ds.backend, TpuBackend) or not ds._device_available():
        raise ValueError("device join: TPU backend unavailable")
    import time as _time

    _t0 = _time.perf_counter()
    st = ds._state(type_name)
    main, indices, backend_state, _stats, delta = st.snapshot()
    dev = (backend_state or {}).get("z2")
    z2 = indices.get("z2")
    if dev is None or z2 is None or main is None or len(main) == 0:
        raise ValueError("device join: no z2 device residency")
    # age-off: expired rows still sit in the device layout; filter them
    # host-side on the (few) candidates so mesh and host paths agree
    ttl = ds._age_off_ttl_ms(st.sft)
    cutoff_ms = None
    main_dtg = None
    if ttl is not None:
        if st.sft.dtg_field is None:
            raise ValueError("device join: TTL without dtg field")
        import time as _time

        cutoff_ms = int(_time.time() * 1000) - ttl
        main_dtg = main.dtg_millis()
    block = JOIN_BLOCK
    if dev.rows_per_shard % block:
        raise ValueError("device join: layout not block-aligned")
    mesh = ds.backend._get_mesh()
    shards = data_shards(mesh)
    nlon = norm_lon(REFINE_PRECISION)
    nlat = norm_lat(REFINE_PRECISION)
    col = main.geom_column()
    perm = z2.perm

    # f64 bboxes for planning; int-domain bboxes for the device test
    k = len(geoms)
    bbox_deg = np.zeros((k, 4))
    ibox = np.zeros((k, 4), dtype=np.int32)
    empty = np.zeros(k, dtype=bool)
    for i, g in enumerate(geoms):
        if g is None:
            empty[i] = True
            continue
        x1, y1, x2, y2 = g.bbox
        bbox_deg[i] = (x1, y1, x2, y2)
        ibox[i] = (
            int(nlon.normalize(x1)), int(nlon.normalize(x2)),
            int(nlat.normalize(y1)), int(nlat.normalize(y2)),
        )

    count_step = make_block_bbox_count_step(mesh, block)
    true_n = jnp.int32(len(main))
    out: list[tuple[int, np.ndarray]] = []
    # chunk geometries so D × Kc × capacity stays inside the lane budget;
    # kc_limit persists across budget-overflow retries (halving a local kc
    # that is recomputed each iteration would loop forever)
    start = 0
    kc_limit = 1024
    while start < k:
        # plan a provisional chunk, then size capacity from real counts
        kc = min(k - start, kc_limit)
        sel = np.arange(start, start + kc)
        blk, nblk = polygon_block_plan(
            z2.zs, bbox_deg[sel], block, dev.rows_per_shard, shards
        )
        dev_blk = jnp.asarray(blk)
        dev_nblk = jnp.asarray(nblk)
        dev_ibox = jnp.asarray(ibox[sel])
        counts = np.asarray(
            # chunked by the lane budget on purpose: the geometry set can
            # exceed what one launch may materialize, and the overflow retry
            # (kc_limit halving) needs the per-chunk counts on host
            # tpusync: disable-next-line=S003
            count_step(dev.cols["x"], dev.cols["y"], true_n,
                       dev_blk, dev_nblk, dev_ibox)
        )  # (D, Kc)
        cap = max(int(counts.max()), 1)
        cap = 1 << (cap - 1).bit_length()  # pow2: bounded compile variants
        if shards * kc * cap > chunk_budget:
            # split the chunk instead of materializing an oversized buffer
            if kc == 1:
                # single huge geometry: exact host scan for just this one
                if empty[start]:
                    out.append((start, np.empty(0, dtype=np.int64)))
                    start += 1
                    continue
                g = geoms[start]
                m = (
                    P.points_within_geom(col.x, col.y, g)
                    if pred == "within"
                    else P.points_intersect_geom(col.x, col.y, g)
                )
                if main_dtg is not None:
                    m &= main_dtg >= cutoff_ms
                out.append((start, np.nonzero(m)[0]))
                start += 1
                continue
            kc_limit = max(1, kc // 2)
            continue
        gather = make_block_bbox_gather_step(mesh, block, cap)
        # second dispatch of the count+gather pair; same chunking rationale
        # tpusync: disable-next-line=S003
        pos, hits = gather(
            dev.cols["x"], dev.cols["y"], true_n, dev_blk, dev_nblk, dev_ibox
        )
        pos = np.asarray(pos)   # (D, Kc, cap) global sorted positions
        hits = np.asarray(hits)
        for j in range(kc):
            gi = start + j
            if empty[gi]:
                out.append((gi, np.empty(0, dtype=np.int64)))
                continue
            cand = np.concatenate(
                [pos[d, j, : hits[d, j]] for d in range(shards)]
            ).astype(np.int64)
            rows = perm[cand]  # sorted-order → original row indices
            g = geoms[gi]
            m = (
                P.points_within_geom(col.x[rows], col.y[rows], g)
                if pred == "within"
                else P.points_intersect_geom(col.x[rows], col.y[rows], g)
            )
            if main_dtg is not None:
                m &= main_dtg[rows] >= cutoff_ms
            out.append((gi, rows[m]))
        start += kc
        # regrow gradually after success: a hard reset to 1024 would re-pay
        # the whole halving descent (a plan + count dispatch per halving)
        # for every chunk under a tight budget
        kc_limit = min(1024, kc_limit * 2)

    _observe_join(ds, type_name, "block", _t0,
                  sum(len(r) for _, r in out))
    if delta is None or not len(delta):
        return main, out

    # pending hot-tier rows: few (bounded by the compaction threshold) —
    # evaluate the exact predicate host-side and splice them in, same as
    # the live-store KNN merge. Row indices >= len(main) address the delta
    # part of the returned combined snapshot table.
    from geomesa_tpu.schema.columnar import FeatureTable

    dcol = delta.geom_column()
    d_keep = np.ones(len(delta), dtype=bool)
    if dcol.valid is not None:
        d_keep &= dcol.valid
    if cutoff_ms is not None:
        d_keep &= delta.dtg_millis() >= cutoff_ms
    combined = FeatureTable.concat([main, delta])
    n_main = len(main)
    merged: list[tuple[int, np.ndarray]] = []
    for gi, rows in out:
        g = geoms[gi]
        if g is None or not d_keep.any():
            merged.append((gi, rows))
            continue
        dm = (
            P.points_within_geom(dcol.x, dcol.y, g)
            if pred == "within"
            else P.points_intersect_geom(dcol.x, dcol.y, g)
        ) & d_keep
        extra = n_main + np.nonzero(dm)[0]
        merged.append((gi, np.concatenate([rows, extra]) if len(extra) else rows))
    return combined, merged


def join_within_device(ds, type_name: str, polygons, max_vertices: int = 64):
    """Bulk join counts: (K,) ndarray of points-inside counts per polygon.

    Runs the f32 crossing-number kernel over the full point store on device.
    """
    import time as _time

    import jax.numpy as jnp

    from geomesa_tpu.ops.join import pack_polygons, points_in_polygons_count

    _t0 = _time.perf_counter()

    ds.compact(type_name)  # bulk path scans the main tier only
    st = ds._state(type_name)
    if st.table is None or len(st.table) == 0:
        return np.zeros(len(polygons), dtype=np.int32)
    col = st.table.geom_column()
    if col.x is None:
        raise ValueError("device join requires a point geometry store")
    verts, bbox, _ = pack_polygons(polygons, max_vertices)
    counts = points_in_polygons_count(
        jnp.asarray(col.x.astype(np.float32)),
        jnp.asarray(col.y.astype(np.float32)),
        jnp.asarray(verts),
        jnp.asarray(bbox),
    )
    counts = np.asarray(counts)
    _observe_join(ds, type_name, "dense", _t0, int(counts.sum()))
    return counts


def _observe_join(ds, type_name: str, route: str, t0: float,
                  rows: int) -> None:
    """Record one join execution under its plan signature
    (``join:block`` / ``join:dense``) — the cost model's training signal
    for :func:`join_counts_auto`'s route choice."""
    import time as _time

    from geomesa_tpu.obs import devmon

    devmon.costs().observe(
        type_name, f"join:{route}",
        wall_ms=(_time.perf_counter() - t0) * 1000.0, rows=rows,
    )


def measured_pair_density(ds, type_name: str, geoms) -> float | None:
    """MEASURED candidate-pair density of a join: candidate rows a z2
    range plan admits (searchsorted over the HOST z2 keys — no block
    expansion, no device work) over the brute-force
    ``points x geometries`` pair count, clamped to [0, 1]. None when the
    store has no z2 device layout to plan against (the block route can't
    run at all)."""
    from geomesa_tpu.ops.join import planned_candidate_rows
    from geomesa_tpu.store.backends import TpuBackend

    if not isinstance(ds.backend, TpuBackend):
        return None
    st = ds._state(type_name)
    main, indices, backend_state, _stats, _delta = st.snapshot()
    dev = (backend_state or {}).get("z2")
    z2 = indices.get("z2")
    if dev is None or z2 is None or main is None or not len(main):
        return None
    k = sum(1 for g in geoms if g is not None)
    if k == 0:
        return 0.0
    bbox_deg = np.array(
        [g.bbox for g in geoms if g is not None], dtype=np.float64
    )
    # searchsorted row-count estimate — the block route (if chosen) does
    # its own full block planning exactly once, not twice
    cand = planned_candidate_rows(z2.zs, bbox_deg)
    return min(float(int(cand.sum())) / float(len(main) * k), 1.0)


def join_counts_auto(ds, type_name: str, polygons, max_vertices: int = 64):
    """Adaptive join counts: per-polygon points-inside counts via the
    route the cost model picks — ``"block"`` (the index-pruned
    block-sparse gather + exact f64 host refine,
    :func:`join_rows_device`) or ``"dense"`` (the full f32
    crossing-number pass, :func:`join_within_device`). Returns
    ``(counts (K,) int64, route)``.

    The seed comes from the MEASURED pair density (how many candidate
    rows the block plan would actually test): sparse joins — polygons
    touching few z2 blocks — seed the block route, dense ones the full
    pass. Observed wall per route lands under the ``join:block`` /
    ``join:dense`` plan signatures, so once both routes are trained the
    measured p50 decides, and the model's probe cadence re-measures the
    loser (docs/planning.md). Note the documented f32 tolerance of the
    dense kernel (~1e-5 deg at polygon edges); callers needing exact
    parity should call :func:`join_rows_device` directly."""
    from geomesa_tpu.planning import costmodel

    density = measured_pair_density(ds, type_name, polygons)
    route = "dense"
    if density is not None:
        route = costmodel.model().choose_join_path(type_name, density)
    if route == "block":
        try:
            _snap, pairs = join_rows_device(ds, type_name, polygons)
            counts = np.zeros(len(polygons), dtype=np.int64)
            for i, rows in pairs:
                counts[i] = len(rows)
            return counts, route
        except ValueError:
            route = "dense"  # layout can't take the block path after all
    return (
        np.asarray(
            join_within_device(ds, type_name, polygons, max_vertices),
            dtype=np.int64,
        ),
        route,
    )
