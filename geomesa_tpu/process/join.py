"""Spatial join: points × polygons (the ``JoinProcess`` / batched ST_Within role).

Two paths (SURVEY.md §2.14 TPU mapping):

- :func:`join_within` — exact: per-polygon index-planned scan (z2 ranges) +
  f64 residual predicate. The oracle-parity path.
- :func:`join_within_device` — bulk: whole point store against all polygons
  via the f32 device kernel (:mod:`geomesa_tpu.ops.join`), returning counts;
  ~1e-5 deg edge tolerance (BASELINE config #4's throughput shape).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query


def join_scan(ds, type_name: str, geoms, pred: str = "within", filter=None):
    """Per-geometry index-planned scans: yields (geom_index, result table).

    The shared core of the exact join paths (JoinProcess and the SQL
    engine's spatial JOIN): each right-side geometry becomes ONE planned
    query of the left store — Z/XZ ranges + residual — never a cartesian
    pass. ``pred`` is the predicate applied to the LEFT geometry column
    (within/contains/intersects); ``None`` geometries yield empty results.
    """
    sft = ds.get_schema(type_name)
    base = None
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter
    for i, g in enumerate(geoms):
        if g is None:
            yield i, None
            continue
        f = ast.SpatialOp(pred, sft.geom_field, g)
        if base is not None:
            f = ast.And([f, base])
        yield i, ds.query(type_name, Query(filter=f)).table


def join_within(ds, type_name: str, polygons, filter=None):
    """Exact join: returns list of (polygon_index, row fids ndarray)."""
    return [
        (i, t.fids if t is not None else np.empty(0, dtype=object))
        for i, t in join_scan(ds, type_name, polygons, "within", filter)
    ]


def join_within_device(ds, type_name: str, polygons, max_vertices: int = 64):
    """Bulk join counts: (K,) ndarray of points-inside counts per polygon.

    Runs the f32 crossing-number kernel over the full point store on device.
    """
    import jax.numpy as jnp

    from geomesa_tpu.ops.join import pack_polygons, points_in_polygons_count

    ds.compact(type_name)  # bulk path scans the main tier only
    st = ds._state(type_name)
    if st.table is None or len(st.table) == 0:
        return np.zeros(len(polygons), dtype=np.int32)
    col = st.table.geom_column()
    if col.x is None:
        raise ValueError("device join requires a point geometry store")
    verts, bbox, _ = pack_polygons(polygons, max_vertices)
    counts = points_in_polygons_count(
        jnp.asarray(col.x.astype(np.float32)),
        jnp.asarray(col.y.astype(np.float32)),
        jnp.asarray(verts),
        jnp.asarray(bbox),
    )
    return np.asarray(counts)
