"""Spatial join: points × polygons (the ``JoinProcess`` / batched ST_Within role).

Two paths (SURVEY.md §2.14 TPU mapping):

- :func:`join_within` — exact: per-polygon index-planned scan (z2 ranges) +
  f64 residual predicate. The oracle-parity path.
- :func:`join_within_device` — bulk: whole point store against all polygons
  via the f32 device kernel (:mod:`geomesa_tpu.ops.join`), returning counts;
  ~1e-5 deg edge tolerance (BASELINE config #4's throughput shape).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query


def join_scan(ds, type_name: str, geoms, pred: str = "within", filter=None,
              auths=None):
    """Per-geometry index-planned scans: yields (geom_index, result table).

    The shared core of the exact join paths (JoinProcess and the SQL
    engine's spatial JOIN): each right-side geometry becomes ONE planned
    query of the left store — Z/XZ ranges + residual — never a cartesian
    pass. ``pred`` is the predicate applied to the LEFT geometry column
    (within/contains/intersects); ``None`` geometries yield empty results.
    ``auths`` scopes every planned query to the caller's row visibility.
    """
    sft = ds.get_schema(type_name)
    base = None
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter
    for i, g in enumerate(geoms):
        if g is None:
            yield i, None
            continue
        f = ast.SpatialOp(pred, sft.geom_field, g)
        if base is not None:
            f = ast.And([f, base])
        yield i, ds.query(type_name, Query(filter=f, auths=auths)).table


def join_within(ds, type_name: str, polygons, filter=None):
    """Exact join: returns list of (polygon_index, row fids ndarray)."""
    return [
        (i, t.fids if t is not None else np.empty(0, dtype=object))
        for i, t in join_scan(ds, type_name, polygons, "within", filter)
    ]


def join_rows_device(ds, type_name: str, geoms, pred: str = "within",
                     chunk_budget: int = 32_000_000):
    """Distributed EXACT spatial join returning row sets per right geometry.

    The mesh path of the SQL engine's spatial JOIN (``GeoMesaRelation.scala:
    94`` / ``SQLRules.scala`` role, VERDICT r2 item 6): the z2-sorted device
    layout is cut into fixed blocks; per geometry, only the blocks its bbox
    z-ranges touch are tested (host planning, ``polygon_block_plan``), an
    int-domain bbox gather compacts candidate rows on device (a SUPERSET —
    normalize is monotone), and the exact f64 predicate runs host-side on
    the few candidates. One device dispatch per chunk, not per geometry.

    Returns ``(snapshot_table, [(i, rows), ...])`` — the coherent snapshot
    table the row indices refer to (main tier, plus pending delta rows
    appended when the store is live; a racing compaction cannot skew them)
    and, per geometry ``i`` in order, the matching row indices. TTL-expired
    rows are filtered host-side on the candidates, and pending hot-tier
    rows are predicate-tested host-side and spliced in — live stores stay
    on the mesh path. Raises ValueError when the store/layout cannot take
    the device path (caller falls back to :func:`join_scan`); device
    errors propagate for the caller's circuit breaker.

    ``chunk_budget``: max int32 lanes per gather dispatch (bounds HBM).
    """
    import jax.numpy as jnp

    from geomesa_tpu.curve.normalize import lat as norm_lat, lon as norm_lon
    from geomesa_tpu.geometry import predicates as P
    from geomesa_tpu.ops.join import (
        make_block_bbox_count_step,
        make_block_bbox_gather_step,
        polygon_block_plan,
    )
    from geomesa_tpu.parallel.mesh import data_shards
    from geomesa_tpu.store.backends import JOIN_BLOCK, REFINE_PRECISION, TpuBackend

    if pred not in ("within", "intersects"):
        raise ValueError(f"device join: unsupported predicate {pred!r}")
    if not isinstance(ds.backend, TpuBackend) or not ds._device_available():
        raise ValueError("device join: TPU backend unavailable")
    st = ds._state(type_name)
    main, indices, backend_state, _stats, delta = st.snapshot()
    dev = (backend_state or {}).get("z2")
    z2 = indices.get("z2")
    if dev is None or z2 is None or main is None or len(main) == 0:
        raise ValueError("device join: no z2 device residency")
    # age-off: expired rows still sit in the device layout; filter them
    # host-side on the (few) candidates so mesh and host paths agree
    ttl = ds._age_off_ttl_ms(st.sft)
    cutoff_ms = None
    main_dtg = None
    if ttl is not None:
        if st.sft.dtg_field is None:
            raise ValueError("device join: TTL without dtg field")
        import time as _time

        cutoff_ms = int(_time.time() * 1000) - ttl
        main_dtg = main.dtg_millis()
    block = JOIN_BLOCK
    if dev.rows_per_shard % block:
        raise ValueError("device join: layout not block-aligned")
    mesh = ds.backend._get_mesh()
    shards = data_shards(mesh)
    nlon = norm_lon(REFINE_PRECISION)
    nlat = norm_lat(REFINE_PRECISION)
    col = main.geom_column()
    perm = z2.perm

    # f64 bboxes for planning; int-domain bboxes for the device test
    k = len(geoms)
    bbox_deg = np.zeros((k, 4))
    ibox = np.zeros((k, 4), dtype=np.int32)
    empty = np.zeros(k, dtype=bool)
    for i, g in enumerate(geoms):
        if g is None:
            empty[i] = True
            continue
        x1, y1, x2, y2 = g.bbox
        bbox_deg[i] = (x1, y1, x2, y2)
        ibox[i] = (
            int(nlon.normalize(x1)), int(nlon.normalize(x2)),
            int(nlat.normalize(y1)), int(nlat.normalize(y2)),
        )

    count_step = make_block_bbox_count_step(mesh, block)
    true_n = jnp.int32(len(main))
    out: list[tuple[int, np.ndarray]] = []
    # chunk geometries so D × Kc × capacity stays inside the lane budget;
    # kc_limit persists across budget-overflow retries (halving a local kc
    # that is recomputed each iteration would loop forever)
    start = 0
    kc_limit = 1024
    while start < k:
        # plan a provisional chunk, then size capacity from real counts
        kc = min(k - start, kc_limit)
        sel = np.arange(start, start + kc)
        blk, nblk = polygon_block_plan(
            z2.zs, bbox_deg[sel], block, dev.rows_per_shard, shards
        )
        dev_blk = jnp.asarray(blk)
        dev_nblk = jnp.asarray(nblk)
        dev_ibox = jnp.asarray(ibox[sel])
        counts = np.asarray(
            count_step(dev.cols["x"], dev.cols["y"], true_n,
                       dev_blk, dev_nblk, dev_ibox)
        )  # (D, Kc)
        cap = max(int(counts.max()), 1)
        cap = 1 << (cap - 1).bit_length()  # pow2: bounded compile variants
        if shards * kc * cap > chunk_budget:
            # split the chunk instead of materializing an oversized buffer
            if kc == 1:
                # single huge geometry: exact host scan for just this one
                if empty[start]:
                    out.append((start, np.empty(0, dtype=np.int64)))
                    start += 1
                    continue
                g = geoms[start]
                m = (
                    P.points_within_geom(col.x, col.y, g)
                    if pred == "within"
                    else P.points_intersect_geom(col.x, col.y, g)
                )
                if main_dtg is not None:
                    m &= main_dtg >= cutoff_ms
                out.append((start, np.nonzero(m)[0]))
                start += 1
                continue
            kc_limit = max(1, kc // 2)
            continue
        gather = make_block_bbox_gather_step(mesh, block, cap)
        pos, hits = gather(
            dev.cols["x"], dev.cols["y"], true_n, dev_blk, dev_nblk, dev_ibox
        )
        pos = np.asarray(pos)   # (D, Kc, cap) global sorted positions
        hits = np.asarray(hits)
        for j in range(kc):
            gi = start + j
            if empty[gi]:
                out.append((gi, np.empty(0, dtype=np.int64)))
                continue
            cand = np.concatenate(
                [pos[d, j, : hits[d, j]] for d in range(shards)]
            ).astype(np.int64)
            rows = perm[cand]  # sorted-order → original row indices
            g = geoms[gi]
            m = (
                P.points_within_geom(col.x[rows], col.y[rows], g)
                if pred == "within"
                else P.points_intersect_geom(col.x[rows], col.y[rows], g)
            )
            if main_dtg is not None:
                m &= main_dtg[rows] >= cutoff_ms
            out.append((gi, rows[m]))
        start += kc
        # regrow gradually after success: a hard reset to 1024 would re-pay
        # the whole halving descent (a plan + count dispatch per halving)
        # for every chunk under a tight budget
        kc_limit = min(1024, kc_limit * 2)

    if delta is None or not len(delta):
        return main, out

    # pending hot-tier rows: few (bounded by the compaction threshold) —
    # evaluate the exact predicate host-side and splice them in, same as
    # the live-store KNN merge. Row indices >= len(main) address the delta
    # part of the returned combined snapshot table.
    from geomesa_tpu.schema.columnar import FeatureTable

    dcol = delta.geom_column()
    d_keep = np.ones(len(delta), dtype=bool)
    if dcol.valid is not None:
        d_keep &= dcol.valid
    if cutoff_ms is not None:
        d_keep &= delta.dtg_millis() >= cutoff_ms
    combined = FeatureTable.concat([main, delta])
    n_main = len(main)
    merged: list[tuple[int, np.ndarray]] = []
    for gi, rows in out:
        g = geoms[gi]
        if g is None or not d_keep.any():
            merged.append((gi, rows))
            continue
        dm = (
            P.points_within_geom(dcol.x, dcol.y, g)
            if pred == "within"
            else P.points_intersect_geom(dcol.x, dcol.y, g)
        ) & d_keep
        extra = n_main + np.nonzero(dm)[0]
        merged.append((gi, np.concatenate([rows, extra]) if len(extra) else rows))
    return combined, merged


def join_within_device(ds, type_name: str, polygons, max_vertices: int = 64):
    """Bulk join counts: (K,) ndarray of points-inside counts per polygon.

    Runs the f32 crossing-number kernel over the full point store on device.
    """
    import jax.numpy as jnp

    from geomesa_tpu.ops.join import pack_polygons, points_in_polygons_count

    ds.compact(type_name)  # bulk path scans the main tier only
    st = ds._state(type_name)
    if st.table is None or len(st.table) == 0:
        return np.zeros(len(polygons), dtype=np.int32)
    col = st.table.geom_column()
    if col.x is None:
        raise ValueError("device join requires a point geometry store")
    verts, bbox, _ = pack_polygons(polygons, max_vertices)
    counts = points_in_polygons_count(
        jnp.asarray(col.x.astype(np.float32)),
        jnp.asarray(col.y.astype(np.float32)),
        jnp.asarray(verts),
        jnp.asarray(bbox),
    )
    return np.asarray(counts)
