"""K-nearest-neighbor search via expanding-window index scans.

Reference: ``geomesa-process/.../KNearestNeighborSearchProcess`` (583 LoC;
SURVEY.md §2.15) — iterative-deepening geo window search: query a window
around the point, and if fewer than k candidates are found, double the window
and retry; final distances ranked exactly. Same shape here, with the window
scans going through the normal (index-planned, device-refined) query path and
the distance ranking vectorized.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query


def knn(
    ds,
    type_name: str,
    point: Point,
    k: int = 10,
    filter=None,
    initial_radius_deg: float = 0.5,
    max_radius_deg: float = 45.0,
):
    """Returns (table, distances_deg) of the k nearest features to ``point``.

    ``filter``: optional extra CQL/AST predicate AND'ed with the window.
    """
    base = None
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter

    sft = ds.get_schema(type_name)
    geom_field = sft.geom_field
    radius = initial_radius_deg
    result = None
    while True:
        window = ast.BBox(
            geom_field,
            point.x - radius,
            max(point.y - radius, -90.0),
            point.x + radius,
            min(point.y + radius, 90.0),
        )
        f = window if base is None else ast.And([window, base])
        r = ds.query(type_name, Query(filter=f))
        # enough candidates, and the k-th distance is inside the window's
        # inscribed circle (otherwise a nearer point could hide outside)
        if r.count >= k:
            d = _distances(r, point)
            kth = np.partition(d, k - 1)[k - 1]
            if kth <= radius or radius >= max_radius_deg:
                result = (r, d)
                break
        elif radius >= max_radius_deg:
            result = (r, _distances(r, point))
            break
        radius = min(radius * 2.0, max_radius_deg)

    r, d = result
    take = min(k, r.count)
    order = np.argsort(d, kind="stable")[:take]
    return r.table.take(order), d[order]


def _distances(r, point: Point) -> np.ndarray:
    col = r.table.geom_column()
    if col.x is not None:
        return np.sqrt((col.x - point.x) ** 2 + (col.y - point.y) ** 2)
    from geomesa_tpu.geometry import predicates as P

    geoms = col.geometries()
    return np.array(
        [P.distance(point, g) if g is not None else np.inf for g in geoms]
    )
