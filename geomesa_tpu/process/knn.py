"""K-nearest-neighbor search via expanding-window index scans.

Reference: ``geomesa-process/.../KNearestNeighborSearchProcess`` (583 LoC;
SURVEY.md §2.15) — iterative-deepening geo window search: query a window
around the point, and if fewer than k candidates are found, double the window
and retry; final distances ranked exactly. Same shape here, with the window
scans going through the normal (index-planned, device-refined) query path and
the distance ranking vectorized.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.planning.planner import Query


def knn(
    ds,
    type_name: str,
    point: Point,
    k: int = 10,
    filter=None,
    initial_radius_deg: float = 0.5,
    max_radius_deg: float = 45.0,
):
    """Returns (table, distances_deg) of the k nearest features to ``point``.

    ``filter``: optional extra CQL/AST predicate AND'ed with the window.
    """
    base = None
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter

    sft = ds.get_schema(type_name)
    geom_field = sft.geom_field
    radius = initial_radius_deg
    result = None
    while True:
        window = ast.BBox(
            geom_field,
            point.x - radius,
            max(point.y - radius, -90.0),
            point.x + radius,
            min(point.y + radius, 90.0),
        )
        f = window if base is None else ast.And([window, base])
        r = ds.query(type_name, Query(filter=f))
        # enough candidates, and the k-th distance is inside the window's
        # inscribed circle (otherwise a nearer point could hide outside)
        if r.count >= k:
            d = _distances(r, point)
            kth = np.partition(d, k - 1)[k - 1]
            if kth <= radius or radius >= max_radius_deg:
                result = (r, d)
                break
        elif radius >= max_radius_deg:
            result = (r, _distances(r, point))
            break
        radius = min(radius * 2.0, max_radius_deg)

    r, d = result
    take = min(k, r.count)
    order = np.argsort(d, kind="stable")[:take]
    return r.table.take(order), d[order]


def knn_many(ds, type_name: str, points, k: int = 10,
             topology: str = "gather", now_ms: int | None = None,
             impl: str | None = None):
    """Batched KNN: all query points answered in ONE device pass.

    Device path (TpuBackend): per-shard f32 distance scan + ``top_k``,
    candidate heaps merged across the mesh
    (:func:`geomesa_tpu.parallel.query.make_batched_knn_step`) — the
    reference's per-point window-doubling loop collapses into a single
    sweep. Other backends fall back to per-point :func:`knn`.

    LIVE stores stay on the device path (VERDICT r2 item 5): TTL-expired
    rows are masked on device (the ``with_ttl`` step variant), and pending
    hot-tier (delta) rows are ranked host-side and merged into each
    point's candidate heap — correct because any true neighbor in the main
    tier is within the main tier's device top-k. ``now_ms`` pins the TTL
    clock (tests / reproducibility); default wall clock.

    ``topology``: heap-merge collective — ``"gather"`` (all_gather, one
    round) or ``"ring"`` (ppermute, D-1 hops of O(k) payload — for big
    meshes × large query batches where D·k·Q pressures memory). Identical
    distances; row choice may differ where k-th distances tie.

    ``impl``: per-shard sweep shape (map/scan/blocked), overriding the
    ``GEOMESA_KNN_IMPL`` knob; ``None`` defers to it (see
    :func:`geomesa_tpu.parallel.query._local_knn_heaps`).

    Returns a list of (table, distances_deg) pairs, one per query point,
    each holding that point's k nearest features sorted by distance.
    """
    if topology not in ("gather", "ring"):
        raise ValueError(f"topology must be gather|ring: {topology!r}")
    from geomesa_tpu.parallel.query import _check_knn_impl
    from geomesa_tpu.store.backends import TpuBackend

    _check_knn_impl(impl)  # loud even when the host fallback serves

    st = ds._state(type_name)
    # coherent snapshot: device residency, count, and permutations must all
    # come from the same store generation (background compactions race)
    main, indices, backend_state, _stats, delta_table = st.snapshot()
    main_n = 0 if main is None else len(main)
    dev = index_name = None
    if isinstance(ds.backend, TpuBackend) and ds._device_available():
        dev, index_name = TpuBackend.point_state(backend_state)
    ttl = ds._age_off_ttl_ms(st.sft)
    if dev is None or main_n == 0 or (
        ttl is not None and st.sft.dtg_field is None
    ):
        return [knn(ds, type_name, p, k) for p in points]

    import jax.numpy as jnp

    from geomesa_tpu.parallel.mesh import pad_query_axis
    from geomesa_tpu.parallel.query import (
        cached_batched_knn_step,
        cached_ring_knn_step,
    )

    mesh = ds.backend._get_mesh()
    kk = min(k, main_n)
    with_ttl = ttl is not None
    cutoff_ms = None
    if with_ttl:
        import time as _time

        cutoff_ms = (
            int(_time.time() * 1000) if now_ms is None else now_ms
        ) - ttl
    maker = cached_ring_knn_step if topology == "ring" else cached_batched_knn_step
    step = maker(mesh, kk, with_ttl, impl=impl)
    qx = np.array([p.x for p in points], dtype=np.float32)
    qy = np.array([p.y for p in points], dtype=np.float32)
    (qx, qy), _ = pad_query_axis(mesh, qx, qy)
    c = dev.cols
    try:
        if with_ttl:
            from geomesa_tpu.curve.binned_time import BinnedTime

            binned = BinnedTime(st.sft.z3_interval)
            (cb,), (co,) = binned.to_bin_and_offset(np.array([cutoff_ms]))
            cut = jnp.asarray(np.array([cb, co], dtype=np.int32))
            dists, pos = step(
                c["x"], c["y"], c["bins"], c["offs"], jnp.int32(main_n),
                jnp.asarray(qx), jnp.asarray(qy), cut,
            )
        else:
            dists, pos = step(
                c["x"], c["y"], jnp.int32(main_n),
                jnp.asarray(qx), jnp.asarray(qy),
            )
        # materialize INSIDE the try: jax dispatch is async, so a dead
        # device often surfaces at transfer time, not at the step() call
        dists = np.asarray(dists)[: len(points)]
        pos = np.asarray(pos)[: len(points)]
    except Exception as e:  # noqa: BLE001 — device failover to exact path
        if not ds._is_device_error(e):
            raise
        ds._trip_device_circuit(e)
        ds.metrics.counter("store.query.device_failovers").inc()
        return [knn(ds, type_name, p, k) for p in points]
    ds._note_device_ok()
    perm = indices[index_name].perm

    # hot-tier merge: rank pending delta rows host-side (they are few — the
    # compaction threshold bounds them) and fold into each candidate heap
    d_x = d_y = d_t = None
    if delta_table is not None and len(delta_table):
        dcol = delta_table.geom_column()
        d_keep = np.ones(len(delta_table), dtype=bool)
        if dcol.valid is not None:
            d_keep &= dcol.valid
        if with_ttl:
            d_keep &= delta_table.dtg_millis() >= cutoff_ms
        d_rows = np.nonzero(d_keep)[0]
        if len(d_rows):
            d_x = dcol.x[d_rows].astype(np.float32)
            d_y = dcol.y[d_rows].astype(np.float32)
            d_t = delta_table.take(d_rows)  # materialized once, reused per point

    # Device TTL masking is at quantized (bin, offset) granularity. Rows the
    # device EXCLUDED are genuinely expired (quantization floors, so a lower
    # quantized unit implies a lower exact ms), but rows it KEPT can still be
    # up to one offset unit below the exact cutoff — re-check the candidates
    # at exact milliseconds so the device path agrees with the per-point
    # fallback and join_rows_device. When that check drops anything, the
    # k-heap is under-filled (a farther fresh row belonged in it): recompute
    # just that query point host-side over the fresh rows — bounded work,
    # only points whose top-k touched the ambiguous unit pay it.
    main_dtg = fresh_rows = fx = fy = None
    if with_ttl:
        main_dtg = main.dtg_millis()

    out = []
    for qi in range(len(points)):
        rows = perm[pos[qi]]
        if main_dtg is not None and not (main_dtg[rows] >= cutoff_ms).all():
            if fresh_rows is None:  # lazily built, shared across points
                fresh_rows = np.nonzero(main_dtg >= cutoff_ms)[0]
                colm = main.geom_column()
                fx = colm.x[fresh_rows].astype(np.float32)
                fy = colm.y[fresh_rows].astype(np.float32)
            dd = _f32_dists(fx, fy, points[qi])
            near = np.argpartition(dd, kk - 1)[:kk] if kk < len(dd) \
                else np.arange(len(dd))
            near = near[np.argsort(dd[near], kind="stable")]
            rows = fresh_rows[near]
            cand_t = main.take(rows)
            cand_d = dd[near].astype(np.float64)
            live = np.isfinite(cand_d)
        else:
            cand_t = main.take(rows)
            cand_d = dists[qi].astype(np.float64)
            # device heaps of a near-empty/expired store can carry inf slots
            live = np.isfinite(cand_d)
        if not live.all():
            cand_t = cand_t.take(np.nonzero(live)[0])
            cand_d = cand_d[live]
        if d_x is not None:
            from geomesa_tpu.schema.columnar import FeatureTable

            dd = _f32_dists(d_x, d_y, points[qi]).astype(np.float64)
            cand_t = FeatureTable.concat([cand_t, d_t])
            cand_d = np.concatenate([cand_d, dd])
        take = min(k, len(cand_d))
        order = np.argsort(cand_d, kind="stable")[:take]
        out.append((cand_t.take(order), cand_d[order]))
    return out


def _f32_dists(x: np.ndarray, y: np.ndarray, point: Point) -> np.ndarray:
    """f32 euclidean distances — matches the device kernel's ranking metric,
    so host-computed candidates merge consistently with device heaps."""
    return np.sqrt(
        (x - np.float32(point.x)) ** 2 + (y - np.float32(point.y)) ** 2
    )


def _distances(r, point: Point) -> np.ndarray:
    col = r.table.geom_column()
    if col.x is not None:
        return np.sqrt((col.x - point.x) ** 2 + (col.y - point.y) ** 2)
    from geomesa_tpu.geometry import predicates as P

    geoms = col.geometries()
    return np.array(
        [P.distance(point, g) if g is not None else np.inf for g in geoms]
    )
