"""geomesa_tpu subpackage."""
