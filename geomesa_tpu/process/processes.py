"""Geoprocessing operations: unique, proximity, tube-select, point2point,
sampling, min/max, density, stats.

Reference: ``geomesa-process`` WPS processes (SURVEY.md §2.15):
``UniqueProcess`` (301), ``ProximitySearchProcess``, ``TubeSelectProcess``
(183) + ``TubeBuilder`` (270), ``Point2PointProcess``, ``SamplingProcess``,
``MinMaxProcess``, ``DensityProcess`` (198), ``StatsProcess`` (128),
``QueryProcess``. Each pushes work into normal (index-planned) queries where
possible and vectorizes the rest.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.geometry.types import LineString, Point
from geomesa_tpu.planning.planner import Query


def unique(ds, type_name: str, attribute: str, filter=None, sort: bool = True):
    """Distinct values + counts of an attribute (``UniqueProcess`` role)."""
    r = ds.query(type_name, Query(filter=filter))
    col = r.table.columns[attribute]
    vals = col.values[col.is_valid()]
    values, counts = np.unique(vals.astype(object), return_counts=True)
    out = list(zip(values.tolist(), counts.tolist()))
    if sort:
        out.sort(key=lambda vc: (-vc[1], str(vc[0])))
    return out


def sampling(ds, type_name: str, fraction: float, filter=None, threads_or_by=None):
    """~``fraction`` of the matching features, deterministic every-nth,
    optionally per-group (``SamplingProcess`` role, rides the ``sample``
    query hint → SamplingIterator path)."""
    hints = {"sample": fraction}
    if threads_or_by:
        hints["sample_by"] = threads_or_by
    return ds.query(type_name, Query(filter=filter, hints=hints)).table


def min_max(ds, type_name: str, attribute: str, filter=None, cached: bool = True):
    """(min, max) of an attribute (``MinMaxProcess`` role). With ``cached``
    and no filter, served from the stats store sketches; otherwise exact via
    a planned query."""
    if cached and filter is None:
        try:
            return ds.stats_bounds(type_name, attribute)
        except Exception:
            pass
    r = ds.query(type_name, Query(filter=filter, hints={"stats": f"MinMax({attribute})"}))
    mm = r.stats[f"MinMax({attribute})"]
    return None if mm.min is None else (mm.min, mm.max)


def density(ds, type_name: str, filter=None, bbox=None, width: int = 256, height: int = 256, weight_by=None):
    """Heatmap grid over matching features (``DensityProcess`` role, rides
    the ``density`` hint → DensityScan path). Returns (height, width) f64."""
    opts = {"width": width, "height": height}
    if bbox is not None:
        opts["bbox"] = bbox
    if weight_by:
        opts["weight_by"] = weight_by
    return ds.query(type_name, Query(filter=filter, hints={"density": opts})).density


def stats(ds, type_name: str, stats_spec: str, filter=None):
    """Stat sketches over matching features (``StatsProcess`` role, rides the
    ``stats`` hint → StatsScan path). Returns label → sketch."""
    return ds.query(type_name, Query(filter=filter, hints={"stats": stats_spec})).stats


def proximity(ds, type_name: str, geometries, distance_deg: float, filter=None):
    """Features within ``distance_deg`` of any input geometry
    (``ProximitySearchProcess`` role): bbox-expanded index scan + exact
    distance refine."""
    sft = ds.get_schema(type_name)
    parts = [
        ast.SpatialOp("dwithin", sft.geom_field, g, distance=distance_deg)
        for g in geometries
    ]
    f = parts[0] if len(parts) == 1 else ast.Or(parts)
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter
        f = ast.And([f, base])
    return ds.query(type_name, Query(filter=f)).table


def point2point(table, sort_field: str, group_field: str | None = None):
    """Convert point sequences into track LineStrings (``Point2PointProcess``):
    order by ``sort_field`` (within ``group_field`` groups) and connect.
    Extended geometries contribute their bbox centroids."""
    from geomesa_tpu.schema.columnar import representative_xy

    xs, ys = representative_xy(table)
    keys = table.columns[sort_field].values
    if group_field is None:
        order = np.argsort(keys, kind="stable")
        coords = np.stack([xs[order], ys[order]], axis=1)
        return {None: LineString(coords)} if len(coords) >= 2 else {}
    groups = table.columns[group_field].values
    out = {}
    for g in np.unique(groups.astype(object)):
        sel = np.nonzero(groups == g)[0]
        if len(sel) < 2:
            continue
        order = sel[np.argsort(keys[sel], kind="stable")]
        out[g] = LineString(np.stack([xs[order], ys[order]], axis=1))
    return out


def tube_select(
    ds,
    type_name: str,
    track: list[tuple[float, float, int]],
    buffer_deg: float,
    time_buffer_ms: int,
    filter=None,
):
    """Spatio-temporal corridor search (``TubeSelectProcess``/``TubeBuilder``):
    features within ``buffer_deg`` of the track's path AND within
    ``time_buffer_ms`` of the track's local time.

    ``track``: [(lon, lat, epoch_ms), ...] ordered waypoints. Implemented as
    one OR-of-segments query (each segment = bbox+time window primary bounds)
    followed by an exact per-segment (distance, time-interpolation) refine.

    DEMOTED to the audit referee: the product path is the batched device
    corridor engine (:func:`geomesa_tpu.trajectory.corridor.
    tube_select_device`), which shadow-compares sampled results against
    this host path through the ISSUE-13 audit plane (docs/trajectory.md).
    """
    sft = ds.get_schema(type_name)
    if len(track) < 2:
        raise ValueError("tube requires at least 2 waypoints")
    pts = np.asarray([(x, y) for x, y, _ in track], dtype=np.float64)
    ts = np.asarray([t for _, _, t in track], dtype=np.int64)

    # primary scan: OR of per-segment bbox+time windows
    parts = []
    for i in range(len(track) - 1):
        x1 = min(pts[i, 0], pts[i + 1, 0]) - buffer_deg
        x2 = max(pts[i, 0], pts[i + 1, 0]) + buffer_deg
        y1 = min(pts[i, 1], pts[i + 1, 1]) - buffer_deg
        y2 = max(pts[i, 1], pts[i + 1, 1]) + buffer_deg
        t1 = int(min(ts[i], ts[i + 1]) - time_buffer_ms)
        t2 = int(max(ts[i], ts[i + 1]) + time_buffer_ms)
        parts.append(
            ast.And(
                [
                    ast.BBox(sft.geom_field, x1, y1, x2, y2),
                    ast.During(sft.dtg_field, t1 - 1, t2 + 1),
                ]
            )
        )
    f = ast.Or(parts)
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter
        f = ast.And([f, base])
    r = ds.query(type_name, Query(filter=f))
    if r.count == 0:
        return r.table

    # exact refine: distance to segment AND time within the segment's
    # (time-extended) span, vectorized over candidates × segments (extended
    # geometries refine by bbox centroid)
    from geomesa_tpu.schema.columnar import representative_xy

    xs, ys = representative_xy(r.table)
    cx = xs[:, None]
    cy = ys[:, None]
    ct = r.table.dtg_millis()[:, None]
    x1, y1 = pts[:-1, 0][None, :], pts[:-1, 1][None, :]
    x2, y2 = pts[1:, 0][None, :], pts[1:, 1][None, :]
    dx, dy = x2 - x1, y2 - y1
    len2 = dx * dx + dy * dy
    with np.errstate(divide="ignore", invalid="ignore"):
        tproj = np.where(len2 > 0, ((cx - x1) * dx + (cy - y1) * dy) / len2, 0.0)
    tproj = np.clip(tproj, 0.0, 1.0)
    d2 = (cx - (x1 + tproj * dx)) ** 2 + (cy - (y1 + tproj * dy)) ** 2
    t_lo = np.minimum(ts[:-1], ts[1:])[None, :] - time_buffer_ms
    t_hi = np.maximum(ts[:-1], ts[1:])[None, :] + time_buffer_ms
    ok = (d2 <= buffer_deg**2) & (ct >= t_lo) & (ct <= t_hi)
    keep = ok.any(axis=1)
    return r.table.take(np.nonzero(keep)[0])
