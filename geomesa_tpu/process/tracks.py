"""Track-oriented geoprocesses: route search, track labels.

Reference: ``geomesa-process`` (SURVEY.md §2.15) — ``RouteSearchProcess``
(309 LoC; features traveling along a route, matched by corridor distance and
heading alignment) and ``TrackLabelProcess`` (one label point per track — the
most recent position, used for map labeling).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import FeatureTable, representative_xy


def route_search(
    ds,
    type_name: str,
    route: list[tuple[float, float]],
    buffer_deg: float,
    heading_field: str | None = None,
    heading_tolerance_deg: float = 45.0,
    bidirectional: bool = False,
    filter=None,
):
    """Features travelling along ``route`` (``RouteSearchProcess`` role).

    ``route``: ordered (lon, lat) waypoints. A feature matches when it lies
    within ``buffer_deg`` of some route segment and — when ``heading_field``
    is given — its heading is within ``heading_tolerance_deg`` of that
    segment's bearing (or the reverse bearing too, if ``bidirectional``).

    Primary scan: OR of per-segment buffered bboxes through the planned index
    path; refine: vectorized point-to-segment distance + heading comparison.
    """
    if len(route) < 2:
        raise ValueError("route requires at least 2 waypoints")
    sft = ds.get_schema(type_name)
    pts = np.asarray(route, dtype=np.float64)

    parts = []
    for i in range(len(pts) - 1):
        x1 = min(pts[i, 0], pts[i + 1, 0]) - buffer_deg
        x2 = max(pts[i, 0], pts[i + 1, 0]) + buffer_deg
        y1 = min(pts[i, 1], pts[i + 1, 1]) - buffer_deg
        y2 = max(pts[i, 1], pts[i + 1, 1]) + buffer_deg
        parts.append(ast.BBox(sft.geom_field, x1, y1, x2, y2))
    f = parts[0] if len(parts) == 1 else ast.Or(parts)
    if filter is not None:
        from geomesa_tpu.filter.cql import parse

        base = parse(filter) if isinstance(filter, str) else filter
        f = ast.And([f, base])
    r = ds.query(type_name, Query(filter=f))
    if r.count == 0:
        return r.table

    xs, ys = representative_xy(r.table)
    cx, cy = xs[:, None], ys[:, None]
    x1, y1 = pts[:-1, 0][None, :], pts[:-1, 1][None, :]
    x2, y2 = pts[1:, 0][None, :], pts[1:, 1][None, :]
    dx, dy = x2 - x1, y2 - y1
    len2 = dx * dx + dy * dy
    with np.errstate(divide="ignore", invalid="ignore"):
        tproj = np.where(len2 > 0, ((cx - x1) * dx + (cy - y1) * dy) / len2, 0.0)
    tproj = np.clip(tproj, 0.0, 1.0)
    d2 = (cx - (x1 + tproj * dx)) ** 2 + (cy - (y1 + tproj * dy)) ** 2
    ok = d2 <= buffer_deg**2

    if heading_field is not None:
        # bearing: degrees clockwise from north (navigation convention)
        seg_bearing = np.degrees(np.arctan2(dx, dy)) % 360.0  # (1, S)
        col = r.table.columns[heading_field]
        raw = col.values.astype(np.float64)[:, None]
        # NaN headings are NOT-ALIGNED by explicit mask — previously
        # ``NaN % 360.0`` propagated NaN into the comparison, which read
        # all-False only by accident of IEEE compare semantics (and
        # sprayed invalid-value warnings); the mask states the rule
        finite = np.isfinite(raw)
        with np.errstate(invalid="ignore"):
            heading = np.where(finite, raw, 0.0) % 360.0
            diff = np.abs((heading - seg_bearing + 180.0) % 360.0 - 180.0)
        if bidirectional:
            diff = np.minimum(diff, 180.0 - diff)
        aligned = finite & (diff <= heading_tolerance_deg)
        if col.valid is not None:
            aligned &= col.valid[:, None]
        ok &= aligned

    keep = ok.any(axis=1)
    return r.table.take(np.nonzero(keep)[0])


def track_label(table: FeatureTable, track_field: str) -> FeatureTable:
    """One label feature per track — the most recent point by the schema's
    date attribute (``TrackLabelProcess`` role).

    Vectorized: lexsort by (track, time, descending-row) and take each
    group's last sorted element — the max-time row, ties resolved to the
    LOWEST original row (the historical dict-loop rule, pinned red/green
    in tests/test_trajectory.py). Output rows stay in ascending original
    order, exactly as before.
    """
    n = len(table)
    if n == 0:
        return table
    t = table.dtg_millis()
    groups = table.columns[track_field].values.astype(object)
    _ents, codes = np.unique(groups, return_inverse=True)
    # tertiary key: descending row index, so among equal (track, time)
    # rows the SMALLEST original index sorts last and wins the label
    order = np.lexsort((-np.arange(n), t, codes))
    sorted_codes = codes[order]
    last = np.nonzero(np.r_[sorted_codes[1:] != sorted_codes[:-1], True])[0]
    idx = np.sort(order[last]).astype(np.int64)
    return table.take(idx)
