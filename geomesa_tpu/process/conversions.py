"""Conversion / attribute-derivation geoprocesses.

Reference: ``geomesa-process`` (SURVEY.md §2.15) — ``ArrowConversionProcess``
(279), ``BinConversionProcess`` (131), ``DateOffsetProcess``,
``HashAttributeProcess``. Each converts or derives from a (query-planned)
result set; here the conversions ride the shared reduce pipeline so they stay
consistent with the push-down aggregation hints.
"""

from __future__ import annotations

import zlib

import numpy as np

from geomesa_tpu.planning.planner import Query
from geomesa_tpu.schema.columnar import Column, FeatureTable


def arrow_conversion(ds, type_name: str, filter=None, dictionary_encode: bool = True) -> bytes:
    """Query → Arrow IPC stream bytes (``ArrowConversionProcess`` role)."""
    from geomesa_tpu.io.arrow import to_ipc_bytes

    r = ds.query(type_name, Query(filter=filter))
    return to_ipc_bytes(r.table)


def bin_conversion(
    ds,
    type_name: str,
    filter=None,
    track: str | None = None,
    label: str | None = None,
    sort: bool = False,
) -> bytes:
    """Query → BIN track-point byte stream (``BinConversionProcess`` role):
    16-byte (trackId, dtg, lat, lon) records, 24-byte when labeled."""
    opts = {"track": track, "label": label, "sort": sort}
    r = ds.query(type_name, Query(filter=filter, hints={"bin": opts}))
    return r.bin_data


def date_offset(table: FeatureTable, offset_ms: int) -> FeatureTable:
    """Shift the schema's date attribute by ``offset_ms``
    (``DateOffsetProcess`` role); other columns are shared, not copied."""
    dtg = table.sft.dtg_field
    if dtg is None:
        raise ValueError(f"schema {table.sft.name} has no date attribute")
    col = table.columns[dtg]
    shifted = Column(col.type, col.values + np.int64(offset_ms), col.valid)
    cols = dict(table.columns)
    cols[dtg] = shifted
    return FeatureTable(table.sft, table.fids, cols)


def hash_attribute(table: FeatureTable, attribute: str, modulo: int) -> np.ndarray:
    """Stable per-feature bucket = crc32(str(value)) % modulo
    (``HashAttributeProcess`` role — deterministic across runs/processes,
    unlike Python's salted ``hash``). Null attributes hash to bucket 0."""
    if modulo <= 0:
        raise ValueError("modulo must be positive")
    col = table.columns[attribute]
    valid = col.is_valid()
    out = np.zeros(len(table), dtype=np.int64)
    vals = col.values
    for i in np.nonzero(valid)[0]:
        out[i] = zlib.crc32(str(vals[i]).encode()) % modulo
    return out
