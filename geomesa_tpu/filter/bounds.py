"""Filter → (spatial boxes, time intervals) extraction for index planning.

The ``FilterHelper.extractGeometries`` / ``extractIntervals`` role
(``geomesa-filter/.../FilterHelper.scala``, used by every key space —
``Z3IndexKeySpace.scala:100-112``; SURVEY.md §2.2): walk the AST and compute,
per indexed attribute, a *sound over-approximation* of where matching rows can
live. Unextractable subtrees (NOT, attribute predicates, cross-attribute ORs)
widen to "unconstrained" — soundness comes from the algebra:

- AND intersects child bounds (any child's bounds alone are already a cover);
- OR unions child bounds, and becomes unconstrained if any child is;
- NOT / non-indexed predicates are unconstrained.

so the returned bounds always satisfy ``rows(filter) ⊆ rows(bounds)``; the
full original filter is re-applied as the residual ("secondary") predicate
after the scan, exactly like the reference's iterator stack.

Temporal bounds are inclusive int epoch-millis intervals: CQL ``DURING`` is
exclusive (→ ``[lo+1, hi-1]``), matching ``Z3IndexKeySpace.scala:110-112``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from geomesa_tpu.filter import ast

# an interval is (lo_ms, hi_ms) inclusive; None bound = unbounded
MIN_MS = -(2**62)
MAX_MS = 2**62


# Attribute value bounds are lists of (lo, hi, lo_inc, hi_inc); None endpoint
# = unbounded on that side. The same sound over-approximation algebra as
# boxes/intervals (the ``FilterHelper.extractAttributeBounds`` role used by
# ``AttributeIndexKeySpace``).


@dataclass(frozen=True)
class Extraction:
    """Bounds for one (geom_field, dtg_field) pair plus indexed attributes.

    ``boxes``: None = spatially unconstrained; else list of (xmin, ymin, xmax,
    ymax) whose union covers all matching rows. ``intervals``: None =
    temporally unconstrained; else list of inclusive (lo_ms, hi_ms).
    ``attributes``: per-attribute value intervals (None = unconstrained).
    """

    boxes: list | None
    intervals: list | None
    attributes: dict = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.attributes is None:
            object.__setattr__(self, "attributes", {})

    @property
    def spatially_bounded(self) -> bool:
        return self.boxes is not None

    @property
    def temporally_bounded(self) -> bool:
        return self.intervals is not None

    def attr_bounded(self, name: str) -> bool:
        return self.attributes.get(name) is not None

    @property
    def disjoint(self) -> bool:
        """True when bounds prove the filter matches nothing."""
        return (
            (self.boxes is not None and len(self.boxes) == 0)
            or (self.intervals is not None and len(self.intervals) == 0)
            or any(v is not None and len(v) == 0 for v in self.attributes.values())
        )


def extract(
    f: ast.Filter,
    geom_field: str | None,
    dtg_field: str | None,
    attrs: tuple = (),
) -> Extraction:
    boxes, intervals = _walk(f, geom_field, dtg_field)
    if boxes is not None:
        boxes = _dedupe_boxes(boxes)
    if intervals is not None:
        intervals = _merge_intervals(intervals)
    attributes = {a: _walk_attr(f, a) for a in attrs}
    return Extraction(boxes, intervals, attributes)


def _walk_attr(f: ast.Filter, attr: str):
    """Value intervals for one attribute: None = unconstrained, [] = disjoint."""
    if isinstance(f, ast.And):
        out = None
        for c in f.children:
            out = _intersect_attr(out, _walk_attr(c, attr))
        return out
    if isinstance(f, ast.Or):
        out = []
        for c in f.children:
            ci = _walk_attr(c, attr)
            if ci is None:
                return None
            out.extend(ci)
        return out
    if isinstance(f, ast.Compare) and f.prop == attr:
        v = f.literal
        if f.op == "=":
            return [(v, v, True, True)]
        if f.op == "<":
            return [(None, v, True, False)]
        if f.op == "<=":
            return [(None, v, True, True)]
        if f.op == ">":
            return [(v, None, False, True)]
        if f.op == ">=":
            return [(v, None, True, True)]
        return None  # <> : unconstrained
    if isinstance(f, ast.Between) and f.prop == attr:
        return [(f.lo, f.hi, True, True)]
    if isinstance(f, ast.In) and f.prop == attr:
        return [(v, v, True, True) for v in f.literals]
    if isinstance(f, ast.Like) and f.prop == attr:
        # prefix pattern -> range [prefix, next_prefix): the upper bound is the
        # prefix with its last char incremented, so EVERY string starting with
        # the prefix (including supplementary-plane chars) stays inside the
        # cover — bounds must over-approximate
        p = f.pattern
        i = min(
            (p.index(c) for c in "%_" if c in p), default=len(p)
        )
        prefix = p[:i]
        if not prefix:
            return None
        return [(prefix, _prefix_upper(prefix), True, False)]
    if isinstance(f, ast.Exclude):
        return []
    return None


def _prefix_upper(prefix: str) -> str | None:
    """Smallest string greater than every string with this prefix (None if the
    prefix is all U+10FFFF — then the range is unbounded above)."""
    chars = list(prefix)
    while chars:
        if ord(chars[-1]) < 0x10FFFF:
            chars[-1] = chr(ord(chars[-1]) + 1)
            return "".join(chars)
        chars.pop()
    return None


def coerce_attr_bounds(sft, extraction: "Extraction") -> "Extraction":
    """Normalize extracted attribute bounds to column value types: quoted CQL
    date literals arrive as strings but DATE columns store int64 millis."""
    from geomesa_tpu.schema.sft import AttributeType

    out = {}
    changed = False
    for name, bounds in extraction.attributes.items():
        if bounds is None or name not in sft:
            out[name] = bounds
            continue
        if sft.attr(name).type == AttributeType.DATE:
            from geomesa_tpu.schema.columnar import _to_millis

            def conv(v):
                return _to_millis(v) if isinstance(v, str) else v

            bounds = [
                (conv(lo) if lo is not None else None, conv(hi) if hi is not None else None, li, ri)
                for lo, hi, li, ri in bounds
            ]
            changed = True
        out[name] = bounds
    if not changed:
        return extraction
    return Extraction(extraction.boxes, extraction.intervals, out)


def _intersect_attr(a, b):
    if a is None:
        return b
    if b is None:
        return a
    out = []
    for alo, ahi, ali, ari in a:
        for blo, bhi, bli, bri in b:
            lo, li = _max_lo((alo, ali), (blo, bli))
            hi, ri = _min_hi((ahi, ari), (bhi, bri))
            if _nonempty(lo, hi, li, ri):
                out.append((lo, hi, li, ri))
    return out


def _max_lo(a, b):
    (alo, ai), (blo, bi) = a, b
    if alo is None:
        return blo, bi
    if blo is None:
        return alo, ai
    if alo == blo:
        return alo, ai and bi
    return (alo, ai) if _gt(alo, blo) else (blo, bi)


def _min_hi(a, b):
    (ahi, ai), (bhi, bi) = a, b
    if ahi is None:
        return bhi, bi
    if bhi is None:
        return ahi, ai
    if ahi == bhi:
        return ahi, ai and bi
    return (ahi, ai) if _gt(bhi, ahi) else (bhi, bi)


def _gt(a, b):
    try:
        return a > b
    except TypeError:
        return str(a) > str(b)


def _nonempty(lo, hi, li, ri):
    if lo is None or hi is None:
        return True
    if _gt(lo, hi):
        return False
    if lo == hi and not (li and ri):
        return False
    return True


def _walk(f: ast.Filter, geom: str | None, dtg: str | None):
    """Returns (boxes|None, intervals|None)."""
    if isinstance(f, ast.And):
        boxes, intervals = None, None
        for c in f.children:
            cb, ci = _walk(c, geom, dtg)
            boxes = _intersect_boxes(boxes, cb)
            intervals = _intersect_intervals(intervals, ci)
        return boxes, intervals
    if isinstance(f, ast.Or):
        boxes_list, iv_list = [], []
        any_unbounded_space = False
        any_unbounded_time = False
        for c in f.children:
            cb, ci = _walk(c, geom, dtg)
            if cb is None:
                any_unbounded_space = True
            else:
                boxes_list.extend(cb)
            if ci is None:
                any_unbounded_time = True
            else:
                iv_list.extend(ci)
        return (
            None if any_unbounded_space else boxes_list,
            None if any_unbounded_time else iv_list,
        )
    if isinstance(f, ast.BBox) and f.prop == geom:
        return _split_lon([f.bounds]), None
    if isinstance(f, ast.SpatialOp) and f.prop == geom:
        if f.op in ("disjoint", "beyond", "relate"):
            # matches may lie anywhere (relate patterns can encode
            # disjointness): unconstrained — evaluated as residual only
            return None, None
        xmin, ymin, xmax, ymax = f.geometry.bbox
        if f.op == "dwithin":
            d = f.distance
            xmin, ymin, xmax, ymax = xmin - d, ymin - d, xmax + d, ymax + d
        return _split_lon([(xmin, ymin, xmax, ymax)]), None
    if isinstance(f, ast.During) and f.prop == dtg:
        return None, [(f.lo_millis + 1, f.hi_millis - 1)]
    if isinstance(f, ast.TempOp) and f.prop == dtg:
        if f.op == "before":
            return None, [(MIN_MS, f.millis - 1)]
        if f.op == "after":
            return None, [(f.millis + 1, MAX_MS)]
        return None, [(f.millis, f.millis)]  # tequals
    if isinstance(f, ast.Between) and f.prop == dtg:
        from geomesa_tpu.schema.columnar import _to_millis

        lo = f.lo if isinstance(f.lo, (int, np.integer)) else _to_millis(f.lo)
        hi = f.hi if isinstance(f.hi, (int, np.integer)) else _to_millis(f.hi)
        return None, [(int(lo), int(hi))]
    if isinstance(f, ast.Compare) and f.prop == dtg:
        from geomesa_tpu.schema.columnar import _to_millis

        lit = f.literal if isinstance(f.literal, (int, np.integer)) else _to_millis(f.literal)
        lit = int(lit)
        if f.op == "=":
            return None, [(lit, lit)]
        if f.op == "<":
            return None, [(MIN_MS, lit - 1)]
        if f.op == "<=":
            return None, [(MIN_MS, lit)]
        if f.op == ">":
            return None, [(lit + 1, MAX_MS)]
        if f.op == ">=":
            return None, [(lit, MAX_MS)]
        return None, None
    if isinstance(f, ast.Exclude):
        return [], []
    # Include, Not, attribute predicates, fid filters: unconstrained
    return None, None


def _split_lon(boxes):
    """Clamp to the world and split antimeridian-wrapping boxes."""
    out = []
    for xmin, ymin, xmax, ymax in boxes:
        ymin = max(ymin, -90.0)
        ymax = min(ymax, 90.0)
        if ymin > ymax:
            continue
        if xmin > xmax:  # antimeridian wrap
            out.append((max(xmin, -180.0), ymin, 180.0, ymax))
            out.append((-180.0, ymin, min(xmax, 180.0), ymax))
        else:
            out.append((max(xmin, -180.0), ymin, min(xmax, 180.0), ymax))
    return out


def _intersect_boxes(a, b):
    if a is None:
        return b
    if b is None:
        return a
    out = []
    for ax1, ay1, ax2, ay2 in a:
        for bx1, by1, bx2, by2 in b:
            x1, y1 = max(ax1, bx1), max(ay1, by1)
            x2, y2 = min(ax2, bx2), min(ay2, by2)
            if x1 <= x2 and y1 <= y2:
                out.append((x1, y1, x2, y2))
    return out


def _intersect_intervals(a, b):
    if a is None:
        return b
    if b is None:
        return a
    out = []
    for alo, ahi in a:
        for blo, bhi in b:
            lo, hi = max(alo, blo), min(ahi, bhi)
            if lo <= hi:
                out.append((lo, hi))
    return out


def _merge_intervals(ivs):
    if not ivs:
        return []
    ivs = sorted(ivs)
    out = [list(ivs[0])]
    for lo, hi in ivs[1:]:
        if lo <= out[-1][1] + 1:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return [tuple(iv) for iv in out]


def _dedupe_boxes(boxes):
    seen = set()
    out = []
    for b in boxes:
        if b not in seen:
            seen.add(b)
            out.append(b)
    return out
