"""Filter → (spatial boxes, time intervals) extraction for index planning.

The ``FilterHelper.extractGeometries`` / ``extractIntervals`` role
(``geomesa-filter/.../FilterHelper.scala``, used by every key space —
``Z3IndexKeySpace.scala:100-112``; SURVEY.md §2.2): walk the AST and compute,
per indexed attribute, a *sound over-approximation* of where matching rows can
live. Unextractable subtrees (NOT, attribute predicates, cross-attribute ORs)
widen to "unconstrained" — soundness comes from the algebra:

- AND intersects child bounds (any child's bounds alone are already a cover);
- OR unions child bounds, and becomes unconstrained if any child is;
- NOT / non-indexed predicates are unconstrained.

so the returned bounds always satisfy ``rows(filter) ⊆ rows(bounds)``; the
full original filter is re-applied as the residual ("secondary") predicate
after the scan, exactly like the reference's iterator stack.

Temporal bounds are inclusive int epoch-millis intervals: CQL ``DURING`` is
exclusive (→ ``[lo+1, hi-1]``), matching ``Z3IndexKeySpace.scala:110-112``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from geomesa_tpu.filter import ast

# an interval is (lo_ms, hi_ms) inclusive; None bound = unbounded
MIN_MS = -(2**62)
MAX_MS = 2**62


@dataclass(frozen=True)
class Extraction:
    """Bounds for one (geom_field, dtg_field) pair.

    ``boxes``: None = spatially unconstrained; else list of (xmin, ymin, xmax,
    ymax) whose union covers all matching rows. ``intervals``: None =
    temporally unconstrained; else list of inclusive (lo_ms, hi_ms).
    """

    boxes: list | None
    intervals: list | None

    @property
    def spatially_bounded(self) -> bool:
        return self.boxes is not None

    @property
    def temporally_bounded(self) -> bool:
        return self.intervals is not None

    @property
    def disjoint(self) -> bool:
        """True when bounds prove the filter matches nothing."""
        return (self.boxes is not None and len(self.boxes) == 0) or (
            self.intervals is not None and len(self.intervals) == 0
        )


def extract(f: ast.Filter, geom_field: str | None, dtg_field: str | None) -> Extraction:
    boxes, intervals = _walk(f, geom_field, dtg_field)
    if boxes is not None:
        boxes = _dedupe_boxes(boxes)
    if intervals is not None:
        intervals = _merge_intervals(intervals)
    return Extraction(boxes, intervals)


def _walk(f: ast.Filter, geom: str | None, dtg: str | None):
    """Returns (boxes|None, intervals|None)."""
    if isinstance(f, ast.And):
        boxes, intervals = None, None
        for c in f.children:
            cb, ci = _walk(c, geom, dtg)
            boxes = _intersect_boxes(boxes, cb)
            intervals = _intersect_intervals(intervals, ci)
        return boxes, intervals
    if isinstance(f, ast.Or):
        boxes_list, iv_list = [], []
        any_unbounded_space = False
        any_unbounded_time = False
        for c in f.children:
            cb, ci = _walk(c, geom, dtg)
            if cb is None:
                any_unbounded_space = True
            else:
                boxes_list.extend(cb)
            if ci is None:
                any_unbounded_time = True
            else:
                iv_list.extend(ci)
        return (
            None if any_unbounded_space else boxes_list,
            None if any_unbounded_time else iv_list,
        )
    if isinstance(f, ast.BBox) and f.prop == geom:
        return _split_lon([f.bounds]), None
    if isinstance(f, ast.SpatialOp) and f.prop == geom:
        if f.op == "disjoint":
            return None, None  # complement of a box: unconstrained
        xmin, ymin, xmax, ymax = f.geometry.bbox
        if f.op == "dwithin":
            d = f.distance
            xmin, ymin, xmax, ymax = xmin - d, ymin - d, xmax + d, ymax + d
        return _split_lon([(xmin, ymin, xmax, ymax)]), None
    if isinstance(f, ast.During) and f.prop == dtg:
        return None, [(f.lo_millis + 1, f.hi_millis - 1)]
    if isinstance(f, ast.TempOp) and f.prop == dtg:
        if f.op == "before":
            return None, [(MIN_MS, f.millis - 1)]
        if f.op == "after":
            return None, [(f.millis + 1, MAX_MS)]
        return None, [(f.millis, f.millis)]  # tequals
    if isinstance(f, ast.Between) and f.prop == dtg:
        from geomesa_tpu.schema.columnar import _to_millis

        lo = f.lo if isinstance(f.lo, (int, np.integer)) else _to_millis(f.lo)
        hi = f.hi if isinstance(f.hi, (int, np.integer)) else _to_millis(f.hi)
        return None, [(int(lo), int(hi))]
    if isinstance(f, ast.Compare) and f.prop == dtg:
        from geomesa_tpu.schema.columnar import _to_millis

        lit = f.literal if isinstance(f.literal, (int, np.integer)) else _to_millis(f.literal)
        lit = int(lit)
        if f.op == "=":
            return None, [(lit, lit)]
        if f.op == "<":
            return None, [(MIN_MS, lit - 1)]
        if f.op == "<=":
            return None, [(MIN_MS, lit)]
        if f.op == ">":
            return None, [(lit + 1, MAX_MS)]
        if f.op == ">=":
            return None, [(lit, MAX_MS)]
        return None, None
    if isinstance(f, ast.Exclude):
        return [], []
    # Include, Not, attribute predicates, fid filters: unconstrained
    return None, None


def _split_lon(boxes):
    """Clamp to the world and split antimeridian-wrapping boxes."""
    out = []
    for xmin, ymin, xmax, ymax in boxes:
        ymin = max(ymin, -90.0)
        ymax = min(ymax, 90.0)
        if ymin > ymax:
            continue
        if xmin > xmax:  # antimeridian wrap
            out.append((max(xmin, -180.0), ymin, 180.0, ymax))
            out.append((-180.0, ymin, min(xmax, 180.0), ymax))
        else:
            out.append((max(xmin, -180.0), ymin, min(xmax, 180.0), ymax))
    return out


def _intersect_boxes(a, b):
    if a is None:
        return b
    if b is None:
        return a
    out = []
    for ax1, ay1, ax2, ay2 in a:
        for bx1, by1, bx2, by2 in b:
            x1, y1 = max(ax1, bx1), max(ay1, by1)
            x2, y2 = min(ax2, bx2), min(ay2, by2)
            if x1 <= x2 and y1 <= y2:
                out.append((x1, y1, x2, y2))
    return out


def _intersect_intervals(a, b):
    if a is None:
        return b
    if b is None:
        return a
    out = []
    for alo, ahi in a:
        for blo, bhi in b:
            lo, hi = max(alo, blo), min(ahi, bhi)
            if lo <= hi:
                out.append((lo, hi))
    return out


def _merge_intervals(ivs):
    if not ivs:
        return []
    ivs = sorted(ivs)
    out = [list(ivs[0])]
    for lo, hi in ivs[1:]:
        if lo <= out[-1][1] + 1:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return [tuple(iv) for iv in out]


def _dedupe_boxes(boxes):
    seen = set()
    out = []
    for b in boxes:
        if b not in seen:
            seen.add(b)
            out.append(b)
    return out
