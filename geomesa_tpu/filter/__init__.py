"""Filter layer: AST, CQL parser, bounds extraction (``geomesa-filter`` role)."""

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.bounds import Extraction, extract
from geomesa_tpu.filter.cql import CQLError, parse

__all__ = ["ast", "parse", "CQLError", "extract", "Extraction"]
