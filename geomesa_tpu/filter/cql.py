"""CQL(-subset) text parser → :mod:`geomesa_tpu.filter.ast` nodes.

The role of GeoTools' ECQL parser as used throughout the reference (queries
arrive as CQL strings in tools/tests: ``bbox(geom,-10,-10,10,10) AND dtg
DURING 2018-01-01T00:00:00.000Z/2018-01-02T00:00:00.000Z``). Supported:

- ``INCLUDE`` / ``EXCLUDE``
- ``BBOX(geom, xmin, ymin, xmax, ymax)``
- ``INTERSECTS/WITHIN/CONTAINS/DISJOINT(geom, <WKT>)``, ``DWITHIN(geom, <WKT>, dist, units)``
- ``dtg DURING t1/t2``, ``dtg BEFORE t``, ``dtg AFTER t``, ``dtg TEQUALS t``
- comparisons ``= <> < <= > >=``, ``BETWEEN ... AND ...``, ``IN (...)``,
  ``LIKE``, ``IS [NOT] NULL``
- ``AND`` / ``OR`` / ``NOT``, parentheses
- bare ``IN ('id1', ...)`` as a feature-id filter

Recursive-descent over a cursor (WKT literals need balanced-paren scanning).
"""

from __future__ import annotations

import re

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.geometry.wkt import from_wkt

_WS = re.compile(r"\s+")
_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_.]*")
_NUMBER = re.compile(r"[-+]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][-+]?\d+)?")
_DATETIME = re.compile(
    r"\d{4}-\d{2}-\d{2}(?:T\d{2}:\d{2}:\d{2}(?:\.\d+)?Z?)?"
)
_GEOM_KEYWORDS = (
    "POINT",
    "LINESTRING",
    "POLYGON",
    "MULTIPOINT",
    "MULTILINESTRING",
    "MULTIPOLYGON",
)
_SPATIAL_OPS = {
    "INTERSECTS": "intersects",
    "WITHIN": "within",
    "CONTAINS": "contains",
    "DISJOINT": "disjoint",
    "CROSSES": "crosses",
    "TOUCHES": "touches",
    "OVERLAPS": "overlaps",
    "EQUALS": "equals",
}


class CQLError(ValueError):
    pass


def parse(cql: str) -> ast.Filter:
    p = _Parser(cql)
    f = p.parse_or()
    p.skip_ws()
    if p.pos != len(p.s):
        raise CQLError(f"trailing input at {p.pos}: {p.s[p.pos:p.pos+30]!r}")
    return f


def datetime_to_millis(s: str) -> int:
    """ISO-8601 (subset) → epoch millis."""
    s = s.strip().rstrip("Z")
    return int(np.datetime64(s, "ms").astype(np.int64))


class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.pos = 0

    # -- low-level -----------------------------------------------------------
    def skip_ws(self):
        m = _WS.match(self.s, self.pos)
        if m:
            self.pos = m.end()

    def peek_word(self) -> str:
        self.skip_ws()
        m = _IDENT.match(self.s, self.pos)
        return m.group(0).upper() if m else ""

    def take_word(self) -> str:
        self.skip_ws()
        m = _IDENT.match(self.s, self.pos)
        if not m:
            raise CQLError(f"expected identifier at {self.pos}: {self.s[self.pos:self.pos+20]!r}")
        self.pos = m.end()
        return m.group(0)

    def expect(self, ch: str):
        self.skip_ws()
        if not self.s.startswith(ch, self.pos):
            raise CQLError(f"expected {ch!r} at {self.pos}: {self.s[self.pos:self.pos+20]!r}")
        self.pos += len(ch)

    def try_take(self, ch: str) -> bool:
        self.skip_ws()
        if self.s.startswith(ch, self.pos):
            self.pos += len(ch)
            return True
        return False

    def _comparison_op(self, after: str) -> str:
        """Consume one of <> <= >= = < > or raise (shared by the jsonPath,
        property-function, and plain comparison predicate tails)."""
        self.skip_ws()
        for op in ("<>", "<=", ">=", "=", "<", ">"):
            if self.s.startswith(op, self.pos):
                self.pos += len(op)
                return op
        raise CQLError(f"expected comparison after {after} at {self.pos}")

    def number(self) -> float:
        self.skip_ws()
        m = _NUMBER.match(self.s, self.pos)
        if not m:
            raise CQLError(f"expected number at {self.pos}: {self.s[self.pos:self.pos+20]!r}")
        self.pos = m.end()
        return float(m.group(0))

    def quoted(self) -> str:
        self.skip_ws()
        q = self.s[self.pos]
        if q not in "'\"":
            raise CQLError(f"expected quote at {self.pos}")
        end = self.s.find(q, self.pos + 1)
        # CQL doubles quotes to escape: 'it''s'
        while end != -1 and self.s[end + 1 : end + 2] == q:
            end = self.s.find(q, end + 2)
        if end == -1:
            raise CQLError("unterminated string literal")
        raw = self.s[self.pos + 1 : end].replace(q + q, q)
        self.pos = end + 1
        return raw

    def wkt(self):
        self.skip_ws()
        up = self.s[self.pos :].upper()
        for kw in _GEOM_KEYWORDS:
            if up.startswith(kw):
                # scan balanced parens
                i = self.pos + len(kw)
                while self.s[i] in " \t\n":
                    i += 1
                if self.s[i] != "(":
                    raise CQLError(f"bad WKT at {self.pos}")
                depth = 0
                j = i
                while j < len(self.s):
                    if self.s[j] == "(":
                        depth += 1
                    elif self.s[j] == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    j += 1
                if depth != 0:
                    raise CQLError("unbalanced parens in WKT")
                text = self.s[self.pos : j + 1]
                self.pos = j + 1
                return from_wkt(text)
        raise CQLError(f"expected WKT geometry at {self.pos}: {self.s[self.pos:self.pos+20]!r}")

    def datetime_millis(self) -> int:
        self.skip_ws()
        if self.s[self.pos] in "'\"":
            return datetime_to_millis(self.quoted())
        m = _DATETIME.match(self.s, self.pos)
        if not m:
            raise CQLError(f"expected datetime at {self.pos}: {self.s[self.pos:self.pos+25]!r}")
        self.pos = m.end()
        return datetime_to_millis(m.group(0))

    def literal(self):
        self.skip_ws()
        ch = self.s[self.pos]
        if ch in "'\"":
            return self.quoted()
        m = _DATETIME.match(self.s, self.pos)
        if m and "-" in m.group(0)[1:]:
            self.pos = m.end()
            return datetime_to_millis(m.group(0))
        m = _NUMBER.match(self.s, self.pos)
        if m:
            self.pos = m.end()
            txt = m.group(0)
            return float(txt) if ("." in txt or "e" in txt or "E" in txt) else int(txt)
        w = self.take_word()
        if w.upper() == "TRUE":
            return True
        if w.upper() == "FALSE":
            return False
        return w

    # -- grammar ---------------------------------------------------------------
    def parse_or(self) -> ast.Filter:
        left = self.parse_and()
        parts = [left]
        while self.peek_word() == "OR":
            self.take_word()
            parts.append(self.parse_and())
        return parts[0] if len(parts) == 1 else ast.Or(parts)

    def parse_and(self) -> ast.Filter:
        left = self.parse_unary()
        parts = [left]
        while self.peek_word() == "AND":
            self.take_word()
            parts.append(self.parse_unary())
        return parts[0] if len(parts) == 1 else ast.And(parts)

    def parse_unary(self) -> ast.Filter:
        w = self.peek_word()
        if w == "NOT":
            self.take_word()
            return ast.Not(self.parse_unary())
        self.skip_ws()
        if self.s.startswith("(", self.pos):
            self.expect("(")
            f = self.parse_or()
            self.expect(")")
            return f
        return self.parse_predicate()

    def parse_predicate(self) -> ast.Filter:
        w = self.peek_word()
        if w == "INCLUDE":
            self.take_word()
            return ast.Include()
        if w == "EXCLUDE":
            self.take_word()
            return ast.Exclude()
        if w == "BBOX":
            self.take_word()
            self.expect("(")
            prop = self.take_word()
            self.expect(",")
            xmin = self.number()
            self.expect(",")
            ymin = self.number()
            self.expect(",")
            xmax = self.number()
            self.expect(",")
            ymax = self.number()
            # optional CRS argument
            if self.try_take(","):
                self.quoted()
            self.expect(")")
            return ast.BBox(prop, xmin, ymin, xmax, ymax)
        if w in _SPATIAL_OPS:
            self.take_word()
            self.expect("(")
            prop = self.take_word()
            self.expect(",")
            geom = self.wkt()
            self.expect(")")
            return ast.SpatialOp(_SPATIAL_OPS[w], prop, geom)
        if w in ("DWITHIN", "BEYOND"):
            self.take_word()
            self.expect("(")
            prop = self.take_word()
            self.expect(",")
            geom = self.wkt()
            self.expect(",")
            dist = self.number()
            self.expect(",")
            units = self.take_word().lower()
            self.expect(")")
            dist = _to_degrees(dist, units)
            return ast.SpatialOp(w.lower(), prop, geom, distance=dist)
        if w == "RELATE":
            # RELATE(geom, <wkt>, 'DE-9IM pattern')
            self.take_word()
            self.expect("(")
            prop = self.take_word()
            self.expect(",")
            geom = self.wkt()
            self.expect(",")
            pattern = self.quoted()
            self.expect(")")
            pattern = pattern.upper()
            if len(pattern) != 9 or any(c not in "TF*012" for c in pattern):
                raise CQLError(
                    f"RELATE pattern must be 9 chars of TF*012: {pattern!r}"
                )
            return ast.SpatialOp("relate", prop, geom, pattern=pattern)
        if w == "IN":  # bare fid filter
            self.take_word()
            self.expect("(")
            fids = [str(self.literal())]
            while self.try_take(","):
                fids.append(str(self.literal()))
            self.expect(")")
            return ast.FidIn(tuple(fids))
        if w.upper() == "JSONPATH":
            # jsonPath('<$.path>', attr) <op> <literal>  — JSON attribute
            # query (KryoJsonSerialization role); both argument orders accepted
            self.take_word()
            self.expect("(")

            def _path_or_ident():
                self.skip_ws()
                return (
                    self.quoted()
                    if self.s[self.pos : self.pos + 1] in ("'", '"')
                    else self.take_word()
                )

            a1 = _path_or_ident()
            self.expect(",")
            a2 = _path_or_ident()
            self.expect(")")
            path, attr = (a1, a2) if str(a1).startswith("$") else (a2, a1)
            if not str(path).startswith("$"):
                raise CQLError(f"jsonPath needs a '$...' path: {a1!r}, {a2!r}")
            op = self._comparison_op(after="jsonPath")
            return ast.JsonPathCompare(op, str(path), str(attr), self.literal())

        if w.lower() in ast._PROP_FUNCS:
            # func(attr) <op> literal — FastFilterFactory function role.
            # Only a call shape selects this branch: an ATTRIBUTE merely
            # named 'abs'/'floor'/... must still parse as a plain predicate
            save = self.pos
            func = self.take_word().lower()
            self.skip_ws()
            if self.s.startswith("(", self.pos):
                self.expect("(")
                prop = self.take_word()
                self.expect(")")
                op = self._comparison_op(after=f"{func}()")
                return ast.FuncCompare(func, op, prop, self.literal())
            self.pos = save  # not a call: fall through to property-led

        # property-led predicates
        prop = self.take_word()
        nxt = self.peek_word()
        if nxt == "DURING":
            self.take_word()
            lo = self.datetime_millis()
            self.expect("/")
            hi = self.datetime_millis()
            return ast.During(prop, lo, hi)
        if nxt in ("BEFORE", "AFTER", "TEQUALS"):
            self.take_word()
            t = self.datetime_millis()
            return ast.TempOp(nxt.lower(), prop, t)
        if nxt == "BETWEEN":
            self.take_word()
            lo = self.literal()
            if self.peek_word() != "AND":
                raise CQLError("expected AND in BETWEEN")
            self.take_word()
            hi = self.literal()
            return ast.Between(prop, lo, hi)
        if nxt == "IN":
            self.take_word()
            self.expect("(")
            lits = [self.literal()]
            while self.try_take(","):
                lits.append(self.literal())
            self.expect(")")
            return ast.In(prop, tuple(lits))
        if nxt in ("LIKE", "ILIKE"):
            self.take_word()
            return ast.Like(prop, self.quoted(), nocase=nxt == "ILIKE")
        if nxt == "IS":
            self.take_word()
            if self.peek_word() == "NOT":
                self.take_word()
                if self.take_word().upper() != "NULL":
                    raise CQLError("expected NULL")
                return ast.Not(ast.IsNull(prop))
            if self.take_word().upper() != "NULL":
                raise CQLError("expected NULL")
            return ast.IsNull(prop)

        # comparison operators
        try:
            op = self._comparison_op(after=f"property {prop!r}")
        except CQLError:
            raise CQLError(
                f"cannot parse predicate at {self.pos}: "
                f"{self.s[self.pos:self.pos+30]!r}"
            ) from None
        return ast.Compare(op, prop, self.literal())


_METERS_PER_DEGREE = 111_320.0


def _to_degrees(dist: float, units: str) -> float:
    """DWithin distance → degrees (planar approximation at the equator, the
    same simplification the reference applies for geodesic DWithin buffering
    in ``GeometryProcessing.scala``)."""
    if units in ("meters", "metres", "m"):
        return dist / _METERS_PER_DEGREE
    if units in ("kilometers", "km"):
        return dist * 1000.0 / _METERS_PER_DEGREE
    if units in ("feet", "ft"):
        return dist * 0.3048 / _METERS_PER_DEGREE
    if units in ("statute_miles", "miles", "mi"):
        return dist * 1609.344 / _METERS_PER_DEGREE
    if units in ("nautical_miles", "nm"):
        return dist * 1852.0 / _METERS_PER_DEGREE
    if units in ("degrees", "deg"):
        return dist
    raise CQLError(f"unknown distance units: {units!r}")
