"""Filter AST: the query predicate language (the OGC ``Filter`` role).

A drastically simplified, typed re-design of the reference's GeoTools filter
objects + CNF/DNF rewriting (``geomesa-filter/.../filter/package.scala``,
SURVEY.md §2.2). Nodes are immutable; evaluation against a
:class:`~geomesa_tpu.schema.columnar.FeatureTable` is *vectorized* — every node
evaluates to a boolean mask over the whole table (this is the CPU-oracle
semantics the device kernels must match).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import re

import numpy as np

from geomesa_tpu.geometry import predicates as P
from geomesa_tpu.geometry.types import Geometry
from geomesa_tpu.schema.columnar import FeatureTable, GeometryColumn
from geomesa_tpu.schema.sft import AttributeType


class Filter:
    """Base node; ``mask(table)`` is the vectorized truth function."""

    def mask(self, table: FeatureTable) -> np.ndarray:
        raise NotImplementedError

    # -- combinators ---------------------------------------------------------
    def __and__(self, other: "Filter") -> "Filter":
        return And([self, other])

    def __or__(self, other: "Filter") -> "Filter":
        return Or([self, other])

    def __invert__(self) -> "Filter":
        return Not(self)


@dataclass(frozen=True)
class Include(Filter):
    """Matches everything (CQL ``INCLUDE``)."""

    def mask(self, table):
        return np.ones(len(table), dtype=bool)


@dataclass(frozen=True)
class Exclude(Filter):
    def mask(self, table):
        return np.zeros(len(table), dtype=bool)


@dataclass(frozen=True)
class And(Filter):
    children: Sequence[Filter]

    def __post_init__(self):
        object.__setattr__(self, "children", tuple(_flatten(And, self.children)))

    def mask(self, table):
        m = np.ones(len(table), dtype=bool)
        for c in self.children:
            m &= c.mask(table)
        return m


@dataclass(frozen=True)
class Or(Filter):
    children: Sequence[Filter]

    def __post_init__(self):
        object.__setattr__(self, "children", tuple(_flatten(Or, self.children)))

    def mask(self, table):
        m = np.zeros(len(table), dtype=bool)
        for c in self.children:
            m |= c.mask(table)
        return m


@dataclass(frozen=True)
class Not(Filter):
    child: Filter

    def mask(self, table):
        return ~self.child.mask(table)


def _flatten(cls, children):
    out = []
    for c in children:
        if isinstance(c, cls):
            out.extend(c.children)
        else:
            out.append(c)
    return out


# ---------------------------------------------------------------------------
# spatial predicates
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BBox(Filter):
    """``BBOX(geom, xmin, ymin, xmax, ymax)`` — geometry bbox intersects box."""

    prop: str
    xmin: float
    ymin: float
    xmax: float
    ymax: float

    @property
    def bounds(self):
        return (self.xmin, self.ymin, self.xmax, self.ymax)

    def mask(self, table):
        col: GeometryColumn = table.columns[self.prop]  # type: ignore[assignment]
        b = col.bounds
        if self.xmin > self.xmax:  # antimeridian wrap: lon > xmin OR lon < xmax
            mx = (b[:, 2] >= self.xmin) | (b[:, 0] <= self.xmax)
        else:
            mx = (b[:, 2] >= self.xmin) & (b[:, 0] <= self.xmax)
        m = mx & (b[:, 3] >= self.ymin) & (b[:, 1] <= self.ymax)
        return m & col.is_valid()


_VECTOR_SPATIAL_OPS = frozenset(
    {"intersects", "within", "contains", "disjoint", "dwithin", "beyond",
     "equals"}
)


@dataclass(frozen=True)
class SpatialOp(Filter):
    """Spatial predicate against a literal geometry: intersects / within /
    contains / disjoint / dwithin / beyond / equals / crosses / touches /
    overlaps / relate (DE-9IM pattern)."""

    op: str
    prop: str
    geometry: Geometry
    distance: float = 0.0  # dwithin/beyond only (degrees)
    pattern: str = ""  # relate only (DE-9IM, e.g. "T*T******")

    def mask(self, table):
        col: GeometryColumn = table.columns[self.prop]  # type: ignore[assignment]
        valid = col.is_valid()
        if (
            col.type == AttributeType.POINT
            and col.x is not None
            and self.op in _VECTOR_SPATIAL_OPS
        ):
            m = self._points_mask(col.x, col.y)
        else:
            geoms = col.geometries()
            m = np.zeros(len(table), dtype=bool)
            for i in range(len(table)):
                if not valid[i]:
                    continue
                m[i] = self._scalar(geoms[i])
        # null geometries never match, including for disjoint (JTS semantics)
        return m & valid

    def _points_mask(self, xs, ys):
        g = self.geometry
        if self.op == "intersects":
            return P.points_intersect_geom(xs, ys, g)
        if self.op == "within":
            return P.points_within_geom(xs, ys, g)
        if self.op == "contains":
            # a point can only contain an equal point
            return P.points_intersect_geom(xs, ys, g) if g.is_point else np.zeros(len(xs), bool)
        if self.op == "disjoint":
            return ~P.points_intersect_geom(xs, ys, g)
        if self.op == "dwithin":
            return P.points_dist2_geom(xs, ys, g) <= self.distance**2
        if self.op == "beyond":
            return P.points_dist2_geom(xs, ys, g) > self.distance**2
        if self.op == "equals":
            # a point only equals an identical point
            if not g.is_point:
                return np.zeros(len(xs), bool)
            return (xs == g.x) & (ys == g.y)
        raise ValueError(f"unknown spatial op: {self.op}")

    def _scalar(self, geom) -> bool:
        g = self.geometry
        if self.op == "intersects":
            return P.intersects(geom, g)
        if self.op == "within":
            return P.within(geom, g)
        if self.op == "contains":
            return P.contains(geom, g)
        if self.op == "disjoint":
            return P.disjoint(geom, g)
        if self.op == "dwithin":
            return P.dwithin(geom, g, self.distance)
        if self.op == "beyond":
            return not P.dwithin(geom, g, self.distance)
        # DE-9IM-backed predicates (geometry/ops.py from-scratch relate)
        from geomesa_tpu.geometry import ops as _ops

        if self.op == "equals":
            return _ops.equals(geom, g)
        if self.op == "crosses":
            return _ops.crosses(geom, g)
        if self.op == "touches":
            return _ops.touches(geom, g)
        if self.op == "overlaps":
            return _ops.overlaps(geom, g)
        if self.op == "relate":
            return _ops.relate_bool(geom, g, self.pattern)
        raise ValueError(f"unknown spatial op: {self.op}")


# ---------------------------------------------------------------------------
# temporal predicates (epoch-millis semantics; CQL DURING/BEFORE/AFTER/TEQUALS)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class During(Filter):
    """``prop DURING t1/t2`` — exclusive endpoints, per CQL temporal semantics
    (the reference converts DURING to exclusive bounds —
    ``Z3IndexKeySpace.scala:110-112``)."""

    prop: str
    lo_millis: int
    hi_millis: int

    def mask(self, table):
        col = table.columns[self.prop]
        v = col.values
        return (v > self.lo_millis) & (v < self.hi_millis) & col.is_valid()


@dataclass(frozen=True)
class TempOp(Filter):
    """BEFORE (<), AFTER (>), TEQUALS (==)."""

    op: str
    prop: str
    millis: int

    def mask(self, table):
        v = table.columns[self.prop].values
        valid = table.columns[self.prop].is_valid()
        if self.op == "before":
            return (v < self.millis) & valid
        if self.op == "after":
            return (v > self.millis) & valid
        if self.op == "tequals":
            return (v == self.millis) & valid
        raise ValueError(f"unknown temporal op: {self.op}")


# ---------------------------------------------------------------------------
# attribute predicates
# ---------------------------------------------------------------------------

# rows below this skip dictionary building (vocab sort isn't worth it)
_DICT_THRESHOLD = 1024

_CMP = {
    "=": lambda v, x: v == x,
    "<>": lambda v, x: v != x,
    "<": lambda v, x: v < x,
    "<=": lambda v, x: v <= x,
    ">": lambda v, x: v > x,
    ">=": lambda v, x: v >= x,
}


@dataclass(frozen=True)
class Compare(Filter):
    op: str  # =, <>, <, <=, >, >=
    prop: str
    literal: Any

    def mask(self, table):
        col = table.columns[self.prop]
        v = col.values
        lit = self.literal
        if col.type == AttributeType.DATE and not isinstance(lit, (int, np.integer)):
            from geomesa_tpu.schema.columnar import _to_millis

            lit = _to_millis(lit)
        # dictionary pushdown (ArrowFilterOptimizer role): string equality
        # resolves the literal against the vocab ONCE, then compares int32
        # codes per row instead of python strings
        if (
            self.op in ("=", "<>")
            and isinstance(lit, str)
            and len(v) >= _DICT_THRESHOLD
            and col.dictionary() is not None
        ):
            vocab, codes = col.dictionary()
            i = int(np.searchsorted(vocab, lit))
            hit = i < len(vocab) and vocab[i] == lit
            eq = (codes == i) if hit else np.zeros(len(v), dtype=bool)
            valid = col.is_valid()
            return (eq & valid) if self.op == "=" else (~eq & valid)
        if v.dtype == object:
            f = _CMP[self.op]
            out = np.zeros(len(v), dtype=bool)
            valid = col.is_valid()
            for i in range(len(v)):
                if valid[i]:
                    try:
                        out[i] = bool(f(v[i], lit))
                    except TypeError:
                        out[i] = False
            return out
        return _CMP[self.op](v, lit) & col.is_valid()


@dataclass(frozen=True)
class Between(Filter):
    """``prop BETWEEN lo AND hi`` — inclusive both ends (CQL)."""

    prop: str
    lo: Any
    hi: Any

    def mask(self, table):
        col = table.columns[self.prop]
        lo, hi = self.lo, self.hi
        if col.type == AttributeType.DATE:
            from geomesa_tpu.schema.columnar import _to_millis

            lo = lo if isinstance(lo, (int, np.integer)) else _to_millis(lo)
            hi = hi if isinstance(hi, (int, np.integer)) else _to_millis(hi)
        v = col.values
        if v.dtype == object:
            return Compare(">=", self.prop, lo).mask(table) & Compare(
                "<=", self.prop, hi
            ).mask(table)
        return (v >= lo) & (v <= hi) & col.is_valid()


@dataclass(frozen=True)
class In(Filter):
    prop: str
    literals: tuple

    def __post_init__(self):
        object.__setattr__(self, "literals", tuple(self.literals))

    def mask(self, table):
        col = table.columns[self.prop]
        # dictionary pushdown: resolve every literal against the vocab once,
        # one np.isin over int codes instead of L equality passes
        if (
            len(col) >= _DICT_THRESHOLD
            and all(isinstance(x, str) for x in self.literals)
            and col.dictionary() is not None
        ):
            vocab, codes = col.dictionary()
            # scalar vocab lookups: python == compares the FULL strings (a
            # numpy cast would truncate literals to the vocab's fixed width)
            want = []
            for lit in self.literals:
                i = int(np.searchsorted(vocab, lit))
                if i < len(vocab) and vocab[i] == lit:
                    want.append(i)
            if not want:
                return np.zeros(len(col), dtype=bool)
            return np.isin(codes, np.array(want)) & col.is_valid()
        out = np.zeros(len(col), dtype=bool)
        for lit in self.literals:
            out |= Compare("=", self.prop, lit).mask(table)
        return out


@dataclass(frozen=True)
class Like(Filter):
    """``prop LIKE pattern`` with ``%``/``_`` wildcards (``nocase`` = ILIKE)."""

    prop: str
    pattern: str
    nocase: bool = False

    def _regex(self):
        import re

        esc = re.escape(self.pattern).replace("%", ".*").replace("_", ".")
        return re.compile("^" + esc + "$", re.IGNORECASE if self.nocase else 0)

    def mask(self, table):
        col = table.columns[self.prop]
        rx = self._regex()
        # dictionary pushdown: run the regex over the (small) vocab once,
        # then one np.isin over int codes
        if len(col) >= _DICT_THRESHOLD and col.dictionary() is not None:
            vocab, codes = col.dictionary()
            want = np.nonzero(
                np.array([rx.match(u) is not None for u in vocab], dtype=bool)
            )[0]
            return np.isin(codes, want) & col.is_valid()
        valid = col.is_valid()
        out = np.zeros(len(col), dtype=bool)
        for i, v in enumerate(col.values):
            if valid[i] and isinstance(v, str):
                out[i] = rx.match(v) is not None
        return out


@dataclass(frozen=True)
class IsNull(Filter):
    prop: str

    def mask(self, table):
        return ~table.columns[self.prop].is_valid()


_PROP_FUNCS = {
    # name (lowercase) -> value transform over a 1-d column array
    "strtouppercase": lambda v: np.array([s.upper() if isinstance(s, str) else s for s in v], dtype=object),
    "strtolowercase": lambda v: np.array([s.lower() if isinstance(s, str) else s for s in v], dtype=object),
    "strtrim": lambda v: np.array([s.strip() if isinstance(s, str) else s for s in v], dtype=object),
    "strlength": lambda v: np.array([len(s) if isinstance(s, str) else -1 for s in v], dtype=np.int64),
    "abs": lambda v: np.abs(np.asarray(v, dtype=np.float64)),
    "floor": lambda v: np.floor(np.asarray(v, dtype=np.float64)),
    "ceil": lambda v: np.ceil(np.asarray(v, dtype=np.float64)),
    "datetolong": lambda v: np.asarray(v, dtype=np.int64),
}


@dataclass(frozen=True)
class FuncCompare(Filter):
    """``func(attr) <op> literal`` — property-function predicates (the
    ``FastFilterFactory`` function-expression role, SURVEY.md §2.2).

    Functions: strToUpperCase, strToLowerCase, strTrim, strLength, abs,
    floor, ceil, dateToLong. Null attribute values never match."""

    func: str  # lowercase key into _PROP_FUNCS
    op: str  # =, <>, <, <=, >, >=
    prop: str
    literal: Any

    def mask(self, table):
        col = table.columns[self.prop]
        v = _PROP_FUNCS[self.func](col.values)
        lit = self.literal
        cmp = _CMP[self.op]
        if v.dtype == object:
            out = np.zeros(len(v), dtype=bool)
            for i, val in enumerate(v):
                if val is None:
                    continue
                try:
                    out[i] = bool(cmp(val, lit))
                except TypeError:
                    pass
            return out & col.is_valid()
        return cmp(v, lit) & col.is_valid()


@dataclass(frozen=True)
class JsonPathCompare(Filter):
    """``jsonPath('<path>', attr) <op> <literal>`` — compare a value inside a
    JSON-text attribute (the ``KryoJsonSerialization`` role, SURVEY.md §2.4:
    JSON-path-indexable attributes). Path subset: ``$.a.b[0].c``. A row with
    unparseable JSON or a missing path never matches (op ``<>`` included —
    absent is not 'different', it's absent, matching the reference's
    JSONPath-miss semantics)."""

    op: str  # =, <>, <, <=, >, >=
    path: str
    prop: str
    literal: Any

    _TOKEN = re.compile(r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]")

    def _steps(self):
        if not self.path.startswith("$"):
            raise ValueError(f"json path must start with $: {self.path!r}")
        pos = 1
        steps = []
        while pos < len(self.path):
            m = self._TOKEN.match(self.path, pos)
            if not m:
                raise ValueError(f"bad json path at {pos}: {self.path!r}")
            steps.append(m.group(1) if m.group(1) is not None else int(m.group(2)))
            pos = m.end()
        return steps

    def mask(self, table):
        import json as _json

        steps = self._steps()
        col = table.columns[self.prop]
        valid = col.is_valid()
        values = col.values
        cmp = _CMP[self.op]
        out = np.zeros(len(values), dtype=bool)
        lit = self.literal
        for i in range(len(values)):
            if not valid[i]:
                continue
            try:
                v = _json.loads(values[i])
                for s in steps:
                    v = v[s]
            except (ValueError, KeyError, IndexError, TypeError):
                continue  # missing path / bad JSON: no match
            try:
                if isinstance(lit, str) != isinstance(v, str):
                    continue  # cross-type compares never match
                if isinstance(lit, bool) != isinstance(v, bool):
                    continue  # bool is an int subclass: true must not equal 1
                if cmp(v, lit):
                    out[i] = True
            except TypeError:
                continue
        return out


@dataclass(frozen=True)
class FidIn(Filter):
    """``IN ('fid1', 'fid2')`` on feature ids (the ID index path)."""

    fids: tuple

    def __post_init__(self):
        object.__setattr__(self, "fids", tuple(self.fids))

    def mask(self, table):
        want = set(self.fids)
        return np.fromiter(
            (f in want for f in table.fids), dtype=bool, count=len(table)
        )


def _cql_literal(v) -> str:
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    return str(v)


def _cql_millis(ms: int) -> str:
    import datetime

    return (
        datetime.datetime.fromtimestamp(ms / 1000, datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
    )


def to_cql(f: Filter) -> str:
    """Render an AST back to CQL text (parse(to_cql(f)) round-trips).

    The wire format for shipping filters to REMOTE stores (the federation
    path, ``MergedDataStoreView`` over DCN) and the explain/audit rendering.
    """
    from geomesa_tpu.geometry.wkt import to_wkt

    if isinstance(f, Include):
        return "INCLUDE"
    if isinstance(f, Exclude):
        return "EXCLUDE"
    if isinstance(f, And):
        return " AND ".join(f"({to_cql(c)})" for c in f.children)
    if isinstance(f, Or):
        return " OR ".join(f"({to_cql(c)})" for c in f.children)
    if isinstance(f, Not):
        return f"NOT ({to_cql(f.child)})"
    if isinstance(f, BBox):
        return f"BBOX({f.prop}, {f.xmin}, {f.ymin}, {f.xmax}, {f.ymax})"
    if isinstance(f, SpatialOp):
        wkt = to_wkt(f.geometry)
        if f.op in ("dwithin", "beyond"):
            # distance is stored in degrees; render in km so the remote
            # parser's unit conversion round-trips exactly
            km = f.distance * 111.320
            return f"{f.op.upper()}({f.prop}, {wkt}, {km}, kilometers)"
        if f.op == "relate":
            return f"RELATE({f.prop}, {wkt}, {_cql_literal(f.pattern)})"
        return f"{f.op.upper()}({f.prop}, {wkt})"
    if isinstance(f, During):
        return f"{f.prop} DURING {_cql_millis(f.lo_millis)}/{_cql_millis(f.hi_millis)}"
    if isinstance(f, TempOp):
        return f"{f.prop} {f.op.upper()} {_cql_millis(f.millis)}"
    if isinstance(f, JsonPathCompare):
        return (
            f"jsonPath({_cql_literal(f.path)}, {f.prop}) "
            f"{f.op} {_cql_literal(f.literal)}"
        )
    if isinstance(f, FuncCompare):
        return f"{f.func}({f.prop}) {f.op} {_cql_literal(f.literal)}"
    if isinstance(f, Compare):
        return f"{f.prop} {f.op} {_cql_literal(f.literal)}"
    if isinstance(f, Between):
        return f"{f.prop} BETWEEN {_cql_literal(f.lo)} AND {_cql_literal(f.hi)}"
    if isinstance(f, In):
        vals = ", ".join(_cql_literal(v) for v in f.literals)
        return f"{f.prop} IN ({vals})"
    if isinstance(f, Like):
        kw = "ILIKE" if f.nocase else "LIKE"
        return f"{f.prop} {kw} {_cql_literal(f.pattern)}"
    if isinstance(f, IsNull):
        return f"{f.prop} IS NULL"
    if isinstance(f, FidIn):
        vals = ", ".join(_cql_literal(v) for v in f.fids)
        return f"IN ({vals})"
    raise ValueError(f"cannot render {type(f).__name__} to CQL")


# ---------------------------------------------------------------------------
# residual evaluation on candidate rows (the refine hot path)
# ---------------------------------------------------------------------------

# leaf nodes whose mask() reads exactly table.columns[node.prop]
_PROP_LEAVES = (
    BBox, SpatialOp, During, TempOp, Compare, Between, In, Like, IsNull,
    FuncCompare, JsonPathCompare,
)


def column_refs(f: Filter) -> tuple[set, bool, bool]:
    """``(props, uses_fids, opaque)`` — the attribute columns ``f.mask()``
    reads, whether it reads ``table.fids``, and True when the tree holds a
    node this walker doesn't know (the caller must materialize the full
    table). The contract every :data:`_PROP_LEAVES` node upholds: its mask
    touches ``table.columns[self.prop]`` and nothing else."""
    props: set = set()
    fids = False
    opaque = False

    def walk(n):
        nonlocal fids, opaque
        if isinstance(n, (Include, Exclude)):
            return
        if isinstance(n, (And, Or)):
            for c in n.children:
                walk(c)
        elif isinstance(n, Not):
            walk(n.child)
        elif isinstance(n, FidIn):
            fids = True
        elif isinstance(n, _PROP_LEAVES):
            props.add(n.prop)
        else:
            opaque = True

    walk(f)
    return props, fids, opaque


def _residual_take(col, idx):
    """Column slice for residual evaluation: point-geometry columns take
    coordinates/bounds WITHOUT gathering the lazy object array (a 14k-row
    object fancy-index costs more than the whole mask; ``geometries()``
    rebuilds Points from x/y if some later consumer asks)."""
    if isinstance(col, GeometryColumn) and col.x is not None:
        return GeometryColumn(
            col.type,
            None,
            None if col.valid is None else col.valid[idx],
            x=col.x[idx],
            y=col.y[idx],
            bounds=None if col.bounds is None else col.bounds[idx],
        )
    return col.take(idx)


def residual_mask(f: Filter, table: FeatureTable, rows: np.ndarray) -> np.ndarray:
    """``f.mask(table.take(rows))`` without materializing columns the
    filter never reads — byte-identical result (pinned in
    ``tests/test_costmodel.py``), a fraction of the cost on wide tables:
    the full ``take`` gathers every column (object fids included) only for
    the mask to read two of them. Unknown filter nodes fall back to the
    full take, so third-party Filter subclasses stay correct."""
    rows = np.asarray(rows)
    if isinstance(f, Include):
        return np.ones(len(rows), dtype=bool)
    if isinstance(f, Exclude):
        return np.zeros(len(rows), dtype=bool)
    props, fids, opaque = column_refs(f)
    if opaque:
        return np.asarray(f.mask(table.take(rows)), dtype=bool)
    cols = {p: _residual_take(table.columns[p], rows) for p in props
            if p in table.columns}
    if len(cols) < len(props):
        # unknown column: surface the same KeyError the full path raises
        return np.asarray(f.mask(table.take(rows)), dtype=bool)
    sub_fids = (
        table.fids[rows] if fids else np.empty(len(rows), dtype=object)
    )
    sub = FeatureTable(table.sft, sub_fids, cols)
    return np.asarray(f.mask(sub), dtype=bool)
