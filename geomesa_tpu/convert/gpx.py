"""GPX (GPS exchange XML) trajectory converter + predefined OSM-GPX schema.

Role parity: the reference ships predefined SFTs/converters for public
datasets incl. OSM GPX traces (``geomesa-tools/conf/sfts/`` — SURVEY.md
§2.16), with XML parsed via its xpath converter module. The OSM-GPX planet
dump is the BASELINE config-5 trajectory workload: here each ``<trk>``
becomes a LineString feature (timestamped by its first fix) and, optionally,
each ``<trkpt>`` a point feature — the two shapes the XZ2 and Z3 indexes
want.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

import numpy as np

from geomesa_tpu.geometry.types import LineString, Point
from geomesa_tpu.schema.columnar import FeatureTable, _to_millis
from geomesa_tpu.schema.sft import parse_spec

GPX_TRACK_SPEC = (
    "trackId:String:index=true,name:String,nPoints:Integer,dtg:Date,"
    "*geom:LineString;geomesa.xz.precision='12'"
)
GPX_POINT_SPEC = (
    "trackId:String:index=true,dtg:Date,*geom:Point;geomesa.z3.interval='week'"
)


def gpx_track_sft(name: str = "gpx_tracks"):
    return parse_spec(name, GPX_TRACK_SPEC)


def gpx_point_sft(name: str = "gpx_points"):
    return parse_spec(name, GPX_POINT_SPEC)


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def parse_gpx(source, as_points: bool = False) -> FeatureTable:
    """Parse GPX text/path → FeatureTable of tracks (or track points).

    Namespace-agnostic (GPX 1.0/1.1). Tracks without a timestamp get dtg
    null; tracks with < 2 fixes are skipped in LineString mode.
    """
    if isinstance(source, str) and source.lstrip().startswith("<"):
        root = ET.fromstring(source)
    else:
        root = ET.parse(source).getroot()

    tracks = []
    for ti, trk in enumerate(el for el in root.iter() if _local(el.tag) == "trk"):
        name = None
        pts = []
        times = []
        for el in trk.iter():
            tag = _local(el.tag)
            if tag == "name" and name is None:
                name = (el.text or "").strip() or None
            elif tag == "trkpt":
                lat = float(el.get("lat"))
                lon = float(el.get("lon"))
                t = None
                for sub in el:
                    if _local(sub.tag) == "time" and sub.text:
                        t = _to_millis(sub.text.strip())
                pts.append((lon, lat))
                times.append(t)
        if pts:
            tracks.append((f"trk-{ti}", name, pts, times))

    if as_points:
        sft = gpx_point_sft()
        recs = []
        for tid, _name, pts, times in tracks:
            for (lon, lat), t in zip(pts, times):
                recs.append({"trackId": tid, "dtg": t, "geom": Point(lon, lat)})
        return FeatureTable.from_records(sft, recs)

    sft = gpx_track_sft()
    recs = []
    fids = []
    for tid, name, pts, times in tracks:
        if len(pts) < 2:
            continue
        t0 = next((t for t in times if t is not None), None)
        recs.append(
            {
                "trackId": tid,
                "name": name,
                "nPoints": len(pts),
                "dtg": t0,
                "geom": LineString(np.asarray(pts, dtype=np.float64)),
            }
        )
        fids.append(tid)
    return FeatureTable.from_records(sft, recs, fids)
