"""Shapefile (.shp/.shx/.dbf) reader + point writer.

Role parity: ``geomesa-convert/geomesa-convert-shp`` and the tools' shp
export (SURVEY.md §2.16/§2.17). Implemented from the public ESRI shapefile
and dBase III specs: the .shp geometry record stream (Point, PolyLine,
Polygon), the .dbf fixed-width attribute table, and for export the
.shp/.shx/.dbf triple for point layers.
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

from geomesa_tpu.geometry.types import LineString, Point, Polygon
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import AttributeType, FeatureType, parse_spec

__all__ = ["read_shapefile", "write_shapefile", "shapefile_sft"]

SHP_POINT = 1
SHP_POLYLINE = 3
SHP_POLYGON = 5

_DBF_TO_ATTR = {"C": AttributeType.STRING, "N": AttributeType.DOUBLE,
                "F": AttributeType.DOUBLE, "L": AttributeType.BOOLEAN,
                "D": AttributeType.DATE}


def _read_dbf(path: Path):
    """dBase III: → (field names, attr types, record dicts)."""
    data = path.read_bytes()
    n_records = struct.unpack("<I", data[4:8])[0]
    header_size, record_size = struct.unpack("<HH", data[8:12])
    fields = []
    off = 32
    while data[off] != 0x0D:  # field descriptor terminator
        raw = data[off : off + 32]
        name = raw[:11].split(b"\x00")[0].decode("ascii", "replace")
        ftype = chr(raw[11])
        length = raw[16]
        decimals = raw[17]
        fields.append((name, ftype, length, decimals))
        off += 32
    records = []
    pos = header_size
    for _ in range(n_records):
        rec_raw = data[pos : pos + record_size]
        pos += record_size
        if not rec_raw or rec_raw[0:1] == b"*":  # deleted
            continue
        rec = {}
        fo = 1
        for name, ftype, length, decimals in fields:
            cell = rec_raw[fo : fo + length].decode("ascii", "replace").strip()
            fo += length
            if cell == "":
                rec[name] = None
            elif ftype in ("N", "F"):
                rec[name] = int(cell) if ftype == "N" and decimals == 0 and "." not in cell else float(cell)
            elif ftype == "L":
                rec[name] = cell in ("T", "t", "Y", "y")
            elif ftype == "D":  # YYYYMMDD
                import datetime

                try:
                    d = datetime.datetime.strptime(cell, "%Y%m%d")
                    rec[name] = int(d.replace(tzinfo=datetime.timezone.utc).timestamp() * 1000)
                except ValueError:
                    rec[name] = None
            else:
                rec[name] = cell
        records.append(rec)
    return fields, records


def _read_shp(path: Path):
    """→ list of geometries (None for null shapes)."""
    data = path.read_bytes()
    (code,) = struct.unpack(">i", data[0:4])
    if code != 9994:
        raise ValueError("not a shapefile (bad magic)")
    geoms = []
    pos = 100
    while pos < len(data):
        _, length_words = struct.unpack(">ii", data[pos : pos + 8])
        pos += 8
        body = data[pos : pos + length_words * 2]
        pos += length_words * 2
        (stype,) = struct.unpack("<i", body[0:4])
        if stype == 0:
            geoms.append(None)
        elif stype == SHP_POINT:
            x, y = struct.unpack("<dd", body[4:20])
            geoms.append(Point(x, y))
        elif stype in (SHP_POLYLINE, SHP_POLYGON):
            n_parts, n_points = struct.unpack("<ii", body[36:44])
            parts = struct.unpack(f"<{n_parts}i", body[44 : 44 + 4 * n_parts])
            coords = np.frombuffer(
                body[44 + 4 * n_parts : 44 + 4 * n_parts + 16 * n_points],
                dtype="<f8",
            ).reshape(n_points, 2)
            bounds = list(parts) + [n_points]
            rings = [
                np.array(coords[bounds[i] : bounds[i + 1]])
                for i in range(n_parts)
            ]
            if stype == SHP_POLYLINE:
                geoms.append(LineString(np.vstack(rings)))
            else:
                geoms.append(Polygon(rings[0], holes=rings[1:]))
        else:
            raise ValueError(f"unsupported shape type: {stype}")
    return geoms


def shapefile_sft(name: str, shp_path: str) -> FeatureType:
    """Infer a feature type from the .dbf fields + shape type."""
    base = Path(shp_path).with_suffix("")
    fields, _ = _read_dbf(base.with_suffix(".dbf"))
    geoms = _read_shp(base.with_suffix(".shp"))
    gtype = "Geometry"
    for g in geoms:
        if g is not None:
            gtype = {"Point": "Point", "LineString": "LineString",
                     "Polygon": "Polygon"}[g.geom_type]
            break
    attr_spec = ",".join(
        f"{n}:{_DBF_TO_ATTR[t].value if t in _DBF_TO_ATTR else 'String'}"
        for n, t, _, _ in fields
    )
    spec = (attr_spec + "," if attr_spec else "") + f"*geom:{gtype}"
    return parse_spec(name, spec)


def read_shapefile(shp_path: str, sft: FeatureType | None = None) -> FeatureTable:
    """Read .shp + .dbf into a FeatureTable (geometry column = ``geom``)."""
    base = Path(shp_path).with_suffix("")
    sft = sft or shapefile_sft(base.name, shp_path)
    _, records = _read_dbf(base.with_suffix(".dbf"))
    geoms = _read_shp(base.with_suffix(".shp"))
    if len(records) != len(geoms):
        raise ValueError(
            f".dbf rows ({len(records)}) != .shp shapes ({len(geoms)})"
        )
    for rec, g in zip(records, geoms):
        rec[sft.geom_field or "geom"] = g
    fids = [f"{sft.name}.{i}" for i in range(len(records))]
    return FeatureTable.from_records(sft, records, fids)


def write_shapefile(table: FeatureTable, shp_path: str) -> None:
    """Write a POINT FeatureTable as .shp/.shx/.dbf (the shp export role)."""
    base = Path(shp_path).with_suffix("")
    col = table.geom_column()
    if col.x is None:
        raise ValueError("shapefile export supports point layers only")
    n = len(table)
    x, y = col.x, col.y

    # .shp + .shx
    rec_body = struct.pack("<i", SHP_POINT)
    rec_len_words = (len(rec_body) + 16) // 2
    shp_len_words = 50 + n * (4 + rec_len_words)
    bbox = (
        (float(x.min()), float(y.min()), float(x.max()), float(y.max()))
        if n
        else (0.0, 0.0, 0.0, 0.0)
    )

    def header(total_words):
        return (
            struct.pack(">i20x i", 9994, total_words)
            + struct.pack("<ii", 1000, SHP_POINT)
            + struct.pack("<4d", *bbox)
            + struct.pack("<4d", 0, 0, 0, 0)
        )

    with open(base.with_suffix(".shp"), "wb") as f, open(
        base.with_suffix(".shx"), "wb"
    ) as fx:
        f.write(header(shp_len_words))
        fx.write(header(50 + n * 4))
        offset = 50
        for i in range(n):
            f.write(struct.pack(">ii", i + 1, rec_len_words))
            f.write(struct.pack("<idd", SHP_POINT, float(x[i]), float(y[i])))
            fx.write(struct.pack(">ii", offset, rec_len_words))
            offset += 4 + rec_len_words

    # .dbf
    attrs = [a for a in table.sft.attributes if not a.type.is_geometry]

    def dbf_field(a):
        if a.type in (AttributeType.INT, AttributeType.LONG):
            return (a.name[:10], "N", 18, 0)
        if a.type in (AttributeType.FLOAT, AttributeType.DOUBLE):
            return (a.name[:10], "N", 24, 8)
        if a.type == AttributeType.BOOLEAN:
            return (a.name[:10], "L", 1, 0)
        if a.type == AttributeType.DATE:
            return (a.name[:10], "D", 8, 0)
        return (a.name[:10], "C", 64, 0)

    fields = [dbf_field(a) for a in attrs]
    record_size = 1 + sum(f[2] for f in fields)
    header_size = 32 + 32 * len(fields) + 1
    with open(base.with_suffix(".dbf"), "wb") as f:
        f.write(struct.pack("<B3B I HH 20x", 0x03, 24, 1, 1, n,
                            header_size, record_size))
        for name, ftype, length, decimals in fields:
            f.write(
                name.encode("ascii").ljust(11, b"\x00")
                + ftype.encode("ascii")
                + b"\x00" * 4
                + bytes([length, decimals])
                + b"\x00" * 14
            )
        f.write(b"\x0d")
        for i in range(n):
            f.write(b" ")
            rec = table.record(i)
            for (name, ftype, length, decimals), a in zip(fields, attrs):
                v = rec.get(a.name)
                if v is None:
                    cell = ""
                elif ftype == "N" and decimals:
                    cell = f"{float(v):.{decimals}f}"
                elif ftype == "N":
                    cell = str(int(v))
                elif ftype == "L":
                    cell = "T" if v else "F"
                elif ftype == "D":
                    import datetime

                    cell = datetime.datetime.fromtimestamp(
                        v / 1000, datetime.timezone.utc
                    ).strftime("%Y%m%d")
                else:
                    cell = str(v)
                f.write(cell[:length].rjust(length).encode("ascii", "replace"))
        f.write(b"\x1a")
