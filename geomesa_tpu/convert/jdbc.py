"""JDBC-role ingest converter: SQL query results → FeatureTable.

Role parity: ``geomesa-convert/geomesa-convert-jdbc`` (SURVEY.md §2.16) —
ingest rows from a relational database by running a SQL statement and
mapping result columns through the shared transform-expression language.
The JVM reference speaks JDBC; the Python analog is any DB-API 2.0
connection (stdlib ``sqlite3`` in tests; postgres/mysql drivers plug in the
same way). Rows fetch into columnar numpy arrays once, then field
expressions evaluate columnarly exactly like the delimited converter
(``$1``-style 1-based column refs or result-column names).
"""

from __future__ import annotations

import pandas as pd

from geomesa_tpu.convert.delimited import DelimitedConverter, EvaluationContext
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType

__all__ = ["JdbcConverter"]


class JdbcConverter:
    """SQL statement over a DB-API connection → FeatureTable for one schema.

    ``fields``: {attribute: transform expression} in the delimited
    converter's mini-language (``point($2, $3)``, ``isodate($4)``, column
    names when the statement provides them). ``id_field``: expression for
    feature ids (default: row number).
    """

    def __init__(
        self,
        sft: FeatureType,
        query: str,
        fields: dict[str, str] | None = None,
        id_field: str | None = None,
        error_mode: str = "skip",
        fetch_rows: int = 50_000,
    ):
        self.sft = sft
        self.query = query
        self.fetch_rows = fetch_rows
        # reuse the delimited converter's expression evaluator wholesale:
        # a result set is just a header-ed frame of stringly columns
        self._delegate = DelimitedConverter(
            sft, fields or {}, id_field=id_field, header=True,
            error_mode=error_mode,
        )
        self.id_field = id_field

    def convert_connection(
        self, conn, params=(), ctx: EvaluationContext | None = None
    ) -> FeatureTable:
        """Run the statement on ``conn`` (DB-API 2.0) and convert all rows."""
        cur = conn.cursor()
        try:
            cur.execute(self.query, params)
            names = [d[0] for d in cur.description]
            frames = []
            while True:
                rows = cur.fetchmany(self.fetch_rows)
                if not rows:
                    break
                frames.append(pd.DataFrame(rows, columns=names))
        finally:
            cur.close()
        if frames:
            df = pd.concat(frames, ignore_index=True)
            # expressions see strings (the delimited contract); None → ''
            df = df.astype(object).where(~df.isna(), "").astype(str)
            df = df.replace("None", "")
        else:
            df = pd.DataFrame(columns=names, dtype=str)
        return self._delegate.convert_frame(df, ctx)

    def convert_sqlite(
        self, path: str, params=(), ctx: EvaluationContext | None = None
    ) -> FeatureTable:
        """Convenience: open a sqlite file, convert, close."""
        import sqlite3

        conn = sqlite3.connect(path)
        try:
            return self.convert_connection(conn, params, ctx)
        finally:
            conn.close()


def ingest_jdbc(
    ds,
    type_name: str,
    conn,
    query: str,
    fields: dict[str, str] | None = None,
    id_field: str | None = None,
) -> int:
    """One-call ingest: run ``query`` on ``conn`` into ``ds``/``type_name``."""
    sft = ds.get_schema(type_name)
    conv = JdbcConverter(sft, query, fields, id_field=id_field)
    table = conv.convert_connection(conn)
    n = int(len(table))
    ds.write(type_name, table)
    return n
