"""XML ingest converter: xpath-subset field extraction → FeatureTable.

Role parity: ``geomesa-convert/geomesa-convert-xml`` (SURVEY.md §2.16):
declarative mappings from XML documents into typed SFT attributes, sharing
the delimited/JSON converters' typed column builders and error modes.

Path grammar (ElementTree xpath subset, relative to each feature element):

    a/b            nested child elements (text content)
    @id            attribute of the feature element
    a/@units       attribute of a nested element
    .              the feature element's own text

Field expressions: a bare path, ``point(<path>, <path>)`` for lon/lat,
``wkt(<path>)`` for WKT geometry text, ``concat(<path>, 'lit', ...)``.
``feature_path`` is an ElementTree ``iterfind`` pattern for the repeating
feature element (e.g. ``.//row`` or ``items/item``).
"""

from __future__ import annotations

import re
import xml.etree.ElementTree as ET

import numpy as np

from geomesa_tpu.convert.delimited import (
    EvaluationContext,
    _boolean_column,
    _date_column,
    _numeric_column,
    _split_args,
)
from geomesa_tpu.schema.columnar import (
    Column,
    FeatureTable,
    _geometry_column,
    point_column,
)
from geomesa_tpu.schema.sft import AttributeType, FeatureType

_NUMERIC = {
    AttributeType.INT,
    AttributeType.LONG,
    AttributeType.FLOAT,
    AttributeType.DOUBLE,
}

__all__ = ["XmlConverter"]


def _extract(elem: ET.Element, path: str) -> str:
    """One path against one element → text ('' when absent)."""
    path = path.strip()
    if path == ".":
        return (elem.text or "").strip()
    if path.startswith("@"):
        return str(elem.get(path[1:], ""))
    if "/@" in path:
        sub, attr = path.rsplit("/@", 1)
        child = elem.find(sub)
        return "" if child is None else str(child.get(attr, ""))
    child = elem.find(path)
    return "" if child is None or child.text is None else child.text.strip()


class XmlConverter:
    """XML documents → FeatureTable for one schema.

    ``fields``: {attribute: expression}; ``id_field``: expression for ids.
    """

    def __init__(
        self,
        sft: FeatureType,
        fields: dict[str, str],
        feature_path: str = ".//feature",
        id_field: str | None = None,
        error_mode: str = "skip",
    ):
        self.sft = sft
        self.fields = fields
        self.feature_path = feature_path
        self.id_field = id_field
        if error_mode not in ("skip", "raise"):
            raise ValueError(f"error_mode must be skip|raise: {error_mode}")
        self.error_mode = error_mode

    def convert_path(self, path, ctx: EvaluationContext | None = None) -> FeatureTable:
        with open(path, encoding="utf-8") as f:
            return self.convert_str(f.read(), ctx)

    def convert_str(self, text: str, ctx: EvaluationContext | None = None) -> FeatureTable:
        root = ET.fromstring(text)
        elems = (
            [root]
            if self.feature_path in (".", "")
            else list(root.iterfind(self.feature_path))
        )
        ctx = ctx if ctx is not None else EvaluationContext()
        n = len(elems)
        cols: dict[str, Column] = {}
        bad = np.zeros(n, dtype=bool)
        for a in self.sft.attributes:
            expr = self.fields.get(a.name, a.name)
            try:
                col, col_bad = self._eval(expr, elems, a.type)
            except Exception as e:
                raise ValueError(
                    f"transform {expr!r} for {a.name!r} failed: {e}"
                ) from e
            cols[a.name] = col
            bad |= col_bad
        if bad.any():
            if self.error_mode == "raise":
                idx = int(np.nonzero(bad)[0][0])
                raise ValueError(f"bad record at index {idx}")
            ctx.failure += int(bad.sum())
            good = ~bad
            cols = {k: c.take(good) for k, c in cols.items()}
        else:
            good = slice(None)
        ctx.success += int((~bad).sum())
        if self.id_field:
            fid_col, _ = self._eval(self.id_field, elems, AttributeType.STRING)
            fids = fid_col.values[good]
        else:
            fids = np.arange(n)[good].astype(str).astype(object)
        return FeatureTable(self.sft, np.asarray(fids, dtype=object), cols)

    # -- expression evaluation ------------------------------------------------
    def _raw(self, expr: str, elems) -> np.ndarray:
        expr = expr.strip()
        out = np.empty(len(elems), dtype=object)
        if expr.startswith(("'", '"')):
            out[:] = expr[1:-1]
            return out
        if expr.startswith("concat"):
            m = re.match(r"^concat\s*\((.*)\)$", expr, re.S)
            parts = [self._raw(a, elems) for a in _split_args(m.group(1))]
            acc = parts[0].astype(str)
            for p in parts[1:]:
                acc = np.char.add(acc, p.astype(str))
            return acc.astype(object)
        for i, e in enumerate(elems):
            out[i] = _extract(e, expr)
        return out

    def _eval(self, expr: str, elems, typ: AttributeType):
        expr = expr.strip()
        n = len(elems)
        m = re.match(r"^(\w+)\s*\((.*)\)$", expr, re.S)
        fn = (
            m.group(1).lower()
            if m and m.group(1).lower() in ("point", "wkt")
            else None
        )

        if fn == "point":
            ax, ay = _split_args(m.group(2))
            import pandas as pd

            xs = pd.to_numeric(pd.Series(self._raw(ax, elems)), errors="coerce").to_numpy(np.float64)
            ys = pd.to_numeric(pd.Series(self._raw(ay, elems)), errors="coerce").to_numpy(np.float64)
            bad = ~(np.isfinite(xs) & np.isfinite(ys))
            bad |= (np.abs(np.nan_to_num(xs)) > 180) | (np.abs(np.nan_to_num(ys)) > 90)
            return point_column(np.where(bad, 0.0, xs), np.where(bad, 0.0, ys)), bad

        if fn == "wkt":
            from geomesa_tpu.geometry.wkt import from_wkt

            (path,) = _split_args(m.group(2))
            raws = self._raw(path, elems)
            geoms, bad = [], np.zeros(n, dtype=bool)
            for i, r in enumerate(raws):
                if r == "":
                    geoms.append(None)
                    continue
                try:
                    geoms.append(from_wkt(r))
                except Exception:
                    geoms.append(None)
                    bad[i] = True
            return _geometry_column(typ, geoms), bad

        raw = self._raw(expr, elems)
        if typ in _NUMERIC:
            return _numeric_column(raw, typ)
        if typ == AttributeType.DATE:
            import pandas as pd

            parsed = pd.to_datetime(pd.Series(raw), errors="coerce", utc=True)
            return _date_column(raw, parsed)
        if typ == AttributeType.BOOLEAN:
            return _boolean_column(raw)
        if typ.is_geometry:
            from geomesa_tpu.geometry.wkt import from_wkt

            geoms = [from_wkt(r) if r else None for r in raw]
            return _geometry_column(typ, geoms), np.zeros(n, dtype=bool)
        valid = np.array([v != "" for v in raw])
        return Column(typ, raw, None if valid.all() else valid), np.zeros(n, dtype=bool)
