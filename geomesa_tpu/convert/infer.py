"""Schema type inference from sample data.

The ``TypeInference`` role (``geomesa-convert-common/.../TypeInference.scala``,
478 LoC — SURVEY.md §2.16): given sample rows, infer per-column types by
trying progressively wider parses (int → long → double → boolean → date →
string), detect a lon/lat pair for the default geometry, and emit both an SFT
spec string and the matching converter field expressions.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from geomesa_tpu.schema.sft import FeatureType, parse_spec

_LON_NAMES = {"lon", "long", "longitude", "x", "lng"}
_LAT_NAMES = {"lat", "latitude", "y"}


def _non_empty(series: pd.Series) -> pd.Series:
    s = series.astype(str).str.strip()
    return s[s != ""]


def _infer_column(series: pd.Series) -> str:
    """One column's sample values → SFT type name."""
    vals = _non_empty(series)
    if len(vals) == 0:
        return "String"
    nums = pd.to_numeric(vals, errors="coerce")
    if not nums.isna().any():
        if (nums == nums.round()).all() and not vals.str.contains(
            r"[.eE]", regex=True
        ).any():
            lo, hi = nums.min(), nums.max()
            return "Integer" if -(2**31) <= lo and hi < 2**31 else "Long"
        return "Double"
    low = vals.str.lower()
    if low.isin(("true", "false")).all():
        return "Boolean"
    parsed = pd.to_datetime(vals, errors="coerce", utc=True, format="mixed")
    if not parsed.isna().any() and vals.str.contains(r"[-:T/]", regex=True).all():
        return "Date"
    return "String"


def infer_schema(
    df_or_path,
    name: str = "inferred",
    sample: int = 1000,
    delimiter: str = ",",
) -> tuple[FeatureType, dict[str, str]]:
    """Sample data → (FeatureType, converter ``fields``).

    Accepts a path to a headered delimited file or a DataFrame. A geometry
    attribute named ``geom`` is synthesized from the first recognizable
    (lon, lat) column-name pair whose values fit the coordinate domain; the
    first Date column becomes the default time attribute.
    """
    if isinstance(df_or_path, pd.DataFrame):
        df = df_or_path.head(sample).astype(str)
    else:
        df = pd.read_csv(
            df_or_path, sep=delimiter, dtype=str, keep_default_na=False,
            na_values=[], nrows=sample,
        )

    types = {c: _infer_column(df[c]) for c in df.columns}

    lon = lat = None
    for c in df.columns:
        cl = str(c).strip().lower()
        if lon is None and cl in _LON_NAMES and types[c] in ("Integer", "Long", "Double"):
            v = pd.to_numeric(_non_empty(df[c]), errors="coerce")
            if len(v) and v.abs().max() <= 180:
                lon = c
        elif lat is None and cl in _LAT_NAMES and types[c] in ("Integer", "Long", "Double"):
            v = pd.to_numeric(_non_empty(df[c]), errors="coerce")
            if len(v) and v.abs().max() <= 90:
                lat = c

    parts = []
    fields: dict[str, str] = {}
    for c in df.columns:
        attr = str(c).strip().replace(" ", "_")
        parts.append(f"{attr}:{types[c]}")
        fields[attr] = str(c)
    if lon is not None and lat is not None:
        parts.append("*geom:Point")
        fields["geom"] = f"point({lon}, {lat})"
    spec = ",".join(parts)
    return parse_spec(name, spec), fields
