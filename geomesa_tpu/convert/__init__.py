"""geomesa_tpu subpackage."""
