"""Config-driven delimited-text ingest converters.

The ``geomesa-convert`` role (SURVEY.md §2.16): declarative field mappings
from delimited columns to typed SFT attributes, with a transform-expression
mini-language (``$n`` column refs, ``point()``, ``date()``, ``concat()``,
casts), error modes (skip-bad-records / raise), and per-file evaluation
counters — re-designed around *columnar* evaluation: each transform maps whole
numpy columns, not per-record closures.

Transform grammar (subset of the reference's transformer functions):

    $0              whole-record id / $1.. column by 1-based index
    point($4, $5)   lon, lat columns → Point geometry column
    date('%Y%m%d', $2)  strptime parse → epoch millis
    dateHourMinSec / isodate / millisToDate($3)   common date presets
    int($3) long($3) float($3) double($3) string($3) concat($1, '-', $2)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np
import pandas as pd

from geomesa_tpu.schema.columnar import Column, FeatureTable, point_column
from geomesa_tpu.schema.sft import AttributeType, FeatureType

_NUMERIC_DTYPES = {
    AttributeType.INT: np.int32,
    AttributeType.LONG: np.int64,
    AttributeType.FLOAT: np.float32,
    AttributeType.DOUBLE: np.float64,
}


# -- scripting hook (geomesa-convert scripting-module role) -------------------
# The reference lets converter configs call user scripts (JS) as transform
# functions; the analog here is a registry of named Python column functions
# callable from any field expression. A registered fn receives object arrays
# (one per argument) and returns an array of the same length — columnar, so
# a script runs once per file, not once per record.
_CUSTOM_FUNCTIONS: dict[str, object] = {}


def register_function(name: str, fn, vectorized: bool = True) -> None:
    """Expose ``fn`` to converter expressions as ``name(args...)``.

    ``vectorized=False`` wraps a scalar ``fn(*values) -> value`` so per-record
    scripts still work (at per-record cost, like the reference's JS hook).
    """
    key = name.lower()
    if key in _RESERVED_FNS:
        raise ValueError(f"{name!r} shadows a builtin transform function")
    if not vectorized:
        inner = fn

        def fn(*cols):  # noqa: ANN001 — object arrays in/out
            return np.array(
                [inner(*vals) for vals in zip(*cols)], dtype=object
            )

    _CUSTOM_FUNCTIONS[key] = fn


def unregister_function(name: str) -> None:
    _CUSTOM_FUNCTIONS.pop(name.lower(), None)


_RESERVED_FNS = {
    "point", "date", "millistodate", "isodate", "int", "integer", "long",
    "float", "double", "string", "bool", "boolean", "concat", "lower",
    "upper", "trim", "replace", "substr",
}


@dataclass
class EvaluationContext:
    """Ingest counters (the reference's ``EvaluationContext`` role)."""

    success: int = 0
    failure: int = 0
    errors: list = field(default_factory=list)


class DelimitedConverter:
    """CSV/TSV → FeatureTable for one schema.

    ``fields``: {attribute: transform expression}; unlisted attributes default
    to a same-named column if the file has headers. ``id_field``: transform for
    feature ids (default: row number).
    """

    def __init__(
        self,
        sft: FeatureType,
        fields: dict[str, str],
        id_field: str | None = None,
        delimiter: str = ",",
        header: bool = False,
        error_mode: str = "skip",  # skip | raise
    ):
        self.sft = sft
        self.fields = fields
        self.id_field = id_field
        self.delimiter = delimiter
        self.header = header
        if error_mode not in ("skip", "raise"):
            raise ValueError(f"error_mode must be skip|raise: {error_mode}")
        self.error_mode = error_mode

    def convert_path(self, path, ctx: EvaluationContext | None = None) -> FeatureTable:
        df = pd.read_csv(
            path,
            sep=self.delimiter,
            header=0 if self.header else None,
            dtype=str,
            keep_default_na=False,
            na_values=[],
            engine="c",
        )
        return self.convert_frame(df, ctx)

    def convert_str(self, text: str, ctx: EvaluationContext | None = None) -> FeatureTable:
        import io

        return self.convert_path(io.StringIO(text), ctx)

    def convert_frame(self, df, ctx: EvaluationContext | None = None) -> FeatureTable:
        ctx = ctx if ctx is not None else EvaluationContext()
        n = len(df)
        cols: dict[str, Column] = {}
        bad = np.zeros(n, dtype=bool)
        for a in self.sft.attributes:
            expr = self.fields.get(a.name, a.name if self.header else None)
            if expr is None:
                raise ValueError(f"no transform for attribute {a.name!r}")
            try:
                col, col_bad = _eval(expr, df, a.type, self)
            except Exception as e:
                raise ValueError(f"transform {expr!r} for {a.name!r} failed: {e}") from e
            cols[a.name] = col
            bad |= col_bad
        if bad.any():
            if self.error_mode == "raise":
                idx = int(np.nonzero(bad)[0][0])
                raise ValueError(f"bad record at row {idx}")
            ctx.failure += int(bad.sum())
            good = ~bad
            cols = {k: c.take(good) for k, c in cols.items()}
            n = int(good.sum())
        else:
            good = slice(None)
        ctx.success += n
        if self.id_field:
            fid_col, _ = _eval(self.id_field, df, AttributeType.STRING, self)
            fids = fid_col.values[good] if bad.any() else fid_col.values
        else:
            fids = np.arange(len(df))[good].astype(str).astype(object)
        return FeatureTable(self.sft, np.asarray(fids, dtype=object), cols)


_CALL = re.compile(r"^(\w+)\s*\((.*)\)$", re.S)
_COLREF = re.compile(r"^\$(\d+)$")


def _split_args(s: str) -> list[str]:
    out, depth, cur, q = [], 0, [], None
    for ch in s:
        if q:
            cur.append(ch)
            if ch == q:
                q = None
        elif ch in "'\"":
            q = ch
            cur.append(ch)
        elif ch == "(":
            depth += 1
            cur.append(ch)
        elif ch == ")":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return out


def _raw(expr: str, df, conv) -> np.ndarray:
    """Evaluate a sub-expression to a raw string object array."""
    expr = expr.strip()
    m = _COLREF.match(expr)
    if m:
        i = int(m.group(1))
        if i == 0:
            return np.arange(len(df)).astype(str).astype(object)
        series = df.iloc[:, i - 1]
        return series.astype(str).to_numpy(dtype=object)
    if expr.startswith(("'", '"')):
        lit = expr[1:-1]
        out = np.empty(len(df), dtype=object)
        out[:] = lit
        return out
    if conv.header and expr in getattr(df, "columns", []):
        return df[expr].astype(str).to_numpy(dtype=object)
    m = _CALL.match(expr)
    if m and m.group(1) == "concat":
        parts = [_raw(a, df, conv) for a in _split_args(m.group(2))]
        out = parts[0]
        for p in parts[1:]:
            out = np.char.add(out.astype(str), p.astype(str)).astype(object)
        return out
    if m and m.group(1).lower() in ("lower", "upper", "trim"):
        (arg,) = _split_args(m.group(2))
        raw = _raw(arg, df, conv).astype(str)
        op = {"lower": np.char.lower, "upper": np.char.upper,
              "trim": np.char.strip}[m.group(1).lower()]
        return op(raw).astype(object)
    if m and m.group(1).lower() == "replace":
        arg, old, new = _split_args(m.group(2))
        raw = _raw(arg, df, conv).astype(str)
        return np.char.replace(raw, old.strip("'\""), new.strip("'\"")).astype(object)
    if m and m.group(1).lower() == "substr":
        args = _split_args(m.group(2))
        raw = _raw(args[0], df, conv).astype(str)
        lo = int(args[1])
        hi = int(args[2]) if len(args) > 2 else None
        return np.array([s[lo:hi] for s in raw], dtype=object)
    if m and m.group(1).lower() in _CUSTOM_FUNCTIONS:
        fn = _CUSTOM_FUNCTIONS[m.group(1).lower()]
        parts = [_raw(a, df, conv) for a in _split_args(m.group(2))]
        out = np.asarray(fn(*parts), dtype=object)
        if out.shape != (len(df),):
            raise ValueError(
                f"custom function {m.group(1)!r} returned shape {out.shape}, "
                f"expected ({len(df)},)"
            )
        return out
    raise ValueError(f"cannot evaluate expression: {expr!r}")


def _eval(expr: str, df, typ: AttributeType, conv) -> tuple[Column, np.ndarray]:
    """Expression → (Column, bad-row mask)."""
    expr = expr.strip()
    n = len(df)
    m = _CALL.match(expr)
    fn = m.group(1).lower() if m else None

    if fn == "point":
        ax, ay = _split_args(m.group(2))
        xs = pd.to_numeric(pd.Series(_raw(ax, df, conv)), errors="coerce").to_numpy(np.float64)
        ys = pd.to_numeric(pd.Series(_raw(ay, df, conv)), errors="coerce").to_numpy(np.float64)
        bad = ~(np.isfinite(xs) & np.isfinite(ys))
        bad |= (np.abs(xs) > 180) | (np.abs(ys) > 90)
        xs = np.where(bad, 0.0, xs)
        ys = np.where(bad, 0.0, ys)
        return point_column(xs, ys), bad

    if fn == "date":
        fmt_arg, col_arg = _split_args(m.group(2))
        fmt = fmt_arg.strip("'\"")
        raw = _raw(col_arg, df, conv)
        parsed = pd.to_datetime(pd.Series(raw), format=fmt, errors="coerce", utc=True)
        return _date_column(raw, parsed)

    if fn == "millistodate":
        (col_arg,) = _split_args(m.group(2))
        raw = _raw(col_arg, df, conv)
        nums = pd.to_numeric(pd.Series(raw), errors="coerce")
        empty = np.array([s == "" for s in raw], dtype=bool)
        nan = nums.isna().to_numpy()
        return (
            Column(AttributeType.DATE, nums.fillna(0).to_numpy(np.int64),
                   None if (~nan).all() else ~nan),
            nan & ~empty,
        )

    if fn == "isodate":
        (col_arg,) = _split_args(m.group(2))
        raw = _raw(col_arg, df, conv)
        parsed = pd.to_datetime(pd.Series(raw), errors="coerce", utc=True, format="ISO8601")
        return _date_column(raw, parsed)

    if fn in ("int", "integer", "long", "float", "double"):
        (col_arg,) = _split_args(m.group(2))
        raw = _raw(col_arg, df, conv)
        t = {
            "int": AttributeType.INT,
            "integer": AttributeType.INT,
            "long": AttributeType.LONG,
            "float": AttributeType.FLOAT,
            "double": AttributeType.DOUBLE,
        }[fn]
        return _numeric_column(raw, t)

    if fn == "string":
        (col_arg,) = _split_args(m.group(2))
        return Column(AttributeType.STRING, _raw(col_arg, df, conv)), np.zeros(n, bool)

    if fn in ("bool", "boolean"):
        (col_arg,) = _split_args(m.group(2))
        return _boolean_column(_raw(col_arg, df, conv))

    # bare expression: raw string (or typed cast for typed targets)
    raw = _raw(expr, df, conv)
    if typ == AttributeType.BOOLEAN:
        return _boolean_column(raw)
    if typ in _NUMERIC_DTYPES:
        return _numeric_column(raw, typ)
    if typ == AttributeType.DATE:
        parsed = pd.to_datetime(pd.Series(raw), errors="coerce", utc=True)
        return _date_column(raw, parsed)
    valid = np.array([v != "" for v in raw])
    return Column(typ, raw, None if valid.all() else valid), np.zeros(n, bool)


def _numeric_column(raw: np.ndarray, typ: AttributeType) -> tuple[Column, np.ndarray]:
    """Numeric parse where empty cells become nulls and only non-empty
    unparseable cells mark the record bad (the reference converter ingests
    rows with empty optional fields as null attributes)."""
    nums = pd.to_numeric(pd.Series(raw), errors="coerce")
    empty = np.array([s == "" for s in raw], dtype=bool)
    nan = nums.isna().to_numpy()
    valid = ~nan
    col = Column(
        typ, nums.fillna(0).to_numpy(_NUMERIC_DTYPES[typ]), None if valid.all() else valid
    )
    return col, nan & ~empty


def _date_column(raw: np.ndarray, parsed) -> tuple[Column, np.ndarray]:
    """Date parse with the same empty→null / garbage→bad split."""
    nan = parsed.isna().to_numpy()
    empty = np.array([s == "" for s in raw], dtype=bool)
    vals = np.where(nan, 0, parsed.values.astype("datetime64[ms]").astype(np.int64))
    valid = ~nan
    col = Column(AttributeType.DATE, vals.astype(np.int64), None if valid.all() else valid)
    return col, nan & ~empty


_TRUE = {"true", "t", "1", "yes", "y"}
_FALSE = {"false", "f", "0", "no", "n"}


def _boolean_column(raw: np.ndarray) -> tuple[Column, np.ndarray]:
    """Boolean parse: true/false (& t/f/1/0/yes/no), empty→null, garbage→bad."""
    low = np.char.lower(np.char.strip(raw.astype(str)))
    vals = np.isin(low, sorted(_TRUE))
    is_false = np.isin(low, sorted(_FALSE))
    empty = low == ""
    valid = vals | is_false
    bad = ~valid & ~empty
    return Column(AttributeType.BOOLEAN, vals, None if valid.all() else valid), bad
