"""Predefined schemas + converters for common public datasets.

Role parity: ``geomesa-tools/conf/sfts/`` (SURVEY.md §2.16) — the reference
ships ready-made SFTs/converters for GDELT, GeoLife, OSM, NYC taxi, T-Drive,
Twitter, marine-cadastre AIS, …; users ingest with ``--converter <name>``
instead of writing field mappings. The registry here mirrors the high-traffic
ones; GDELT (:mod:`geomesa_tpu.convert.gdelt`) and OSM-GPX
(:mod:`geomesa_tpu.convert.gpx`) have dedicated modules.
"""

from __future__ import annotations

from geomesa_tpu.convert.delimited import DelimitedConverter
from geomesa_tpu.schema.sft import FeatureType, parse_spec

__all__ = ["predefined_sft", "predefined_converter", "PREDEFINED"]

# GeoLife trajectory points (plt files: lat, lon, 0, alt, days, date, time)
GEOLIFE_SPEC = "userId:String:index=true,altitude:Double,dtg:Date,*geom:Point;geomesa.z3.interval='month'"

# NYC yellow taxi trips (2015-era CSV: pickup side)
NYCTAXI_SPEC = (
    "tripId:String,passengers:Integer,distance:Double,totalAmount:Double,"
    "dtg:Date,*geom:Point;geomesa.z3.interval='week'"
)

# T-Drive Beijing taxi traces (taxi id, datetime, lon, lat)
TDRIVE_SPEC = "taxiId:String:index=true,dtg:Date,*geom:Point;geomesa.z3.interval='week'"

# Twitter sample (id, user, text, created_at, lon, lat)
TWITTER_SPEC = (
    "userId:String:index=true,text:String,dtg:Date,*geom:Point;"
    "geomesa.z3.interval='day'"
)

# Marine-cadastre AIS broadcast points
AIS_SPEC = (
    "mmsi:String:index=true,sog:Double,cog:Double,heading:Double,"
    "dtg:Date,*geom:Point;geomesa.z3.interval='day'"
)

PREDEFINED: dict[str, dict] = {
    "geolife": {
        "spec": GEOLIFE_SPEC,
        "delimiter": ",",
        "fields": {
            "userId": "$8",  # caller appends a user-id column when batching files
            "altitude": "double($4)",
            "dtg": "date('%Y-%m-%d %H:%M:%S', concat($6, ' ', $7))",
            "geom": "point($2, $1)",
        },
    },
    "tdrive": {
        "spec": TDRIVE_SPEC,
        "delimiter": ",",
        "fields": {
            "taxiId": "$1",
            "dtg": "date('%Y-%m-%d %H:%M:%S', $2)",
            "geom": "point($3, $4)",
        },
        "id_field": "concat($1, '-', $0)",
    },
    "twitter": {
        "spec": TWITTER_SPEC,
        "delimiter": "\t",
        "fields": {
            "userId": "$2",
            "text": "$3",
            "dtg": "isodate($4)",
            "geom": "point($5, $6)",
        },
        "id_field": "$1",
    },
    "nyctaxi": {
        "spec": NYCTAXI_SPEC,
        "delimiter": ",",
        "fields": {
            "tripId": "$1",
            "dtg": "date('%Y-%m-%d %H:%M:%S', $2)",
            "passengers": "int($4)",
            "distance": "double($5)",
            "totalAmount": "double($6)",
            "geom": "point($7, $8)",
        },
        "id_field": "$1",
    },
    "marinecadastre-ais": {
        "spec": AIS_SPEC,
        "delimiter": ",",
        "fields": {
            "mmsi": "$1",
            "dtg": "isodate($2)",
            "sog": "double($5)",
            "cog": "double($6)",
            "heading": "double($7)",
            "geom": "point($3, $4)",
        },
    },
}


def predefined_sft(name: str, type_name: str | None = None) -> FeatureType:
    cfg = PREDEFINED[name]
    return parse_spec(type_name or name.replace("-", "_"), cfg["spec"])


def predefined_converter(name: str, type_name: str | None = None) -> DelimitedConverter:
    cfg = PREDEFINED[name]
    return DelimitedConverter(
        predefined_sft(name, type_name),
        fields=cfg["fields"],
        id_field=cfg.get("id_field"),
        delimiter=cfg["delimiter"],
    )
