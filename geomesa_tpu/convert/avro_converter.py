"""Avro ingest converter: object-container files → FeatureTable.

Role parity: ``geomesa-convert/geomesa-convert-avro`` (SURVEY.md §2.16) —
ingest Avro records as features, resolving writer→reader schemas (field
reorder/add/drop, the evolution rules in :mod:`geomesa_tpu.io.avro`) and
optionally renaming fields. Schema inference from the writer schema covers
the no-config path (the reference's ``TypeInference`` role for Avro input).
"""

from __future__ import annotations

import io

from geomesa_tpu.io.avro import read_avro, read_writer_schema
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType, parse_spec

__all__ = ["AvroConverter", "infer_sft_from_avro"]

_AVRO_TO_SPEC = {
    "string": "String",
    "int": "Integer",
    "long": "Long",
    "float": "Float",
    "double": "Double",
    "boolean": "Boolean",
}


def _field_types(writer_schema: dict) -> list[tuple[str, str]]:
    """(name, avro primitive) pairs, unions-of-null unwrapped."""
    out = []
    for f in writer_schema.get("fields", []):
        t = f["type"]
        if isinstance(t, list):  # ["null", X] optional union
            t = next((b for b in t if b != "null"), "null")
        if isinstance(t, dict):
            t = t.get("type", "string")
        out.append((f["name"], t))
    return out


def infer_sft_from_avro(
    writer_schema: dict, type_name: str | None = None
) -> FeatureType:
    """Writer schema → SFT: avro primitives map to attribute types; a
    ``bytes`` field named like a geometry (``geom``/``geometry``/``*_geom``)
    becomes the default Point geometry (WKB payload); a ``long`` field named
    ``dtg``/``date``/``timestamp`` becomes the Date field."""
    parts = []
    geom_done = False
    for name, t in _field_types(writer_schema):
        if name == "__fid__":
            continue
        low = name.lower()
        if t == "bytes" and not geom_done and (
            low in ("geom", "geometry") or low.endswith("_geom")
        ):
            parts.append(f"*{name}:Geometry")
            geom_done = True
        elif t == "long" and low in ("dtg", "date", "timestamp"):
            parts.append(f"{name}:Date")
        elif t in _AVRO_TO_SPEC:
            parts.append(f"{name}:{_AVRO_TO_SPEC[t]}")
        else:  # unknown/complex: keep the raw value as text
            parts.append(f"{name}:String")
    return parse_spec(
        type_name or writer_schema.get("name", "avro"), ",".join(parts)
    )


class AvroConverter:
    """Avro container files → FeatureTable for one schema.

    ``sft=None`` infers the schema from the file's writer schema on first
    convert (available as ``self.sft`` afterwards). ``rename`` maps writer
    field names → SFT attribute names for mismatched vocabularies.
    """

    def __init__(
        self,
        sft: FeatureType | None = None,
        rename: dict[str, str] | None = None,
        type_name: str | None = None,
    ):
        self.sft = sft
        self.rename = dict(rename or {})
        self.type_name = type_name
        # "__fid__" when files embed fids (stable across files); None when
        # read_avro synthesizes per-file row numbers, so multi-file ingest
        # callers know to qualify them — set per file in convert_bytes
        self.id_field: str | None = "__fid__"

    def infer_from(self, path) -> FeatureType:
        """Header-only schema inference (no record decode)."""
        self.sft = infer_sft_from_avro(read_writer_schema(path), self.type_name)
        return self.sft

    def convert_path(self, path, ctx=None) -> FeatureTable:
        with open(path, "rb") as f:
            return self.convert_bytes(f.read(), ctx)

    def convert_str(self, data, ctx=None) -> FeatureTable:
        if isinstance(data, str):
            data = data.encode("latin-1")  # container files are binary
        return self.convert_bytes(data, ctx)

    def convert_bytes(self, data: bytes, ctx=None) -> FeatureTable:
        writer = read_writer_schema(io.BytesIO(data))
        embedded = any(
            f.get("name") == "__fid__" for f in writer.get("fields", [])
        )
        self.id_field = "__fid__" if embedded else None
        if self.sft is None:
            self.sft = infer_sft_from_avro(writer, self.type_name)
        if self.rename:
            records, fids, _ = read_avro(io.BytesIO(data))
            records = [
                {self.rename.get(k, k): v for k, v in r.items()}
                for r in records
            ]
            from geomesa_tpu.geometry.wkb import from_wkb

            geom_fields = {
                a.name for a in self.sft.attributes if a.type.is_geometry
            }
            for rec in records:
                for g in geom_fields:
                    if isinstance(rec.get(g), (bytes, bytearray)):
                        rec[g] = from_wkb(rec[g])
            known = {a.name for a in self.sft.attributes}
            records = [
                {k: v for k, v in r.items() if k in known} for r in records
            ]
            table = FeatureTable.from_records(self.sft, records, fids)
        else:
            # schema-resolved path (evolution rules apply; WKB decoded)
            table = read_avro(io.BytesIO(data), reader_sft=self.sft)
        if ctx is not None:
            ctx.success += len(table)
        return table
