"""Distributed ingest: multiprocess converter parsing feeding the store.

Role parity: ``geomesa-jobs/.../mapreduce/ConverterInputFormat.scala:1``
(distributed ingest parse) and the tools' local multi-threaded ingest
(SURVEY.md §2.16/§2.19). Input files — or byte-range CHUNKS of one large
delimited file, split at line boundaries like Hadoop input splits — parse in
a process pool; each worker ships its FeatureTable back as Arrow IPC bytes
(zero shared state), and the parent bulk-appends into the store, compacting
once at the end. This is the parse half of bulk load; the sorted-store build
half is the store's normal compaction (LSM merge_build).
"""

from __future__ import annotations

import os
from concurrent.futures import ProcessPoolExecutor

__all__ = ["split_file", "parallel_ingest"]


def split_file(path: str, n_chunks: int) -> list[tuple[int, int]]:
    """Byte ranges [(offset, length)] cut at line boundaries.

    Mirrors Hadoop's FileSplit semantics: chunk i starts just after the
    first newline at-or-past ``i * size/n`` (chunk 0 at 0), ends where chunk
    i+1 starts — every line lands in exactly one chunk.
    """
    size = os.path.getsize(path)
    if n_chunks <= 1 or size == 0:
        return [(0, size)]
    approx = size // n_chunks
    cuts = [0]
    with open(path, "rb") as f:
        for i in range(1, n_chunks):
            target = i * approx
            if target <= cuts[-1]:
                continue
            f.seek(target)
            f.readline()  # skip to the next line boundary
            pos = f.tell()
            if pos >= size:
                break
            if pos > cuts[-1]:
                cuts.append(pos)
    cuts.append(size)
    return [(cuts[i], cuts[i + 1] - cuts[i]) for i in range(len(cuts) - 1)]


def _worker(args) -> bytes:
    """Parse one (file | chunk) with a freshly-built converter → Arrow IPC."""
    spec, path, offset, length = args
    # workers are fresh interpreters (spawn): force CPU so a wedged TPU
    # tunnel can never hang an ingest worker (parse is host-side anyway)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    from geomesa_tpu.io.arrow import to_ipc_bytes

    table = _convert(spec, path, offset, length)
    return to_ipc_bytes(table)


def _convert(spec: dict, path: str, offset: int, length: int):
    from geomesa_tpu.schema.sft import parse_spec

    kind = spec["kind"]
    if offset or length is not None:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(length)
    else:
        data = open(path, "rb").read()

    if kind == "gdelt":
        from geomesa_tpu.convert.gdelt import gdelt_fast_table

        return gdelt_fast_table(data)
    sft = parse_spec(spec["sft_name"], spec["sft_spec"])
    if kind == "delimited":
        import io

        from geomesa_tpu.convert.delimited import DelimitedConverter

        conv = DelimitedConverter(
            sft, spec["fields"], delimiter=spec.get("delimiter", ","),
            id_field=spec.get("id_field"),
            error_mode=spec.get("error_mode", "skip"),
        )
        return conv.convert_path(io.BytesIO(data))
    if kind == "json":
        from geomesa_tpu.convert.json_converter import JsonConverter

        conv = JsonConverter(
            sft, spec["fields"], feature_path=spec.get("feature_path", "$"),
            id_field=spec.get("id_field"),
        )
        return conv.convert_str(data.decode("utf-8"))
    if kind == "xml":
        from geomesa_tpu.convert.xml_converter import XmlConverter

        conv = XmlConverter(
            sft, spec["fields"],
            feature_path=spec.get("feature_path", ".//feature"),
            id_field=spec.get("id_field"),
        )
        return conv.convert_str(data.decode("utf-8"))
    raise ValueError(f"unknown converter kind: {kind!r}")


def parallel_ingest(
    ds,
    type_name: str,
    converter_spec: dict,
    paths: list[str] | None = None,
    chunks_of: str | None = None,
    processes: int | None = None,
    fid_prefix: bool = True,
) -> int:
    """Ingest files (or chunks of one file) in parallel; returns rows written.

    ``converter_spec``: {"kind": "delimited"|"json"|"xml"|"gdelt",
    "sft_name", "sft_spec", "fields", ...} — everything a worker needs to
    rebuild the converter (workers share nothing). ``chunks_of``: split ONE
    large file into line-aligned byte ranges instead of per-file tasks.
    ``fid_prefix``: re-key each chunk's fids as ``<chunk>-<fid>`` so
    independently-parsed chunks can't collide.
    """
    from geomesa_tpu.io.arrow import from_ipc_bytes
    from geomesa_tpu.schema.sft import parse_spec

    if (paths is None) == (chunks_of is None):
        raise ValueError("pass exactly one of paths= or chunks_of=")
    if chunks_of is not None:
        n = processes or os.cpu_count() or 4
        tasks = [
            (converter_spec, chunks_of, off, ln)
            for off, ln in split_file(chunks_of, n)
        ]
        # chunk 0 carries the header if the format has one; delimited/gdelt
        # data files are headerless so every chunk parses standalone
    else:
        tasks = [(converter_spec, p, 0, None) for p in paths]

    sft = ds.get_schema(type_name)
    total = 0
    n_workers = min(processes or os.cpu_count() or 4, len(tasks)) or 1
    import multiprocessing as mp

    # spawn: fresh interpreters (no forked jax/pyarrow state)
    with ProcessPoolExecutor(
        max_workers=n_workers, mp_context=mp.get_context("spawn")
    ) as pool:
        for i, ipc in enumerate(pool.map(_worker, tasks)):
            table = from_ipc_bytes(sft, ipc)
            if fid_prefix:
                import numpy as np

                table = type(table)(
                    table.sft,
                    np.array([f"{i}-{f}" for f in table.fids], dtype=object),
                    table.columns,
                )
            total += ds.write(type_name, table)
    ds.compact(type_name)
    return total
