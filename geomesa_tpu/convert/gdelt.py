"""GDELT predefined schema + converter (the benchmark dataset).

Mirrors the reference's predefined GDELT config
(``geomesa-tools/conf/sfts/gdelt/reference.conf`` — SURVEY.md §2.16): the
(v1) event schema keyed on ``globalEventId`` with CAMEO codes, actors,
Goldstein scale, tone, and ``dtg``/``geom`` from SQLDATE +
ActionGeo_Lat/Long. Raw GDELT v1 events export is tab-delimited, 57 columns.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.convert.delimited import DelimitedConverter
from geomesa_tpu.schema.sft import parse_spec

GDELT_SPEC = (
    "globalEventId:String,eventCode:String,eventBaseCode:String,"
    "eventRootCode:String,isRootEvent:Integer,"
    "actor1Name:String:index=true,actor1Code:String,actor1CountryCode:String,"
    "actor2Name:String:index=true,actor2Code:String,actor2CountryCode:String,"
    "quadClass:Integer,goldsteinScale:Double,numMentions:Integer,"
    "numSources:Integer,numArticles:Integer,avgTone:Double,"
    "dtg:Date,*geom:Point:srid=4326"
    ";geomesa.z3.interval='week'"
)


def gdelt_sft(name: str = "gdelt"):
    return parse_spec(name, GDELT_SPEC)


def gdelt_converter(sft=None) -> DelimitedConverter:
    """Converter for the raw GDELT v1 daily export (TSV, no header).

    Column map (1-based, GDELT v1 event table): 1=GLOBALEVENTID, 2=SQLDATE
    (yyyyMMdd), 7=Actor1Name, 6=Actor1Code, 8=Actor1CountryCode, 17=Actor2Name,
    16=Actor2Code, 18=Actor2CountryCode, 26=IsRootEvent, 27=EventCode,
    28=EventBaseCode, 29=EventRootCode, 30=QuadClass, 31=GoldsteinScale,
    32=NumMentions, 33=NumSources, 34=NumArticles, 35=AvgTone,
    40=ActionGeo_Lat, 41=ActionGeo_Long.
    """
    sft = sft or gdelt_sft()
    return DelimitedConverter(
        sft,
        fields={
            "globalEventId": "$1",
            "dtg": "date('%Y%m%d', $2)",
            "actor1Code": "$6",
            "actor1Name": "$7",
            "actor1CountryCode": "$8",
            "actor2Code": "$16",
            "actor2Name": "$17",
            "actor2CountryCode": "$18",
            "isRootEvent": "int($26)",
            "eventCode": "$27",
            "eventBaseCode": "$28",
            "eventRootCode": "$29",
            "quadClass": "int($30)",
            "goldsteinScale": "double($31)",
            "numMentions": "int($32)",
            "numSources": "int($33)",
            "numArticles": "int($34)",
            "avgTone": "double($35)",
            "geom": "point($41, $40)",
        },
        id_field="$1",
        delimiter="\t",
        header=False,
    )


# (attr, 0-based column, native type) for the numeric/date/point hot columns;
# string attrs go through pandas (native loader is typed-numeric only)
_NATIVE_COLS = [
    ("dtg", 1, "date"),
    ("isRootEvent", 25, "i64"),
    ("quadClass", 29, "i64"),
    ("goldsteinScale", 30, "f64"),
    ("numMentions", 31, "i64"),
    ("numSources", 32, "i64"),
    ("numArticles", 33, "i64"),
    ("avgTone", 34, "f64"),
    ("lat", 39, "f64"),
    ("lon", 40, "f64"),
]
_STRING_COLS = {
    "globalEventId": 0, "actor1Code": 5, "actor1Name": 6,
    "actor1CountryCode": 7, "actor2Code": 15, "actor2Name": 16,
    "actor2CountryCode": 17, "eventCode": 26, "eventBaseCode": 27,
    "eventRootCode": 28,
}
_INT_ATTRS = {"isRootEvent", "quadClass", "numMentions", "numSources", "numArticles"}


def gdelt_fast_table(source, sft=None):
    """Fast GDELT ingest: numeric/date/point columns extracted by the native
    C++ loader (:mod:`geomesa_tpu.native`, one pass over the raw bytes),
    string columns via pandas. Returns a FeatureTable with rows lacking a
    valid geometry or date dropped (the converter's ``skip`` error mode).
    Falls back to :func:`gdelt_converter` when the native loader is absent.

    ``source``: path or raw bytes of a GDELT v1 TSV export.
    """
    import io

    import pandas as pd

    from geomesa_tpu import native
    from geomesa_tpu.schema.columnar import Column, FeatureTable, point_column
    from geomesa_tpu.schema.sft import AttributeType

    sft = sft or gdelt_sft()
    data = source if isinstance(source, bytes) else open(source, "rb").read()

    typ_map = {"f64": native.F64, "i64": native.I64, "date": native.DATE_YYYYMMDD}
    out = native.parse_delimited(
        data, "\t", [(c, typ_map[t]) for _, c, t in _NATIVE_COLS]
    )
    if out is None:  # no toolchain: plain converter path
        return gdelt_converter(sft).convert_path(
            io.BytesIO(data) if isinstance(source, bytes) else source
        )
    arrays, valid = out
    byname = {name: (arr, valid[i]) for i, (name, _, _) in enumerate(_NATIVE_COLS)
              for arr in [arrays[i]]}

    lon, lon_ok = byname["lon"]
    lat, lat_ok = byname["lat"]
    dtg, dtg_ok = byname["dtg"]
    keep = (
        lon_ok & lat_ok & dtg_ok
        & (np.abs(lon) <= 180) & (np.abs(lat) <= 90)
    )
    idx = np.nonzero(keep)[0]

    # row boundaries must match the native parser exactly: pandas defaults
    # skip blank lines and honor '"' quoting, either of which would shift df
    # rows relative to the native arrays and silently mispair strings/fids
    # with coordinates — disable both and verify the row count
    import csv

    try:
        df = pd.read_csv(
            io.BytesIO(data), sep="\t", header=None, dtype=str,
            keep_default_na=False, na_values=[],
            usecols=sorted(_STRING_COLS.values()),
            engine="c", skip_blank_lines=False, quoting=csv.QUOTE_NONE,
        )
    except pd.errors.ParserError:
        df = None  # ragged rows under QUOTE_NONE: take the converter path
    if df is None or len(df) != len(lon):
        return gdelt_converter(sft).convert_path(
            io.BytesIO(data) if isinstance(source, bytes) else source
        )
    cols: dict[str, Column] = {}
    for a in sft.attributes:
        if a.name == "geom":
            cols["geom"] = point_column(lon[idx], lat[idx])
        elif a.name == "dtg":
            cols["dtg"] = Column(AttributeType.DATE, dtg[idx])
        elif a.name in _STRING_COLS:
            vals = df[_STRING_COLS[a.name]].to_numpy(dtype=object)[idx]
            ok = np.array([v != "" for v in vals])
            cols[a.name] = Column(a.type, vals, None if ok.all() else ok)
        else:
            arr, ok = byname[a.name]
            dtype = np.int32 if a.name in _INT_ATTRS else np.float64
            cols[a.name] = Column(
                a.type, arr[idx].astype(dtype), None if ok[idx].all() else ok[idx]
            )
    fids = df[0].to_numpy(dtype=object)[idx]
    return FeatureTable(sft, fids, cols)
