"""GDELT predefined schema + converter (the benchmark dataset).

Mirrors the reference's predefined GDELT config
(``geomesa-tools/conf/sfts/gdelt/reference.conf`` — SURVEY.md §2.16): the
(v1) event schema keyed on ``globalEventId`` with CAMEO codes, actors,
Goldstein scale, tone, and ``dtg``/``geom`` from SQLDATE +
ActionGeo_Lat/Long. Raw GDELT v1 events export is tab-delimited, 57 columns.
"""

from __future__ import annotations

from geomesa_tpu.convert.delimited import DelimitedConverter
from geomesa_tpu.schema.sft import parse_spec

GDELT_SPEC = (
    "globalEventId:String,eventCode:String,eventBaseCode:String,"
    "eventRootCode:String,isRootEvent:Integer,"
    "actor1Name:String:index=true,actor1Code:String,actor1CountryCode:String,"
    "actor2Name:String:index=true,actor2Code:String,actor2CountryCode:String,"
    "quadClass:Integer,goldsteinScale:Double,numMentions:Integer,"
    "numSources:Integer,numArticles:Integer,avgTone:Double,"
    "dtg:Date,*geom:Point:srid=4326"
    ";geomesa.z3.interval='week'"
)


def gdelt_sft(name: str = "gdelt"):
    return parse_spec(name, GDELT_SPEC)


def gdelt_converter(sft=None) -> DelimitedConverter:
    """Converter for the raw GDELT v1 daily export (TSV, no header).

    Column map (1-based, GDELT v1 event table): 1=GLOBALEVENTID, 2=SQLDATE
    (yyyyMMdd), 7=Actor1Name, 6=Actor1Code, 8=Actor1CountryCode, 17=Actor2Name,
    16=Actor2Code, 18=Actor2CountryCode, 26=IsRootEvent, 27=EventCode,
    28=EventBaseCode, 29=EventRootCode, 30=QuadClass, 31=GoldsteinScale,
    32=NumMentions, 33=NumSources, 34=NumArticles, 35=AvgTone,
    40=ActionGeo_Lat, 41=ActionGeo_Long.
    """
    sft = sft or gdelt_sft()
    return DelimitedConverter(
        sft,
        fields={
            "globalEventId": "$1",
            "dtg": "date('%Y%m%d', $2)",
            "actor1Code": "$6",
            "actor1Name": "$7",
            "actor1CountryCode": "$8",
            "actor2Code": "$16",
            "actor2Name": "$17",
            "actor2CountryCode": "$18",
            "isRootEvent": "int($26)",
            "eventCode": "$27",
            "eventBaseCode": "$28",
            "eventRootCode": "$29",
            "quadClass": "int($30)",
            "goldsteinScale": "double($31)",
            "numMentions": "int($32)",
            "numSources": "int($33)",
            "numArticles": "int($34)",
            "avgTone": "double($35)",
            "geom": "point($41, $40)",
        },
        id_field="$1",
        delimiter="\t",
        header=False,
    )
