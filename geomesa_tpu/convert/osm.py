"""OpenStreetMap XML converter: nodes → points, ways → linestrings.

Role parity: ``geomesa-convert/geomesa-convert-osm`` (SURVEY.md §2.16)
ingests OSM planet extracts as two feature shapes — tagged nodes as point
features and ways as linestrings with node references resolved against the
node table. The reference streams protobuf/XML per-entity; here the whole
document's nodes parse into columnar arrays in one pass and way geometries
resolve via a vectorized id→position lookup (np.searchsorted over the sorted
node-id column) rather than a per-ref hash probe.

OSM XML shape::

    <osm>
      <node id="1" lat="48.1" lon="11.5" timestamp="..." user="..." ...>
        <tag k="amenity" v="cafe"/>
      </node>
      <way id="7" timestamp="..." user="...">
        <nd ref="1"/> <nd ref="2"/>
        <tag k="highway" v="primary"/>
      </way>
    </osm>

``tag_fields`` promotes chosen tag keys to typed attribute columns; all other
tags land in the ``tags`` column as ``k=v;k=v`` text (the reference keeps a
single tags attribute too).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

import numpy as np

from geomesa_tpu.geometry.types import LineString, Point
from geomesa_tpu.schema.columnar import FeatureTable, _to_millis
from geomesa_tpu.schema.sft import FeatureType, parse_spec

__all__ = [
    "osm_node_sft",
    "osm_way_sft",
    "parse_osm_nodes",
    "parse_osm_ways",
    "OsmConverter",
]

_NODE_BASE = "osmId:Long:index=true,user:String,dtg:Date,tags:String"
_WAY_BASE = "osmId:Long:index=true,user:String,dtg:Date,nNodes:Integer,tags:String"


def osm_node_sft(name: str = "osm_nodes", tag_fields: tuple[str, ...] = ()) -> FeatureType:
    extra = "".join(f",{k}:String" for k in tag_fields)
    return parse_spec(
        name, _NODE_BASE + extra + ",*geom:Point;geomesa.z3.interval='month'"
    )


def osm_way_sft(name: str = "osm_ways", tag_fields: tuple[str, ...] = ()) -> FeatureType:
    extra = "".join(f",{k}:String" for k in tag_fields)
    return parse_spec(
        name, _WAY_BASE + extra + ",*geom:LineString;geomesa.xz.precision='12'"
    )


def _root(source) -> ET.Element:
    if isinstance(source, str) and source.lstrip().startswith("<"):
        return ET.fromstring(source)
    return ET.parse(source).getroot()


def _tags_of(elem: ET.Element) -> dict[str, str]:
    return {
        t.get("k", ""): t.get("v", "")
        for t in elem
        if t.tag == "tag" and t.get("k")
    }


def _meta(elem: ET.Element) -> tuple[str, int | None]:
    user = elem.get("user") or ""
    ts = elem.get("timestamp")
    return user, (_to_millis(ts) if ts else None)


def _tag_text(tags: dict[str, str], promoted: tuple[str, ...]) -> str:
    return ";".join(f"{k}={v}" for k, v in sorted(tags.items()) if k not in promoted)


def parse_osm_nodes(
    source,
    tag_fields: tuple[str, ...] = (),
    tagged_only: bool = False,
    sft: FeatureType | None = None,
) -> FeatureTable:
    """OSM XML → point FeatureTable of nodes.

    ``tagged_only`` keeps only nodes carrying at least one tag (untagged
    nodes are usually just way-geometry vertices — the reference's node
    ingest offers the same cut).
    """
    root = _root(source)
    sft = sft or osm_node_sft(tag_fields=tag_fields)
    recs, fids = [], []
    for el in root:
        if el.tag != "node":
            continue
        tags = _tags_of(el)
        if tagged_only and not tags:
            continue
        try:
            lat, lon = float(el.get("lat")), float(el.get("lon"))
        except (TypeError, ValueError):
            continue  # malformed node: skip (reference error-mode default)
        if abs(lon) > 180 or abs(lat) > 90:
            continue
        user, t = _meta(el)
        oid = int(el.get("id"))
        rec = {
            "osmId": oid,
            "user": user,
            "dtg": t,
            "tags": _tag_text(tags, tag_fields),
            "geom": Point(lon, lat),
        }
        for k in tag_fields:
            rec[k] = tags.get(k)
        recs.append(rec)
        fids.append(f"n{oid}")
    return FeatureTable.from_records(sft, recs, fids)


def parse_osm_ways(
    source,
    tag_fields: tuple[str, ...] = (),
    sft: FeatureType | None = None,
) -> FeatureTable:
    """OSM XML → linestring FeatureTable of ways.

    Node refs resolve against the document's own ``<node>`` elements via one
    sorted-id searchsorted per way batch; ways with unresolvable refs or
    fewer than 2 resolved nodes are skipped (reference behavior for
    incomplete extracts).
    """
    root = _root(source)
    sft = sft or osm_way_sft(tag_fields=tag_fields)

    node_ids, node_lon, node_lat = [], [], []
    ways = []
    for el in root:
        if el.tag == "node":
            try:
                nid = int(el.get("id"))
                x, y = float(el.get("lon")), float(el.get("lat"))
            except (TypeError, ValueError):
                continue
            node_ids.append(nid)
            node_lon.append(x)
            node_lat.append(y)
        elif el.tag == "way":
            refs = [int(nd.get("ref")) for nd in el if nd.tag == "nd"]
            ways.append((el, refs))

    ids = np.asarray(node_ids, dtype=np.int64)
    lon = np.asarray(node_lon, dtype=np.float64)
    lat = np.asarray(node_lat, dtype=np.float64)
    order = np.argsort(ids, kind="stable")
    ids_s, lon_s, lat_s = ids[order], lon[order], lat[order]

    recs, fids = [], []
    for el, refs in ways:
        if len(refs) < 2:
            continue
        r = np.asarray(refs, dtype=np.int64)
        pos = np.searchsorted(ids_s, r)
        if (pos >= len(ids_s)).any() or not np.array_equal(ids_s[pos], r):
            continue  # unresolvable ref: incomplete extract
        coords = np.stack([lon_s[pos], lat_s[pos]], axis=1)
        tags = _tags_of(el)
        user, t = _meta(el)
        oid = int(el.get("id"))
        rec = {
            "osmId": oid,
            "user": user,
            "dtg": t,
            "nNodes": len(refs),
            "tags": _tag_text(tags, tag_fields),
            "geom": LineString(coords),
        }
        for k in tag_fields:
            rec[k] = tags.get(k)
        recs.append(rec)
        fids.append(f"w{oid}")
    return FeatureTable.from_records(sft, recs, fids)


class OsmConverter:
    """Converter-shaped facade (``convert_path``/``convert_str``) so OSM plugs
    into the CLI ingest path like the delimited/JSON/XML/shapefile converters.

    ``mode``: ``"nodes"`` | ``"ways"``.
    """

    def __init__(
        self,
        mode: str = "nodes",
        tag_fields: tuple[str, ...] = (),
        tagged_only: bool = False,
        type_name: str | None = None,
    ):
        if mode not in ("nodes", "ways"):
            raise ValueError(f"mode must be nodes|ways: {mode}")
        self.mode = mode
        self.tag_fields = tuple(tag_fields)
        self.tagged_only = tagged_only
        self.id_field = "osmId"  # fids derive from osm ids: stable across files
        if mode == "nodes":
            self.sft = osm_node_sft(type_name or "osm_nodes", self.tag_fields)
        else:
            self.sft = osm_way_sft(type_name or "osm_ways", self.tag_fields)

    def convert_path(self, path, ctx=None) -> FeatureTable:
        with open(path, encoding="utf-8") as f:
            return self.convert_str(f.read(), ctx)

    def convert_str(self, text: str, ctx=None) -> FeatureTable:
        if self.mode == "nodes":
            out = parse_osm_nodes(
                text, self.tag_fields, self.tagged_only, sft=self.sft
            )
        else:
            out = parse_osm_ways(text, self.tag_fields, sft=self.sft)
        if ctx is not None:
            ctx.success += len(out)
        return out
