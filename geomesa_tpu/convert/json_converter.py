"""JSON ingest converter: jsonpath-subset field extraction → FeatureTable.

The ``geomesa-convert-json`` role (SURVEY.md §2.16): declarative mappings from
JSON documents (a whole document, a path to a feature array, or JSON-lines
files) into typed SFT attributes, sharing the delimited converter's typed
column builders, error modes, and evaluation counters.

Path grammar (subset of the reference's jsonpath support):

    $                   the record itself
    $.a.b               nested object fields
    $.arr[2]            array index
    $.features[*]       (feature_path only) iterate an array of records

Field expressions: a bare path, ``point(<path>, <path>)`` for lon/lat pairs,
``geojson(<path>)`` for GeoJSON geometry objects, or
``concat(<path>, 'lit', ...)``.
"""

from __future__ import annotations

import json
import re

import numpy as np

from geomesa_tpu.convert.delimited import (
    EvaluationContext,
    _boolean_column,
    _date_column,
    _numeric_column,
    _split_args,
)
from geomesa_tpu.geometry.types import (
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.schema.columnar import (
    Column,
    FeatureTable,
    _geometry_column,
    point_column,
)
from geomesa_tpu.schema.sft import AttributeType, FeatureType

_NUMERIC = {
    AttributeType.INT,
    AttributeType.LONG,
    AttributeType.FLOAT,
    AttributeType.DOUBLE,
}
_STEP = re.compile(r"\.(\w+)|\[(\d+|\*)\]")


def _parse_path(path: str):
    path = path.strip()
    if not path.startswith("$"):
        raise ValueError(f"path must start with $: {path!r}")
    steps = []
    for m in _STEP.finditer(path, 1):
        if m.group(1) is not None:
            steps.append(m.group(1))
        elif m.group(2) == "*":
            steps.append("*")
        else:
            steps.append(int(m.group(2)))
    return steps


def _walk(obj, steps):
    for s in steps:
        if obj is None:
            return None
        if s == "*":
            raise ValueError("[*] is only allowed in feature_path")
        if isinstance(s, int):
            obj = obj[s] if isinstance(obj, list) and s < len(obj) else None
        else:
            obj = obj.get(s) if isinstance(obj, dict) else None
    return obj


def geojson_geometry(obj):
    """GeoJSON geometry dict → geometry object (None on null/invalid)."""
    if not isinstance(obj, dict):
        return None
    typ = obj.get("type")
    c = obj.get("coordinates")
    try:
        if typ == "Point":
            return Point(float(c[0]), float(c[1]))
        if typ == "LineString":
            return LineString(c)
        if typ == "Polygon":
            return Polygon(c[0], holes=tuple(c[1:]))
        if typ == "MultiPoint":
            return MultiPoint([Point(float(p[0]), float(p[1])) for p in c])
        if typ == "MultiLineString":
            return MultiLineString([LineString(p) for p in c])
        if typ == "MultiPolygon":
            return MultiPolygon([Polygon(p[0], holes=tuple(p[1:])) for p in c])
    except (TypeError, ValueError, IndexError):
        return None
    return None


class JsonConverter:
    """JSON documents → FeatureTable for one schema.

    ``feature_path``: path to the record array (e.g. ``$.features[*]``) or
    ``$`` for one-record-per-document / JSON-lines input.
    ``fields``: {attribute: expression}; ``id_field``: expression for ids.
    """

    def __init__(
        self,
        sft: FeatureType,
        fields: dict[str, str],
        feature_path: str = "$",
        id_field: str | None = None,
        error_mode: str = "skip",
    ):
        self.sft = sft
        self.fields = fields
        self.id_field = id_field
        if error_mode not in ("skip", "raise"):
            raise ValueError(f"error_mode must be skip|raise: {error_mode}")
        self.error_mode = error_mode
        steps = _parse_path(feature_path)
        if "*" in steps:
            if steps[-1] != "*" or "*" in steps[:-1]:
                raise ValueError("[*] must be the final feature_path step")
            self._prefix, self._iterate = steps[:-1], True
        else:
            self._prefix, self._iterate = steps, False

    # -- record extraction ---------------------------------------------------
    def _records(self, text: str) -> list:
        text = text.strip()
        if not text:
            return []
        if not self._iterate and not text.startswith(("[", "{")):
            raise ValueError("not a JSON document")
        if "\n" in text and not text.startswith("["):
            # JSON-lines: one document per line, feature_path applied per line
            docs = [json.loads(ln) for ln in text.splitlines() if ln.strip()]
        else:
            docs = [json.loads(text)]
        records = []
        for doc in docs:
            base = _walk(doc, self._prefix)
            if self._iterate:
                records.extend(base or [])
            elif isinstance(base, list):
                records.extend(base)
            elif base is not None:
                records.append(base)
        return records

    def convert_path(self, path, ctx: EvaluationContext | None = None) -> FeatureTable:
        with open(path) as f:
            return self.convert_str(f.read(), ctx)

    def convert_str(self, text: str, ctx: EvaluationContext | None = None) -> FeatureTable:
        records = self._records(text)
        ctx = ctx if ctx is not None else EvaluationContext()
        n = len(records)
        cols: dict[str, Column] = {}
        bad = np.zeros(n, dtype=bool)
        for a in self.sft.attributes:
            expr = self.fields.get(a.name, f"$.{a.name}")
            try:
                col, col_bad = self._eval(expr, records, a.type)
            except Exception as e:
                raise ValueError(f"transform {expr!r} for {a.name!r} failed: {e}") from e
            cols[a.name] = col
            bad |= col_bad
        if bad.any():
            if self.error_mode == "raise":
                idx = int(np.nonzero(bad)[0][0])
                raise ValueError(f"bad record at index {idx}")
            ctx.failure += int(bad.sum())
            good = ~bad
            cols = {k: c.take(good) for k, c in cols.items()}
        else:
            good = slice(None)
        kept = int((~bad).sum())
        ctx.success += kept
        if self.id_field:
            fid_col, _ = self._eval(self.id_field, records, AttributeType.STRING)
            fids = fid_col.values[good]
        else:
            fids = np.arange(n)[good].astype(str).astype(object)
        return FeatureTable(self.sft, np.asarray(fids, dtype=object), cols)

    # -- expression evaluation ----------------------------------------------
    def _raw(self, expr: str, records) -> np.ndarray:
        """Sub-expression → object array of raw strings ('' for null)."""
        expr = expr.strip()
        out = np.empty(len(records), dtype=object)
        if expr.startswith(("'", '"')):
            out[:] = expr[1:-1]
            return out
        if expr.startswith("concat"):
            m = re.match(r"^concat\s*\((.*)\)$", expr, re.S)
            parts = [self._raw(a, records) for a in _split_args(m.group(1))]
            acc = parts[0].astype(str)
            for p in parts[1:]:
                acc = np.char.add(acc, p.astype(str))
            return acc.astype(object)
        steps = _parse_path(expr)
        for i, r in enumerate(records):
            v = _walk(r, steps)
            out[i] = "" if v is None else (str(v).lower() if isinstance(v, bool) else str(v))
        return out

    def _values(self, path: str, records) -> list:
        steps = _parse_path(path)
        return [_walk(r, steps) for r in records]

    def _eval(self, expr: str, records, typ: AttributeType) -> tuple[Column, np.ndarray]:
        expr = expr.strip()
        n = len(records)
        m = re.match(r"^(\w+)\s*\((.*)\)$", expr, re.S)
        fn = m.group(1).lower() if m and m.group(1).lower() in (
            "point", "geojson", "isodate", "millistodate",
        ) else None

        if fn == "point":
            ax, ay = _split_args(m.group(2))
            xs = np.array(
                [v if isinstance(v, (int, float)) else np.nan for v in self._values(ax, records)],
                dtype=np.float64,
            )
            ys = np.array(
                [v if isinstance(v, (int, float)) else np.nan for v in self._values(ay, records)],
                dtype=np.float64,
            )
            bad = ~(np.isfinite(xs) & np.isfinite(ys))
            bad |= (np.abs(np.nan_to_num(xs)) > 180) | (np.abs(np.nan_to_num(ys)) > 90)
            return point_column(np.where(bad, 0.0, xs), np.where(bad, 0.0, ys)), bad

        if fn == "geojson":
            (path,) = _split_args(m.group(2))
            raws = self._values(path, records)
            geoms = [geojson_geometry(v) for v in raws]
            bad = np.array(
                [g is None and v is not None for g, v in zip(geoms, raws)], dtype=bool
            )
            return _geometry_column(typ, geoms), bad

        if fn == "isodate":
            import pandas as pd

            (path,) = _split_args(m.group(2))
            raw = self._raw(path, records)
            parsed = pd.to_datetime(pd.Series(raw), errors="coerce", utc=True, format="ISO8601")
            return _date_column(raw, parsed)

        if fn == "millistodate":
            (path,) = _split_args(m.group(2))
            vals = self._values(path, records)
            nums = np.array(
                [v if isinstance(v, (int, float)) else 0 for v in vals], dtype=np.int64
            )
            bad = np.array(
                [not isinstance(v, (int, float)) and v is not None for v in vals], dtype=bool
            )
            valid = np.array([isinstance(v, (int, float)) for v in vals])
            return Column(AttributeType.DATE, nums, None if valid.all() else valid), bad

        # bare path / concat / literal, coerced to the target type
        raw = self._raw(expr, records)
        if typ in _NUMERIC:
            return _numeric_column(raw, typ)
        if typ == AttributeType.DATE:
            import pandas as pd

            parsed = pd.to_datetime(pd.Series(raw), errors="coerce", utc=True)
            return _date_column(raw, parsed)
        if typ == AttributeType.BOOLEAN:
            return _boolean_column(raw)
        if typ.is_geometry:
            geoms = [geojson_geometry(v) for v in self._values(expr, records)]
            return _geometry_column(typ, geoms), np.zeros(n, dtype=bool)
        valid = np.array([v != "" for v in raw])
        return Column(typ, raw, None if valid.all() else valid), np.zeros(n, dtype=bool)
