"""Feature validators applied after conversion, before write.

The ``SimpleFeatureValidator`` role (``convert2/.../SimpleFeatureValidator``,
272 LoC — SURVEY.md §2.16): named validators gate converted features before
ingest. ``has-geo`` requires a non-null geometry, ``has-dtg`` a non-null date,
``index`` both (the reference's default — rows missing either can't be keyed
by the Z/XZ indexes), ``none`` disables validation.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.schema.columnar import FeatureTable

_NAMES = ("index", "has-geo", "has-dtg", "none")


def validation_mask(table: FeatureTable, validators=("index",)) -> np.ndarray:
    """Boolean keep-mask for ``table`` under the named validators."""
    ok = np.ones(len(table), dtype=bool)
    for v in validators:
        if v not in _NAMES:
            raise ValueError(f"unknown validator {v!r}; expected one of {_NAMES}")
        if v == "none":
            continue
        if v in ("index", "has-geo") and table.sft.geom_field is not None:
            ok &= table.geom_column().is_valid()
        if v in ("index", "has-dtg") and table.sft.dtg_field is not None:
            ok &= table.columns[table.sft.dtg_field].is_valid()
    return ok


def apply_validators(
    table: FeatureTable,
    validators=("index",),
    ctx=None,
    error_mode: str = "skip",
) -> FeatureTable:
    """Filter (or reject, under ``error_mode='raise'``) invalid features."""
    ok = validation_mask(table, validators)
    if ok.all():
        return table
    if error_mode == "raise":
        idx = int(np.nonzero(~ok)[0][0])
        raise ValueError(f"feature {table.fids[idx]!r} failed validation {validators}")
    if ctx is not None:
        ctx.failure += int((~ok).sum())
        ctx.success -= int((~ok).sum())
    return table.take(np.nonzero(ok)[0])
