"""Distributed export: query once, write partitioned output files in parallel.

Role parity: ``geomesa-tools`` distributed export
(``export/ExportJob.scala`` — SURVEY.md §2.17): a query's results are split
into chunks, each written as its own output file by a worker, with a manifest
tying the parts together. The reference fans out over MapReduce input splits;
here the scan already ran on the mesh, so the fan-out is over *writers* — the
query result is sliced into row ranges and a process pool encodes each slice
(Arrow IPC ships the slice to the worker; the worker owns one file). Output
formats reuse the single-file export encoders (csv/avro/parquet/orc/arrow).
"""

from __future__ import annotations

import json
import os
from concurrent.futures import ProcessPoolExecutor
from pathlib import Path

__all__ = ["parallel_export", "FORMATS"]

FORMATS = ("csv", "avro", "parquet", "orc", "arrow")


def _write_chunk(args) -> dict:
    """Worker: (sft spec, ipc bytes, path, fmt) → part metadata."""
    spec, ipc, path, fmt = args
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")  # never touch the tunnel
    except Exception:
        pass
    from geomesa_tpu.io.arrow import from_ipc_bytes
    from geomesa_tpu.schema.sft import parse_spec

    sft = parse_spec(spec["name"], spec["spec"])
    table = from_ipc_bytes(sft, ipc)
    p = Path(path)
    if fmt == "arrow":
        p.write_bytes(ipc)
    elif fmt == "avro":
        from geomesa_tpu.io.avro import write_avro

        write_avro(table, str(p))
    elif fmt in ("parquet", "orc"):
        from geomesa_tpu.io.arrow import to_arrow

        at = to_arrow(table, dictionary_encode=False)
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pq.write_table(at, str(p))
        else:
            import pyarrow.orc as po

            po.write_table(at, str(p))
    elif fmt == "csv":
        import pandas as pd

        rows = [table.record(i) for i in range(len(table))]
        cols = list(rows[0]) if rows else [a.name for a in sft.attributes]
        df = pd.DataFrame({c: [str(r.get(c)) for r in rows] for c in cols})
        df.to_csv(str(p), index=False)
    return {"file": p.name, "rows": len(table)}


def parallel_export(
    ds,
    type_name: str,
    query=None,
    out_dir: str | os.PathLike = "export",
    fmt: str = "parquet",
    workers: int | None = None,
    chunk_rows: int = 100_000,
) -> dict:
    """Run ``query`` and write its results as N part files in parallel.

    Returns the manifest (also written to ``<out_dir>/export.json``):
    ``{"type", "format", "rows", "parts": [{"file", "rows"}, ...]}``.
    """
    if fmt not in FORMATS:
        raise ValueError(f"format must be one of {FORMATS}: {fmt!r}")
    if workers is not None and workers < 1:
        raise ValueError(f"workers must be >= 1: {workers}")
    if Path(out_dir).is_file():
        raise ValueError(f"output dir is an existing file: {out_dir}")
    from geomesa_tpu.io.arrow import to_ipc_bytes

    r = ds.query(type_name, query)
    table = r.table
    sft = table.sft
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    n = len(table)
    spec = {"name": sft.name, "spec": sft.to_spec()}

    import numpy as np

    ext = "ipc" if fmt == "arrow" else fmt
    tasks = []
    for k, lo in enumerate(range(0, max(n, 1), chunk_rows)):
        hi = min(lo + chunk_rows, n)
        chunk = table.take(np.arange(lo, hi))
        tasks.append(
            (spec, to_ipc_bytes(chunk), str(out / f"part-{k:05d}.{ext}"), fmt)
        )

    n_workers = min(workers or os.cpu_count() or 4, len(tasks)) or 1
    if n_workers == 1:
        parts = [_write_chunk(t) for t in tasks]
    else:
        import multiprocessing as mp

        with ProcessPoolExecutor(
            max_workers=n_workers, mp_context=mp.get_context("spawn")
        ) as pool:
            parts = list(pool.map(_write_chunk, tasks))

    manifest = {
        "type": type_name,
        "format": fmt,
        "rows": int(sum(p["rows"] for p in parts)),
        "parts": parts,
    }
    (out / "export.json").write_text(json.dumps(manifest, indent=2))
    return manifest
