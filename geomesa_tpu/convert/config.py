"""Config-driven converter definitions (the HOCON converter-config role).

The reference's ingest converters are *declarative*: a HOCON document names
the converter type, the field transform expressions, and the options, and a
factory builds the converter (``geomesa-convert-common/.../convert2/
SimpleFeatureConverter.scala:26``, ``AbstractConverter``). This module is the
same seam for this framework with JSON configs::

    {
      "type": "delimited-text",
      "sft": "name:String,dtg:Date,*geom:Point",
      "type-name": "example",
      "id-field": "$1",
      "fields": {"name": "$1", "dtg": "isodate($2)", "geom": "point($3, $4)"},
      "options": {"delimiter": ",", "header": true, "error-mode": "skip"}
    }

Types: ``delimited-text`` (csv/tsv), ``fixed-width``, ``json``, ``xml``,
``avro``, ``shapefile``, ``gpx``, ``osm``, ``parquet``, and ``predefined``
(named dataset configs — the ``geomesa-tools/conf/sfts`` role). Converters
that infer their own schema (avro/shapefile/parquet/osm/gpx) may omit "sft".
"""

from __future__ import annotations

import json
from pathlib import Path

from geomesa_tpu.schema.sft import FeatureType, parse_spec


class ShapefileConverter:
    """Converter facade over :func:`geomesa_tpu.convert.shapefile.read_shapefile`."""

    def __init__(self, sft: FeatureType | None = None, type_name: str | None = None):
        self.sft = sft
        self.type_name = type_name
        self.id_field = None  # row-number fids: CLI qualifies across files

    def infer_from(self, path) -> FeatureType:
        from geomesa_tpu.convert.shapefile import shapefile_sft

        self.sft = shapefile_sft(self.type_name or Path(path).stem, path)
        return self.sft

    def convert_path(self, path, ctx=None):
        from geomesa_tpu.convert.shapefile import read_shapefile

        if self.sft is None:
            self.infer_from(path)
        t = read_shapefile(path, self.sft)
        if ctx is not None:
            ctx.success += len(t)
        return t


class GpxConverter:
    """Converter facade over :func:`geomesa_tpu.convert.gpx.parse_gpx`."""

    def __init__(self, as_points: bool = False, type_name: str | None = None):
        from geomesa_tpu.convert.gpx import gpx_point_sft, gpx_track_sft

        self.as_points = bool(as_points)
        self.sft = (
            gpx_point_sft(type_name or "gpx_points")
            if self.as_points
            else gpx_track_sft(type_name or "gpx_tracks")
        )
        # track fids are stable trk-N per file only; qualify across files
        self.id_field = None

    def convert_path(self, path, ctx=None):
        from geomesa_tpu.convert.gpx import parse_gpx

        t = parse_gpx(path, as_points=self.as_points)
        if self.sft.name != t.sft.name:
            t.sft = self.sft  # same attribute layout, caller-chosen name
        if ctx is not None:
            ctx.success += len(t)
        return t


def _sft_of(cfg: dict, sft: FeatureType | None) -> FeatureType | None:
    if sft is not None:
        return sft
    spec = cfg.get("sft")
    if spec is None:
        return None
    name = cfg.get("type-name") or cfg.get("type_name") or "features"
    if isinstance(spec, dict):  # {"name": ..., "spec": ...}
        return parse_spec(spec.get("name", name), spec["spec"])
    return parse_spec(name, spec)


def converter_from_config(
    cfg: dict, sft: FeatureType | None = None, type_name: str | None = None
):
    """Build a converter from a config dict. ``sft`` overrides cfg["sft"];
    ``type_name`` (e.g. the CLI schema name) overrides cfg["type-name"]."""
    typ = cfg.get("type")
    if not typ:
        raise ValueError("converter config needs a 'type'")
    typ = typ.replace("_", "-")
    opts = dict(cfg.get("options", {}))
    fields = dict(cfg.get("fields", {}))
    id_field = cfg.get("id-field") or cfg.get("id_field")
    error_mode = opts.pop("error-mode", opts.pop("error_mode", "skip"))
    type_name = type_name or cfg.get("type-name") or cfg.get("type_name")
    if type_name:
        cfg = dict(cfg, **{"type-name": type_name})
    resolved = _sft_of(cfg, sft)

    def need_sft() -> FeatureType:
        if resolved is None:
            raise ValueError(f"converter type {typ!r} requires an 'sft'")
        return resolved

    if typ == "predefined":
        from geomesa_tpu.convert.predefined import predefined_converter

        return predefined_converter(cfg["name"], type_name)
    if typ in ("gpx", "gpx-points"):
        return GpxConverter(
            as_points=typ == "gpx-points"
            or bool(opts.pop("as-points", opts.pop("as_points", False))),
            type_name=type_name,
        )
    if typ in ("delimited-text", "delimited", "csv", "tsv"):
        from geomesa_tpu.convert.delimited import DelimitedConverter

        delim = opts.pop("delimiter", "\t" if typ == "tsv" else ",")
        return DelimitedConverter(
            need_sft(), fields, id_field=id_field, delimiter=delim,
            header=bool(opts.pop("header", False)), error_mode=error_mode,
        )
    if typ == "fixed-width":
        from geomesa_tpu.convert.fixed_width import FixedWidthConverter

        slices = [tuple(s) for s in opts.pop("slices")]
        return FixedWidthConverter(
            need_sft(), slices, fields, id_field=id_field, error_mode=error_mode
        )
    if typ == "json":
        from geomesa_tpu.convert.json_converter import JsonConverter

        return JsonConverter(
            need_sft(), fields,
            feature_path=opts.pop("feature-path", opts.pop("feature_path", "$")),
            id_field=id_field, error_mode=error_mode,
        )
    if typ == "xml":
        from geomesa_tpu.convert.xml_converter import XmlConverter

        return XmlConverter(
            need_sft(), fields,
            feature_path=opts.pop(
                "feature-path", opts.pop("feature_path", ".//feature")
            ),
            id_field=id_field, error_mode=error_mode,
        )
    if typ == "avro":
        from geomesa_tpu.convert.avro_converter import AvroConverter

        return AvroConverter(
            sft=resolved, rename=opts.pop("rename", None), type_name=type_name
        )
    if typ == "shapefile":
        return ShapefileConverter(sft=resolved, type_name=type_name)
    if typ == "osm":
        from geomesa_tpu.convert.osm import OsmConverter

        return OsmConverter(
            mode=opts.pop("mode", "nodes"),
            tag_fields=tuple(opts.pop("tag-fields", opts.pop("tag_fields", ()))),
            tagged_only=bool(opts.pop("tagged-only", opts.pop("tagged_only", False))),
            type_name=type_name,
        )
    if typ in ("parquet", "arrow"):
        from geomesa_tpu.convert.parquet_converter import ParquetConverter

        return ParquetConverter(sft=resolved, type_name=type_name)
    raise ValueError(f"unknown converter type: {typ!r}")


def load_converter(
    name_or_path: str,
    sft: FeatureType | None = None,
    type_name: str | None = None,
):
    """Resolve a CLI ``--converter`` value: a JSON config file path, a
    predefined dataset name, or a bare converter type name. ``type_name``
    names the target schema (overriding any config/inferred name)."""
    from geomesa_tpu.convert.predefined import PREDEFINED, predefined_converter

    _BARE = ("avro", "shapefile", "parquet", "arrow", "gpx", "gpx-points",
             "osm-nodes", "osm-ways")
    p = Path(name_or_path)
    # known names always win: a stray local file called "avro" must not be
    # mistaken for a config document
    if name_or_path not in PREDEFINED and name_or_path not in _BARE and (
        p.suffix == ".json" or p.is_file()
    ):
        with open(p, encoding="utf-8") as f:
            return converter_from_config(json.load(f), sft, type_name)
    if name_or_path in PREDEFINED:
        return predefined_converter(name_or_path, type_name)
    # bare type name: only schema-inferring types make sense without a config
    if name_or_path in _BARE:
        if name_or_path.startswith("osm-"):
            from geomesa_tpu.convert.osm import OsmConverter

            return OsmConverter(
                mode=name_or_path.split("-")[1], type_name=type_name
            )
        return converter_from_config({"type": name_or_path}, sft, type_name)
    raise ValueError(
        f"unknown converter {name_or_path!r}: expected a config file path, a "
        f"predefined dataset ({', '.join(sorted(PREDEFINED))}), or one of "
        "avro/shapefile/parquet/arrow/gpx/gpx-points/osm-nodes/osm-ways"
    )
