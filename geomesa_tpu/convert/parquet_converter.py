"""Parquet / Arrow-IPC file ingest (the ``geomesa-convert-parquet`` role).

The reference ships a Parquet converter module inside ``geomesa-convert``
(SURVEY.md §2.16) that reads SimpleFeatures back out of the FS-storage
Parquet layout (``geomesa-fs-storage-parquet/.../SimpleFeatureParquetSchema.scala``).
Here the equivalent is direct: our canonical Arrow mapping (:mod:`geomesa_tpu.io.arrow`)
already defines the column layout, so ingest is ``read file → pa.Table →
from_arrow``, plus writer-schema → SFT inference so files can be ingested
without a pre-declared schema (the ``TypeInference`` role for columnar files).
"""

from __future__ import annotations

from pathlib import Path

import pyarrow as pa

from geomesa_tpu.convert.delimited import EvaluationContext
from geomesa_tpu.io.arrow import from_arrow
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import AttributeDescriptor, AttributeType, FeatureType

_ARROW_SCALAR = {
    pa.int8(): AttributeType.INT,
    pa.int16(): AttributeType.INT,
    pa.int32(): AttributeType.INT,
    pa.int64(): AttributeType.LONG,
    pa.float32(): AttributeType.FLOAT,
    pa.float64(): AttributeType.DOUBLE,
    pa.bool_(): AttributeType.BOOLEAN,
    pa.string(): AttributeType.STRING,
    pa.large_string(): AttributeType.STRING,
    pa.binary(): AttributeType.BYTES,
    pa.large_binary(): AttributeType.BYTES,
}


def _attr_type(f: pa.Field) -> AttributeType | None:
    t = f.type
    if isinstance(t, pa.DictionaryType):
        t = t.value_type
    if pa.types.is_fixed_size_list(t) and t.list_size == 2 and pa.types.is_floating(
        t.value_type
    ):
        return AttributeType.POINT
    if f.metadata and f.metadata.get(b"geom") in (b"wkt", b"twkb", b"wkb"):
        return AttributeType.GEOMETRY
    if pa.types.is_timestamp(t) or pa.types.is_date(t):
        return AttributeType.DATE
    return _ARROW_SCALAR.get(t)


def infer_sft_from_arrow(schema: pa.Schema, type_name: str) -> FeatureType:
    """Arrow schema → SFT. Unmappable columns are skipped (nested lists etc.)."""
    attrs = []
    for f in schema:
        if f.name == "__fid__":
            continue
        at = _attr_type(f)
        if at is not None:
            attrs.append(AttributeDescriptor(f.name, at))
    if not attrs:
        raise ValueError(f"no ingestible columns in arrow schema: {schema.names}")
    return FeatureType(type_name, attrs)


def _normalize(at: pa.Table, sft: FeatureType) -> pa.Table:
    """Cast date-typed columns to timestamp[ms] so ``from_arrow`` sees the
    canonical layout regardless of the writer's timestamp unit."""
    for i, name in enumerate(at.column_names):
        if name in sft and sft.attr(name).type == AttributeType.DATE:
            col = at.column(i)
            if col.type != pa.timestamp("ms"):
                at = at.set_column(
                    i, pa.field(name, pa.timestamp("ms")),
                    col.cast(pa.timestamp("ms")),
                )
    return at


def _is_ipc(p: Path) -> bool:
    return p.suffix in (".arrow", ".ipc", ".arrows", ".feather")


def _load_arrow(p: Path) -> pa.Table:
    if _is_ipc(p):
        try:
            with pa.ipc.open_file(p) as r:
                return r.read_all()
        except pa.ArrowInvalid:  # stream-format file with a file extension
            with pa.ipc.open_stream(p.read_bytes()) as r:
                return r.read_all()
    import pyarrow.parquet as pq

    return pq.read_table(p)


def read_columnar(path, sft: FeatureType | None = None, type_name: str | None = None):
    """Read one .parquet / .arrow(.ipc/feather) file → (FeatureTable, sft)."""
    p = Path(path)
    at = _load_arrow(p)
    if sft is None:
        sft = infer_sft_from_arrow(at.schema, type_name or p.stem)
    return from_arrow(sft, _normalize(at, sft)), sft


class ParquetConverter:
    """Converter facade (``convert_path``/``.sft``/``.id_field``) over
    :func:`read_columnar`, so columnar files plug into the CLI ingest path
    exactly like the delimited/JSON/XML/Avro converters."""

    def __init__(self, sft: FeatureType | None = None, type_name: str | None = None):
        self.sft = sft
        self.type_name = type_name
        # row fids come from __fid__ when present (stable across files);
        # set per file in convert_path, mirroring AvroConverter
        self.id_field: str | None = "__fid__"

    def _schema(self, p: Path) -> pa.Schema:
        if _is_ipc(p):
            try:
                with pa.ipc.open_file(p) as r:
                    return r.schema
            except pa.ArrowInvalid:
                with pa.ipc.open_stream(p.read_bytes()) as r:
                    return r.schema
        import pyarrow.parquet as pq

        return pq.read_schema(p)

    def infer_from(self, path) -> FeatureType:
        p = Path(path)
        self.sft = infer_sft_from_arrow(self._schema(p), self.type_name or p.stem)
        return self.sft

    def convert_path(self, path, ctx: EvaluationContext | None = None) -> FeatureTable:
        at = _load_arrow(Path(path))
        if self.sft is None:
            self.sft = infer_sft_from_arrow(
                at.schema, self.type_name or Path(path).stem
            )
        # files without an embedded __fid__ get per-file row-number fids,
        # which collide across files — id_field=None tells multi-file
        # ingest to qualify them
        self.id_field = "__fid__" if "__fid__" in at.schema.names else None
        table = from_arrow(self.sft, _normalize(at, self.sft))
        if ctx is not None:
            ctx.success += len(table)
        return table
