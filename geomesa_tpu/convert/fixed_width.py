"""Fixed-width text ingest converter.

The ``geomesa-convert-fixedwidth`` role (SURVEY.md §2.16): records are lines,
attributes are character slices. Columns are cut into a string DataFrame and
handed to the delimited converter's transform machinery, so the full
expression language (``point()``, ``date()``, casts, error modes, counters)
applies unchanged — ``$1``..``$n`` refer to the configured slices in order.
"""

from __future__ import annotations

import io

import pandas as pd

from geomesa_tpu.convert.delimited import DelimitedConverter, EvaluationContext
from geomesa_tpu.schema.columnar import FeatureTable
from geomesa_tpu.schema.sft import FeatureType


class FixedWidthConverter(DelimitedConverter):
    """Lines of fixed-width fields → FeatureTable.

    ``slices``: [(start, length), ...] zero-based character slices, defining
    columns ``$1``..``$n`` for the field expressions.
    """

    def __init__(
        self,
        sft: FeatureType,
        slices: list[tuple[int, int]],
        fields: dict[str, str],
        id_field: str | None = None,
        error_mode: str = "skip",
    ):
        super().__init__(
            sft, fields, id_field=id_field, header=False, error_mode=error_mode
        )
        if not slices:
            raise ValueError("need at least one slice")
        self.slices = [(int(s), int(w)) for s, w in slices]

    def _frame(self, lines) -> pd.DataFrame:
        cols = {
            i: [ln[s : s + w].strip() for ln in lines]
            for i, (s, w) in enumerate(self.slices)
        }
        return pd.DataFrame(cols, dtype=str)

    def convert_path(self, path, ctx: EvaluationContext | None = None) -> FeatureTable:
        with open(path) as f:
            return self.convert_lines(f.read().splitlines(), ctx)

    def convert_str(self, text: str, ctx: EvaluationContext | None = None) -> FeatureTable:
        return self.convert_lines(io.StringIO(text).read().splitlines(), ctx)

    def convert_lines(self, lines, ctx: EvaluationContext | None = None) -> FeatureTable:
        lines = [ln for ln in lines if ln.strip()]
        return self.convert_frame(self._frame(lines), ctx)
