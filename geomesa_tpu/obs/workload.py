"""Workload capture journal — the record half of the usage & workload
plane (docs/observability.md § Usage metering & workload replay).

Every completed query's :class:`~geomesa_tpu.obs.flight.QueryAuditRecord`
serializes as ONE structured wide event (JSON line) to a size-capped
rotating capture file under ``GEOMESA_TPU_WORKLOAD_DIR`` — recording
enough to RE-ISSUE the query (op, type, filter text, re-issuable hints,
arrival timestamp, tenant/auths) plus what it cost (latency, rows,
plan signature, the cost model's prediction), so

- :mod:`geomesa_tpu.obs.replay` can re-run yesterday's real traffic
  against a changed planner/cost-model/admission config and diff the
  latency distributions per plan shape, and
- the capture doubles as an audit trail joinable to the flight recorder
  and devmon attribution by (trace_id, ts).

Capture is OPT-IN by environment: with ``GEOMESA_TPU_WORKLOAD_DIR``
unset, the hot path is one module-global bool check
(:data:`ENABLED` — same pattern as ``devmon.PROFILING``), preserving the
<2% cached-select bound. With capture ON, events buffer in memory and
flush in batches (``flush_every``), so the per-query cost stays an
append + an occasional amortized batch write.

Rotation: ``capture.jsonl`` is the live file; past ``max_bytes`` it
rotates to ``capture.1.jsonl`` … ``capture.<max_files-1>.jsonl`` (oldest
deleted). Every event carries a process-monotonic ``seq`` so readers can
re-establish deterministic total order across rotated files even when
two queries complete in the same clock tick.

Locking (docs/concurrency.md): ``_lock`` is a LEAF guarding the buffer +
sequence counter (no blocking calls under it); ``_flush_lock`` is taken
BEFORE ``_lock`` and serializes file I/O, so flushes from concurrent
threads write buffered batches in seq order. No jax anywhere
(``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import json
import os
import threading

from geomesa_tpu.analysis.contracts import feedback_sink

__all__ = [
    "ENABLED", "WORKLOAD_DIR_ENV", "WorkloadJournal", "flush", "get",
    "install", "read_events", "record",
]

WORKLOAD_DIR_ENV = "GEOMESA_TPU_WORKLOAD_DIR"
MAX_MB_ENV = "GEOMESA_TPU_WORKLOAD_MAX_MB"
MAX_FILES_ENV = "GEOMESA_TPU_WORKLOAD_FILES"

CAPTURE_FILE = "capture.jsonl"

# THE one check the per-query audit path pays when capture is off
ENABLED = False

# hints that survive capture → replay: plain-data knobs a re-issued query
# can carry verbatim. Live objects (deadline handles), identity (tenant —
# captured as its own field), and sampling toggles are dropped.
_REPLAYABLE_HINTS = (
    "index", "loose_bbox", "density", "stats", "bin", "sampling",
    "sample_by", "now_ms", "tenant",
)


def _json_safe(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return str(v)


class WorkloadJournal:
    """Rotating JSONL writer for query wide events.

    ``append`` is the hot path: serialize OUTSIDE any lock, enqueue under
    the leaf lock, flush a full buffer in one batched write. ``flush()``
    forces the buffer to disk (tests, process shutdown, CLI capture)."""

    def __init__(self, directory: str, max_bytes: int | None = None,
                 max_files: int | None = None, flush_every: int = 256):
        if max_bytes is None:
            try:
                max_bytes = int(
                    float(os.environ.get(MAX_MB_ENV, "64")) * 1024 * 1024)
            except ValueError:
                max_bytes = 64 * 1024 * 1024
        if max_files is None:
            try:
                max_files = int(os.environ.get(MAX_FILES_ENV, "4"))
            except ValueError:
                max_files = 4
        self.directory = directory
        self.max_bytes = max(int(max_bytes), 4096)
        self.max_files = max(int(max_files), 1)
        self.flush_every = max(int(flush_every), 1)
        self._flush_lock = threading.Lock()  # ordering: flush_lock → lock
        self._lock = threading.Lock()  # leaf: buffer + seq
        self._buf: list[str] = []
        self._seq = 0
        self.event_count = 0  # lifetime appends (ops surface)
        self.dropped_count = 0  # failed batch writes (full disk)

    @property
    def path(self) -> str:
        return os.path.join(self.directory, CAPTURE_FILE)

    # -- write surface --------------------------------------------------------
    def append(self, event: dict) -> None:
        """Append one wide event (a dict of JSON-able values; ``seq`` is
        stamped here). The write is buffered; a full buffer flushes in
        one batch."""
        with self._lock:
            self._seq += 1
            event = dict(event, seq=self._seq)
            # serialize under the lock: the seq stamp and the line's place
            # in the buffer must agree (serialization is dict→str CPU work,
            # not blocking I/O — the R003 concern is file/socket waits)
            self._buf.append(json.dumps(event, separators=(",", ":")))
            self.event_count += 1
            need_flush = len(self._buf) >= self.flush_every
        if need_flush:
            self.flush()

    def flush(self) -> None:
        """Write every buffered line. ``_flush_lock`` (held across the
        buffer swap AND the file write) keeps concurrent flushes in seq
        order; a failed write (full/readonly disk) drops the batch and
        counts it — capture must never fail the query path."""
        with self._flush_lock:
            with self._lock:
                if not self._buf:
                    return
                lines, self._buf = self._buf, []
            data = "\n".join(lines) + "\n"
            try:
                os.makedirs(self.directory, exist_ok=True)
                self._rotate_if_needed(len(data))
                # _flush_lock exists to serialize exactly this I/O (batch
                # ordering across threads); the hot append path never
                # blocks on it
                # tpulint: disable-next-line=R003
                with open(self.path, "a", encoding="utf-8") as fh:
                    fh.write(data)
            except OSError:
                with self._lock:
                    self.dropped_count += len(lines)

    def _rotate_if_needed(self, incoming: int) -> None:
        """Size-capped rotation (called under ``_flush_lock``)."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size + incoming <= self.max_bytes:
            return
        # capture.(n-1) dies; capture.i → capture.(i+1); capture → capture.1
        oldest = self._rotated(self.max_files - 1)
        if self.max_files == 1:
            os.replace(self.path, self.path + ".tmp")
            os.remove(self.path + ".tmp")
            return
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_files - 2, 0, -1):
            src = self._rotated(i)
            if os.path.exists(src):
                os.replace(src, self._rotated(i + 1))
        os.replace(self.path, self._rotated(1))

    def _rotated(self, i: int) -> str:
        return os.path.join(self.directory, f"capture.{i}.jsonl")

    # -- read surface ---------------------------------------------------------
    def files(self) -> list[str]:
        """Capture files, OLDEST first (rotated high→low, then live)."""
        out = []
        for i in range(self.max_files - 1, 0, -1):
            p = self._rotated(i)
            if os.path.exists(p):
                out.append(p)
        if os.path.exists(self.path):
            out.append(self.path)
        return out


def read_events(path_or_dir: str) -> list[dict]:
    """Load captured events from a capture directory (EVERY rotated file
    present on disk — globbed, so reading never depends on the writing
    process's ``max_files`` config — oldest first) or a single JSONL
    file; returns them sorted by ``(ts_arrival, seq)`` — the
    deterministic replay order. Truncated tail lines (a crash
    mid-write) are skipped, not fatal."""
    if os.path.isdir(path_or_dir):
        import glob as _glob

        rotated = []
        for p in _glob.glob(os.path.join(path_or_dir, "capture.*.jsonl")):
            stem = os.path.basename(p)[len("capture."):-len(".jsonl")]
            if stem.isdigit():
                rotated.append((int(stem), p))
        # highest rotation index = oldest
        paths = [p for _, p in sorted(rotated, reverse=True)]
        live = os.path.join(path_or_dir, CAPTURE_FILE)
        if os.path.exists(live):
            paths.append(live)
    else:
        paths = [path_or_dir]
    events: list[dict] = []
    for p in paths:
        with open(p, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue  # torn tail line
    events.sort(key=lambda e: (e.get("ts_arrival", 0.0), e.get("seq", 0)))
    return events


# -- process-wide journal (env-gated) -----------------------------------------

_journal: WorkloadJournal | None = None
_resolved = False  # env resolution ran (or install() overrode it)
_init_lock = threading.Lock()


def _env_journal() -> "WorkloadJournal | None":
    d = os.environ.get(WORKLOAD_DIR_ENV) or None
    if d is None:
        return None
    return WorkloadJournal(d)


def get() -> "WorkloadJournal | None":
    """The process journal (None when capture is disabled). Created
    lazily from ``GEOMESA_TPU_WORKLOAD_DIR`` on first call; an explicit
    :func:`install` (including ``install(None)``) pins the choice."""
    global _journal, ENABLED, _resolved
    if not _resolved:
        with _init_lock:
            if not _resolved:
                _journal = _env_journal()
                ENABLED = _journal is not None
                _resolved = True
    return _journal


def install(journal: "WorkloadJournal | None") -> "WorkloadJournal | None":
    """Swap the process journal (tests / ``bench.py --capture-workload``);
    ``None`` disables capture. Returns the previous journal."""
    global _journal, ENABLED, _resolved
    with _init_lock:
        prev, _journal = _journal, journal
        ENABLED = journal is not None
        _resolved = True
    return prev


def flush() -> None:
    j = _journal
    if j is not None:
        j.flush()


@feedback_sink
def record(*, ts: float, op: str, type_name: str, source: str,
           filter_text: str, hints: dict | None, tenant: str,
           auths, plan_signature: str, predicted_ms,
           latency_ms: float, rows: int, bytes_out: int = 0,
           trace_id: str = "", device_ms: float = 0.0,
           degraded: bool = False) -> None:
    """Append one query wide event to the process journal (no-op unless
    capture is enabled — callers gate on :data:`ENABLED` first so the off
    path costs one module-global check)."""
    j = get()
    if j is None:
        return
    safe_hints = None
    if hints:
        safe_hints = {
            k: _json_safe(v) for k, v in hints.items()
            if k in _REPLAYABLE_HINTS
        }
    j.append({
        # arrival = completion - latency: replay paces by arrival time
        "ts_arrival": round(ts - latency_ms / 1000.0, 6),
        "ts": round(ts, 6),
        "op": op,
        "type": type_name,
        "source": source,
        "filter": filter_text,
        "hints": safe_hints or None,
        "tenant": tenant,
        "auths": list(auths) if auths is not None else None,
        "plan_signature": plan_signature,
        "predicted_ms": predicted_ms,
        "latency_ms": round(float(latency_ms), 3),
        "rows": int(rows),
        "bytes_out": int(bytes_out),
        "trace_id": trace_id,
        "device_ms": round(float(device_ms), 3),
        "degraded": bool(degraded),
    })


# resolve the env gate at import: the operator path sets
# GEOMESA_TPU_WORKLOAD_DIR before the process starts, and hot-path
# callers gate on the ENABLED bool alone (tests pin a journal with
# install(), which re-resolves)
get()

# buffered tail events land on interpreter exit (bench runs, CLI tools);
# flush() on a disabled journal is a no-op
import atexit  # noqa: E402 — registered after the env gate resolves

atexit.register(flush)
