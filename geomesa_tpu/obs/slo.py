"""SLO objectives and multi-window burn-rate tracking.

The production triad's third leg (docs/observability.md): latency /
availability objectives per index, endpoint, or federation member, with
multi-window burn rates (5m / 1h — the Google SRE multi-window
multi-burn-rate alerting shape) and error-budget accounting.

Mechanics: each tracker keeps time-bucketed good/bad counters (10 s
buckets, enough for the 1 h window in O(1) memory) for the burn-rate
math, plus a fixed ring of the most recent latencies for the quantile
surface (p50/p95/p99 on the member scoreboard — same nearest-rank
interpolation as the metrics registry's Histogram reservoirs, but a
recent-window sample and a single O(1) index store per observation: the
SLO engine sits on the always-on query path, where the reservoir's
per-update RNG draw is too expensive). An observation is *bad* when the
call failed, or — for latency objectives — when it succeeded slower
than ``latency_ms``.

Definitions:

- ``burn_rate(window)`` = (observed error rate over the window) /
  (allowed error rate ``1 - target``). 1.0 = burning the budget exactly
  at the sustainable rate; 14.4 on the 1 h window is the classic
  page-now threshold.
- ``budget_remaining(window)`` = 1 − errors / (total × (1 − target)),
  clamped to [0, 1]: the fraction of the window's error budget left.

Exposition: :meth:`SloEngine.prometheus_lines` emits
``geomesa_slo_burn_rate`` / ``geomesa_slo_budget_remaining`` gauges with
``slo=`` / ``key=`` / ``window=`` labels; the web layer appends them to
``GET /api/metrics?format=prometheus``.

Locking: one leaf lock per engine guards the tracker table and bucket
counters (metrics tier in docs/concurrency.md); Histogram updates run
OUTSIDE it (the histogram owns its own leaf lock). No jax anywhere
(``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from geomesa_tpu.analysis.contracts import feedback_sink

__all__ = ["SloEngine", "SloObjective", "SloTracker", "window_label"]

_BUCKET_S = 10.0  # counter granularity; 1h window = 360 buckets
_LAT_RING = 512  # recent latencies kept per tracker for quantiles


def window_label(window_s: float) -> str:
    if window_s % 3600 == 0:
        return f"{int(window_s // 3600)}h"
    if window_s % 60 == 0:
        return f"{int(window_s // 60)}m"
    return f"{int(window_s)}s"


class SloObjective:
    """One objective definition: availability target plus an optional
    latency threshold (a slow success burns budget too)."""

    __slots__ = ("name", "target", "latency_ms", "windows")

    def __init__(self, name: str, target: float = 0.999,
                 latency_ms: float | None = None,
                 windows: tuple = (300.0, 3600.0)):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if not windows:
            raise ValueError("at least one window required")
        self.name = name
        self.target = target
        self.latency_ms = latency_ms
        self.windows = tuple(float(w) for w in windows)


class SloTracker:
    """Bucketed good/bad counters + a recent-latency ring for one
    (objective, key) pair. Bucket mutation is guarded by the OWNING
    engine's lock (passed in) — one lock per engine keeps the hot path
    at a single acquisition."""

    __slots__ = ("objective", "key", "_buckets", "_lock", "_lat", "_lat_n")

    def __init__(self, objective: SloObjective, key: str, lock):
        self.objective = objective
        self.key = key
        self._lock = lock
        # (bucket_start_s, total, bad), oldest first, pruned to the
        # longest window on append
        self._buckets: deque = deque()
        # fixed ring of the most recent latencies: one index store per
        # observation, quantile sorting happens only at read time
        self._lat: list[float] = [0.0] * _LAT_RING
        self._lat_n = 0

    def _observe_locked(self, ok: bool, latency_ms, now: float) -> None:
        start = now - (now % _BUCKET_S)
        if self._buckets and self._buckets[-1][0] == start:
            b = self._buckets[-1]
            b[1] += 1
            b[2] += 0 if ok else 1
        else:
            self._buckets.append([start, 1, 0 if ok else 1])
            horizon = now - max(self.objective.windows) - _BUCKET_S
            while self._buckets and self._buckets[0][0] < horizon:
                self._buckets.popleft()
        if latency_ms is not None:
            self._lat[self._lat_n % _LAT_RING] = latency_ms
            self._lat_n += 1

    def latency_quantiles(self, qs=(0.5, 0.95, 0.99)) -> list[float]:
        """Quantiles over the recent-latency ring (nearest-rank with
        linear interpolation, sorted OUTSIDE the lock)."""
        with self._lock:
            n = min(self._lat_n, _LAT_RING)
            sample = self._lat[:n]
        sample.sort()
        if not sample:
            return [0.0] * len(qs)
        out = []
        top = len(sample) - 1
        for q in qs:
            pos = q * top
            lo = int(pos)
            hi = min(lo + 1, top)
            frac = pos - lo
            out.append(sample[lo] * (1.0 - frac) + sample[hi] * frac)
        return out

    def _counts(self, window_s: float, now: float) -> tuple[int, int]:
        lo = now - window_s
        total = bad = 0
        with self._lock:
            for start, t, b in self._buckets:
                if start + _BUCKET_S > lo:
                    total += t
                    bad += b
        return total, bad

    def burn_rate(self, window_s: float, now: float | None = None,
                  _clock=time.monotonic) -> float:
        total, bad = self._counts(window_s, _clock() if now is None else now)
        if total == 0:
            return 0.0
        allowed = 1.0 - self.objective.target
        return (bad / total) / allowed

    def budget_remaining(self, window_s: float, now: float | None = None,
                         _clock=time.monotonic) -> float:
        total, bad = self._counts(window_s, _clock() if now is None else now)
        if total == 0:
            return 1.0
        allowed = total * (1.0 - self.objective.target)
        return max(0.0, min(1.0, 1.0 - bad / allowed))


class SloEngine:
    """A set of objectives + their per-key trackers. ``observe`` is the
    hot path: one lock acquisition plus one (unlocked-tier) histogram
    update."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()  # leaf: trackers table + buckets
        self._objectives: dict[str, SloObjective] = {}
        self._trackers: dict[tuple[str, str], SloTracker] = {}

    def objective(self, name: str, target: float = 0.999,
                  latency_ms: float | None = None,
                  windows: tuple = (300.0, 3600.0)) -> SloObjective:
        """Define (or redefine) one objective."""
        obj = SloObjective(name, target, latency_ms, windows)
        with self._lock:
            self._objectives[name] = obj
        return obj

    def tracker(self, name: str, key: str = "") -> SloTracker:
        with self._lock:
            obj = self._objectives.get(name)
            if obj is None:
                obj = self._objectives[name] = SloObjective(name)
            tk = self._trackers.get((name, key))
            if tk is None:
                tk = self._trackers[(name, key)] = SloTracker(
                    obj, key, self._lock)
        return tk

    @feedback_sink
    def observe(self, name: str, ok: bool,
                latency_ms: float | None = None, key: str = "") -> None:
        """One observation against objective ``name`` (auto-defined with
        defaults on first sight), optionally split by ``key`` (a
        federation member index, an index name, an endpoint). The hot
        path: a lock-free tracker-table hit (dict reads are GIL-atomic;
        misses fall back to the locked create) plus ONE lock acquisition
        for the bucket + latency-ring update."""
        tk = self._trackers.get((name, key))
        if tk is None:
            tk = self.tracker(name, key)
        good = ok
        if (
            good
            and latency_ms is not None
            and tk.objective.latency_ms is not None
            and latency_ms > tk.objective.latency_ms
        ):
            good = False  # a slow success burns latency-objective budget
        now = self._clock()
        with self._lock:
            tk._observe_locked(good, latency_ms, now)

    def forget(self, name: str, key: str = "") -> None:
        """Drop one (objective, key) tracker — callers with bounded key
        spaces (the usage meter's tenant table) evict trackers alongside
        their own entries so an unbounded key stream cannot grow the
        engine or its exposition."""
        with self._lock:
            self._trackers.pop((name, key), None)

    def trackers(self) -> list[SloTracker]:
        with self._lock:
            return list(self._trackers.values())

    # -- read surfaces --------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON surface (the ``/api/metrics`` default format)."""
        now = self._clock()
        out: dict = {}
        for tk in self.trackers():
            label = tk.objective.name + (f".{tk.key}" if tk.key else "")
            p50, p95, p99 = tk.latency_quantiles()
            out[label] = {
                "target": tk.objective.target,
                "latency_ms": tk.objective.latency_ms,
                "windows": {
                    window_label(w): {
                        "burn_rate": tk.burn_rate(w, now),
                        "budget_remaining": tk.budget_remaining(w, now),
                    }
                    for w in tk.objective.windows
                },
                "p50_ms": p50,
                "p95_ms": p95,
                "p99_ms": p99,
            }
        return out

    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        """``slo_burn_rate`` / ``slo_budget_remaining`` gauges with
        slo/key/window labels (empty when nothing has been observed)."""
        trackers = self.trackers()
        if not trackers:
            return []
        now = self._clock()

        def esc(v: str) -> str:
            # text-exposition label escaping: keys can carry arbitrary
            # strings (tenant ids, filter-derived names)
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        burn = [f"# TYPE {prefix}_slo_burn_rate gauge"]
        budget = [f"# TYPE {prefix}_slo_budget_remaining gauge"]
        for tk in trackers:
            labels = f'slo="{esc(tk.objective.name)}"'
            if tk.key:
                labels += f',key="{esc(tk.key)}"'
            for w in tk.objective.windows:
                wl = f'{labels},window="{window_label(w)}"'
                burn.append(
                    f"{prefix}_slo_burn_rate{{{wl}}} "
                    f"{tk.burn_rate(w, now):.6g}")
                budget.append(
                    f"{prefix}_slo_budget_remaining{{{wl}}} "
                    f"{tk.budget_remaining(w, now):.6g}")
        return burn + budget

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        lines = self.prometheus_lines(prefix)
        return "\n".join(lines) + "\n" if lines else ""
