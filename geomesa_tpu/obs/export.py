"""Trace and metrics exporters: Chrome/Perfetto trace-event JSON and
Prometheus text exposition.

- :func:`chrome_trace_events` / :func:`write_chrome_trace` — the span trees
  from :mod:`geomesa_tpu.obs.trace` as Chrome trace-event "complete" (ph=X)
  events, loadable in ``ui.perfetto.dev`` or ``chrome://tracing``. One file
  per query (``DataStore.explain(..., analyze=True)`` + ``root=``) or per
  bench run (``bench.py --trace``).

- :func:`prometheus_text` — any number of
  :class:`~geomesa_tpu.utils.metrics.MetricsRegistry` snapshots as
  Prometheus text exposition (version 0.0.4): counters as ``_total``,
  gauges as-is, histograms/timers as summaries with p50/p95/p99 quantile
  labels. Wired into ``GET /api/metrics?format=prometheus``
  (:mod:`geomesa_tpu.web.app`).

No jax anywhere in this module (``GEOMESA_TPU_NO_JAX=1`` safe).
"""

from __future__ import annotations

import json
import re

from geomesa_tpu.obs import trace as _trace

__all__ = [
    "chrome_trace_events", "write_chrome_trace",
    "prometheus_text", "PROMETHEUS_CONTENT_TYPE",
]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# -- Chrome / Perfetto trace-event JSON --------------------------------------

def _span_event(s, tid: int) -> dict:
    args = {"trace_id": s.trace_id, "span_id": s.span_id}
    for k, v in s.attrs.items():
        args[k] = v if isinstance(v, (int, float, bool, str, type(None))) else str(v)
    return {
        "name": s.name,
        "cat": "geomesa",
        "ph": "X",  # complete event: ts + dur
        "ts": s.t0_ns / 1e3,  # microseconds
        "dur": max(s.t1_ns - s.t0_ns, 0) / 1e3,
        "pid": 1,
        "tid": tid,
        "args": args,
    }


def chrome_trace_events(roots=None) -> list[dict]:
    """Flatten span trees into trace events. ``roots=None`` exports (and
    leaves in place) the process buffer of completed root spans.

    Tracks are keyed by ``(trace_id, thread_id)``, NOT the raw thread id:
    two concurrent federated queries served by the same pool thread (or a
    grafted remote subtree whose thread ids collide with local ones) must
    land on separate tracks, and each span's instant events must pin to
    ITS track — raw-thread keying interleaved them (the concurrent-export
    regression in tests/test_obs_federation.py)."""
    if roots is None:
        roots = _trace.recent()
    elif not isinstance(roots, (list, tuple)):
        roots = [roots]
    events = []
    tracks: dict = {}  # (trace_id, thread_id) -> synthetic tid

    def _tid(s) -> int:
        key = (s.trace_id, s.thread_id)
        tid = tracks.get(key)
        if tid is None:
            tid = tracks[key] = len(tracks) + 1
        return tid

    for root in roots:
        for s in root.walk():
            tid = _tid(s)
            events.append(_span_event(s, tid))
            for name, t_ns, attrs in list(s.events):
                # point-in-time span markers (federation member errors,
                # degradation) as Chrome instant events on the SPAN's track
                events.append({
                    "name": name, "ph": "i", "s": "t", "pid": 1,
                    "tid": tid, "ts": t_ns / 1000.0,
                    "args": dict(attrs),
                })
    for (trace_id, thread_id), tid in sorted(tracks.items(),
                                             key=lambda kv: kv[1]):
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": f"{trace_id} thread-{thread_id}"},
        })
    return events


def write_chrome_trace(path: str, roots=None, drain: bool = False) -> int:
    """Write one Perfetto-loadable JSON file; returns the event count.
    ``drain=True`` consumes the process buffer (bench-run semantics)."""
    if roots is None and drain:
        roots = _trace.drain()
    events = chrome_trace_events(roots)
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    return len(events)


# -- Prometheus text exposition ----------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    n = _NAME_RE.sub("_", f"{prefix}_{name}" if prefix else name)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _fmt(v: float) -> str:
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(f) if not f.is_integer() else str(int(f))


def _summary(lines: list, base: str, vals: dict, scale: float, unit: str):
    """One snapshot histogram/timer as a Prometheus summary."""
    name = base + unit
    lines.append(f"# TYPE {name} summary")
    for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
        if key in vals:
            lines.append(
                f'{name}{{quantile="{q}"}} {_fmt(vals[key] * scale)}'
            )
    count = vals.get("count", 0)
    mean = vals.get("mean", vals.get("mean_ms", 0.0))
    lines.append(f"{name}_sum {_fmt(mean * count * scale)}")
    lines.append(f"{name}_count {_fmt(count)}")


def prometheus_text(*registries, prefix: str = "geomesa") -> str:
    """Text exposition for one or more metric registries (duck-typed on
    ``snapshot()``). On a name collision the EARLIEST registry wins and
    later duplicates are dropped — an exposition must never emit the same
    family twice (pass the authoritative registry first)."""
    lines: list[str] = []
    seen: set[str] = set()
    for reg in registries:
        if reg is None:
            continue
        for raw, vals in sorted(reg.snapshot().items()):
            typ = vals.get("type")
            base = _prom_name(raw, prefix)
            if base in seen:
                continue
            seen.add(base)
            if typ == "counter":
                lines.append(f"# TYPE {base}_total counter")
                lines.append(f"{base}_total {_fmt(vals['count'])}")
            elif typ == "gauge":
                lines.append(f"# TYPE {base} gauge")
                lines.append(f"{base} {_fmt(vals['value'])}")
            elif typ == "histogram":
                _summary(lines, base, vals, 1.0, "")
            elif typ == "timer":
                # timers snapshot in ms; Prometheus wants base seconds
                sv = {
                    "count": vals.get("count", 0),
                    "mean": vals.get("mean_ms", 0.0),
                }
                for k in ("p50", "p95", "p99"):
                    if f"{k}_ms" in vals:
                        sv[k] = vals[f"{k}_ms"]
                _summary(lines, base, sv, 1e-3, "_seconds")
    return "\n".join(lines) + "\n"
