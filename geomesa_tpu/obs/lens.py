"""query-lens: retained per-(type, plan-signature) latency history with
trace exemplars, plus the live regression sentinel.

The existing observability planes are point-in-time (Prometheus snapshot,
cost-table means) or per-event (flight ring): none can answer "since
when is signature X slow, and show me one slow trace". This module is the
retained plane:

- :class:`LatencyLens` — per (feature type, plan signature) series, each a
  bounded ring of TIME-BUCKETED latency histograms (fixed log-scale
  ``le`` bin edges, 10 s buckets, 1 h retained by default — the same
  bucketed-deque shape as the SLO engine's burn-rate counters). Each
  bucket also accumulates rows/dispatches and keeps up to
  ``EXEMPLARS_PER_BUCKET`` *trace exemplars*: the (latency, trace_id)
  pairs of the bucket's slowest traced queries — so the tail (p99+) of
  every bucket is one lookup away from its stitched federated span tree
  (``trace.recent()`` → flight dumps). Served at ``GET /api/obs/lens``
  and ``geomesa-tpu obs lens``.
- Prometheus exposition: :meth:`LatencyLens.prometheus_lines` emits TRUE
  histogram families — ``geomesa_lens_latency_ms_bucket`` with cumulative
  ``le`` labels plus ``_sum``/``_count`` under ``# TYPE ... histogram``
  (the summary-style quantile emission in :mod:`obs.export` cannot be
  aggregated across instances; these can).
- :class:`RegressionSentinel` — a background comparator (the
  InvariantSweeper worker pattern, :mod:`obs.audit`) testing each series'
  live window against a rolling reference window and committed BENCH
  baselines. Sustained p50/p99 regression raises an ``A_REGRESSION``
  flight anomaly (rate-limited dump machinery rides for free) and a
  ``geomesa_lens_regression`` gauge.

Overhead discipline: ``observe()`` is on the always-on query path — one
leaf-lock acquisition, a bisect into 15 fixed edges, and a handful of
increments (the <2% cached-jit select bound is gated in scripts/lint.sh).
No jax anywhere (``GEOMESA_TPU_NO_JAX=1`` safe).

Locking: the lens owns ONE leaf lock for the series table + buckets
(metrics tier, docs/concurrency.md) — quantile math and exposition copy
under the lock, format outside it. The sentinel's state is its own leaf.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque

from geomesa_tpu.analysis.contracts import (cache_surface, feedback_sink,
                                            shadow_plane)

__all__ = [
    "HistogramRing", "LatencyLens", "RegressionSentinel", "BUCKET_EDGES_MS",
    "get", "install", "sentinel", "install_sentinel",
]

# fixed log-scale latency bin edges (ms). Fixed — not adaptive — so bucket
# histograms merge across time and across instances by plain addition,
# which is what makes the Prometheus histogram family and the sentinel's
# window quantiles possible. 0.25 ms..10 s covers a cached-jit dispatch
# through a pathological federated fan-out.
BUCKET_EDGES_MS: tuple = (
    0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)
_N_BINS = len(BUCKET_EDGES_MS) + 1  # + the +Inf overflow bin

_BUCKET_S = 10.0  # time-bucket width (matches the SLO engine's counters)
_RING = 360  # buckets retained per series (1 h at 10 s)
_MAX_SERIES = 256  # (type, signature) cardinality valve
EXEMPLARS_PER_BUCKET = 4  # slowest traced queries kept per bucket


class _LensBucket:
    """One time bucket of one series: a latency histogram plus rollups and
    the bucket's slowest traced exemplars. Mutated only under the owning
    lens's lock."""

    __slots__ = ("start", "bins", "count", "sum_ms", "max_ms", "rows",
                 "dispatches", "exemplars")

    def __init__(self, start: float):
        self.start = start
        self.bins = [0] * _N_BINS
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0
        self.rows = 0
        self.dispatches = 0
        # [latency_ms, trace_id, ts] of the bucket's slowest traced
        # queries — replace-min keeps the tail (the p99+ sample IS the
        # bucket max), bounded at EXEMPLARS_PER_BUCKET
        self.exemplars: list = []


class _Series:
    __slots__ = ("buckets",)

    def __init__(self, ring: int = _RING):
        self.buckets: deque = deque(maxlen=ring)


def _quantile(bins: list, count: int, q: float) -> float:
    """Quantile estimate from merged histogram bins: find the bin holding
    the q-th observation, interpolate linearly inside its edge span (the
    overflow bin reports its lower edge — no upper bound to reach for)."""
    if count <= 0:
        return 0.0
    rank = q * count
    cum = 0
    for i, c in enumerate(bins):
        cum += c
        if cum >= rank and c:
            lo = BUCKET_EDGES_MS[i - 1] if i > 0 else 0.0
            if i >= len(BUCKET_EDGES_MS):
                return BUCKET_EDGES_MS[-1]
            hi = BUCKET_EDGES_MS[i]
            frac = (rank - (cum - c)) / c
            return lo + (hi - lo) * frac
    return BUCKET_EDGES_MS[-1]


def _esc(v: str) -> str:
    # text-exposition label escaping (signatures carry ':'s, types can
    # carry arbitrary user strings)
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_le(edge: float) -> str:
    # prometheus convention: integral edges render without the trailing
    # .0 ("le" values must parse as floats either way)
    return str(int(edge)) if float(edge).is_integer() else str(edge)


class HistogramRing:
    """The shared histogram-ring base: the per-key series table, the
    cardinality valve, the time-bucket ring append, the exemplar
    replace-min, and the merged-window histogram math.

    Both lenses — the query lens below and the stream delivery lens
    (:mod:`geomesa_tpu.obs.streamlens`) — are subclasses, so the ring /
    valve / exemplar semantics cannot drift between the two planes.
    Subclasses pick their bucket and series classes via ``_bucket_cls`` /
    ``_series_cls`` (extra ``__slots__`` on top of :class:`_LensBucket`)
    and may override :meth:`_evict_locked` with their own valve policy
    (the query lens drops the longest-idle series; the stream lens drops
    the cheapest and folds it into an ``other`` rollup).

    Locking: ONE leaf lock for the series table + buckets (metrics tier,
    docs/concurrency.md) — every ``*_locked`` helper assumes it is held;
    nothing is called while holding it."""

    _bucket_cls = _LensBucket
    _series_cls = _Series

    def __init__(self, bucket_s: float = _BUCKET_S, ring: int = _RING,
                 max_series: int = _MAX_SERIES, clock=time.time):
        self.bucket_s = float(bucket_s)
        self._ring = ring
        self._max_series = max_series
        self._clock = clock
        self._lock = threading.Lock()  # leaf: series table + buckets
        self._series: dict[tuple, object] = {}
        self.observe_count = 0

    # -- shared machinery (caller holds self._lock) ---------------------------
    def _evict_locked(self) -> None:
        """Cardinality valve: drop the series with the oldest newest-
        bucket (longest idle). Subclasses may override the policy."""
        idle = min(
            self._series,
            key=lambda k: (self._series[k].buckets[-1].start
                           if self._series[k].buckets else 0.0))
        del self._series[idle]

    def _bucket_locked(self, key: tuple, now: float):
        """The series' bucket covering ``now`` (creating series and
        bucket as needed; the valve runs on series creation)."""
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self._max_series:
                self._evict_locked()
            series = self._series[key] = self._series_cls(self._ring)
        start = now - (now % self.bucket_s)
        if series.buckets and series.buckets[-1].start == start:
            return series.buckets[-1]
        b = self._bucket_cls(start)
        series.buckets.append(b)  # deque(maxlen) prunes the ring
        return b

    @staticmethod
    def _exemplar_locked(b, latency_ms: float, trace_id: str,
                         now: float) -> None:
        """Replace-min exemplar keep: the bucket retains its slowest
        ``EXEMPLARS_PER_BUCKET`` traced observations."""
        ex = b.exemplars
        if len(ex) < EXEMPLARS_PER_BUCKET:
            ex.append([latency_ms, trace_id, now])
        else:
            mi = min(range(len(ex)), key=lambda j: ex[j][0])
            if latency_ms > ex[mi][0]:
                ex[mi] = [latency_ms, trace_id, now]

    def _window_locked(self, key: tuple, start_s: float, end_s: float,
                       fold=None) -> tuple:
        """Merge buckets intersecting ``[start_s, end_s)`` →
        ``(bins, count, sum_ms, max_ms)``; ``fold(bucket)`` runs per
        merged bucket so subclasses accumulate their extra counters."""
        bins = [0] * _N_BINS
        count = 0
        sum_ms = 0.0
        max_ms = 0.0
        series = self._series.get(key)
        if series is not None:
            for b in series.buckets:
                if b.start + self.bucket_s > start_s and b.start < end_s:
                    for i, c in enumerate(b.bins):
                        bins[i] += c
                    count += b.count
                    sum_ms += b.sum_ms
                    max_ms = max(max_ms, b.max_ms)
                    if fold is not None:
                        fold(b)
        return bins, count, sum_ms, max_ms

    def _exemplar_rows_locked(self, key: tuple) -> list:
        series = self._series.get(key)
        rows = []
        if series is not None:
            for b in series.buckets:
                for ms, tid, ts in b.exemplars:
                    rows.append({"latency_ms": round(ms, 3),
                                 "trace_id": tid, "ts": ts,
                                 "bucket": b.start})
        return rows

    def series_keys(self) -> list:
        with self._lock:
            return list(self._series)


@cache_surface(name="query-lens", keyed_by="type_name", purge=("forget",))
class LatencyLens(HistogramRing):
    """The retained profiling plane: bounded time-bucketed latency
    histogram rings per (type, plan signature), with trace exemplars.
    Series for a dropped/renamed type are purged via :meth:`forget`
    (``DataStore._purge_type_name``)."""

    # -- the hot path ---------------------------------------------------------
    @feedback_sink
    def observe(self, type_name: str, signature: str, latency_ms: float,
                rows: int = 0, dispatches: int = 0, trace_id: str = "",
                now: float | None = None) -> None:
        """One completed query. Always-on: one lock, one bisect, a few
        increments; the exemplar replace-min only runs for traced queries
        landing in a bucket's current top-``EXEMPLARS_PER_BUCKET``."""
        if now is None:
            now = self._clock()
        key = (type_name, signature)
        bin_i = bisect_left(BUCKET_EDGES_MS, latency_ms)
        with self._lock:
            b = self._bucket_locked(key, now)
            b.bins[bin_i] += 1
            b.count += 1
            b.sum_ms += latency_ms
            if latency_ms > b.max_ms:
                b.max_ms = latency_ms
            b.rows += rows
            b.dispatches += dispatches
            if trace_id:
                self._exemplar_locked(b, latency_ms, trace_id, now)
            self.observe_count += 1

    # -- maintenance ----------------------------------------------------------
    def forget(self, type_name: str) -> None:
        """Purge every series for ``type_name`` (schema delete/rename)."""
        with self._lock:
            for key in [k for k in self._series if k[0] == type_name]:
                del self._series[key]

    # -- read surfaces --------------------------------------------------------
    def window_stats(self, type_name: str, signature: str,
                     start_s: float, end_s: float) -> dict:
        """Merged stats over buckets intersecting ``[start_s, end_s)``:
        count / sum / p50 / p95 / p99 / max / rows / dispatches. The
        sentinel's comparison primitive."""
        extra = {"rows": 0, "dispatches": 0}

        def fold(b):
            extra["rows"] += b.rows
            extra["dispatches"] += b.dispatches

        with self._lock:
            bins, count, sum_ms, max_ms = self._window_locked(
                (type_name, signature), start_s, end_s, fold)
        rows = extra["rows"]
        dispatches = extra["dispatches"]
        return {
            "count": count,
            "sum_ms": sum_ms,
            "mean_ms": sum_ms / count if count else 0.0,
            "p50_ms": _quantile(bins, count, 0.5),
            "p95_ms": _quantile(bins, count, 0.95),
            "p99_ms": _quantile(bins, count, 0.99),
            "max_ms": max_ms,
            "rows": rows,
            "dispatches": dispatches,
        }

    def exemplars(self, type_name: str, signature: str,
                  limit: int = 16) -> list:
        """The series' retained exemplars, slowest first:
        ``{latency_ms, trace_id, ts, bucket}`` — each trace_id resolves
        against ``trace.recent()`` (and flight dumps) to the stitched
        span tree."""
        with self._lock:
            rows = self._exemplar_rows_locked((type_name, signature))
        rows.sort(key=lambda r: -r["latency_ms"])
        return rows[:limit]

    def snapshot(self, limit: int = 50, window_s: float = 300.0,
                 type_name: str | None = None) -> dict:
        """The ``/api/obs/lens`` payload: per-series live-window quantiles,
        the retained bucket series (start/count/mean/max), and the top
        exemplars."""
        now = self._clock()
        with self._lock:
            keys = [k for k in self._series
                    if type_name is None or k[0] == type_name]
        entries = []
        for t, sig in keys:
            win = self.window_stats(t, sig, now - window_s, now + 1.0)
            with self._lock:
                series = self._series.get((t, sig))
                buckets = [
                    {"ts": b.start, "count": b.count,
                     "mean_ms": round(b.sum_ms / b.count, 3) if b.count else 0.0,
                     "max_ms": round(b.max_ms, 3),
                     "rows": b.rows, "dispatches": b.dispatches}
                    for b in (series.buckets if series is not None else ())
                ]
            entries.append({
                "type": t,
                "signature": sig,
                "window_s": window_s,
                "window": {k: (round(v, 3) if isinstance(v, float) else v)
                           for k, v in win.items()},
                "buckets": buckets[-64:],
                "exemplars": self.exemplars(t, sig, limit=8),
            })
        entries.sort(key=lambda e: -e["window"]["count"])
        return {
            "entries": entries[:limit],
            "series": len(keys),
            "bucket_s": self.bucket_s,
            "observe_count": self.observe_count,
        }

    # -- prometheus exposition ------------------------------------------------
    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        """TRUE histogram families over the retained ring: per series,
        cumulative ``_bucket`` counts with ``le`` labels (``+Inf`` bucket
        equals ``_count``), plus ``_sum``/``_count`` — and a companion
        ``_dispatches_total`` counter. Empty when nothing observed."""
        with self._lock:
            rows = []
            for (t, sig), series in self._series.items():
                bins = [0] * _N_BINS
                count = 0
                sum_ms = 0.0
                dispatches = 0
                for b in series.buckets:
                    for i, c in enumerate(b.bins):
                        bins[i] += c
                    count += b.count
                    sum_ms += b.sum_ms
                    dispatches += b.dispatches
                rows.append((t, sig, bins, count, sum_ms, dispatches))
        if not rows:
            return []
        name = f"{prefix}_lens_latency_ms"
        hist = [f"# TYPE {name} histogram"]
        disp = [f"# TYPE {prefix}_lens_dispatches_total counter"]
        for t, sig, bins, count, sum_ms, dispatches in rows:
            labels = f'type="{_esc(t)}",signature="{_esc(sig)}"'
            cum = 0
            for i, edge in enumerate(BUCKET_EDGES_MS):
                cum += bins[i]
                hist.append(
                    f'{name}_bucket{{{labels},le="{_fmt_le(edge)}"}} {cum}')
            hist.append(f'{name}_bucket{{{labels},le="+Inf"}} {count}')
            hist.append(f"{name}_sum{{{labels}}} {sum_ms:.6g}")
            hist.append(f"{name}_count{{{labels}}} {count}")
            disp.append(
                f"{prefix}_lens_dispatches_total{{{labels}}} {dispatches}")
        return hist + disp

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        lines = self.prometheus_lines(prefix)
        return "\n".join(lines) + "\n" if lines else ""


# -- regression sentinel ------------------------------------------------------

@shadow_plane
class RegressionSentinel:
    """Background live-vs-reference latency comparator (the
    InvariantSweeper worker shape: ``start()``/``close()`` around a
    daemon thread, ``evaluate_once()`` for tests and the CLI).

    Per evaluation, for every lens series with enough live traffic:

    - live window = the trailing ``live_window_s``;
    - reference = the ``ref_window_s`` immediately before it (rolling);
    - baseline = a committed per-signature p50 (``load_baselines`` — the
      BENCH rounds' per-config medians).

    Regression = live p50 or p99 above ``factor`` × reference (or
    ``factor`` × baseline). ``sustain`` consecutive regressed evaluations
    raise ONE ``A_REGRESSION`` flight anomaly per episode (the recorder's
    dump rate-limit rides along) and latch the
    ``geomesa_lens_regression`` gauge until the series recovers.

    Evaluations run in audit shadow: sentinel reads must never train the
    cost table, bill a tenant, or re-enter the lens."""

    def __init__(self, lens: LatencyLens | None = None,
                 interval_s: float = 30.0, live_window_s: float = 60.0,
                 ref_window_s: float = 600.0, factor: float = 1.5,
                 min_live: int = 16, min_ref: int = 16, sustain: int = 1,
                 clock=time.time):
        self._lens = lens
        self.interval_s = interval_s
        self.live_window_s = live_window_s
        self.ref_window_s = ref_window_s
        self.factor = factor
        self.min_live = min_live
        self.min_ref = min_ref
        self.sustain = max(1, sustain)
        self._clock = clock
        self._lock = threading.Lock()  # leaf: streaks + alarms + baselines
        self._baselines: dict[tuple[str, str], float] = {}
        self._streaks: dict[tuple[str, str], int] = {}
        self._alarms: dict[tuple[str, str], dict] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.eval_count = 0
        self.regressions_total = 0

    @property
    def lens(self) -> LatencyLens:
        return self._lens if self._lens is not None else get()

    def load_baselines(self, baselines: dict) -> int:
        """Install committed reference medians: ``{"type:signature":
        p50_ms}`` (or ``{"entries": [{"type", "signature", "p50_ms"}]}``,
        the BENCH sidecar shape). Returns the count installed."""
        rows: dict[tuple[str, str], float] = {}
        if "entries" in baselines and isinstance(baselines["entries"], list):
            for e in baselines["entries"]:
                rows[(str(e["type"]), str(e["signature"]))] = float(e["p50_ms"])
        else:
            for k, v in baselines.items():
                t, _, sig = str(k).partition(":")
                rows[(t, sig)] = float(v)
        with self._lock:
            self._baselines.update(rows)
        return len(rows)

    # -- evaluation -----------------------------------------------------------
    def evaluate_once(self, now: float | None = None) -> list[dict]:
        """One comparator pass; returns the alarms RAISED this pass (an
        already-latched alarm does not re-raise). Safe under any caller —
        wraps itself in audit shadow."""
        from geomesa_tpu.obs import audit as _audit

        with _audit.shadow():
            return self._evaluate(self._clock() if now is None else now)

    def _evaluate(self, now: float) -> list[dict]:
        lens = self.lens
        raised = []
        live_lo = now - self.live_window_s
        ref_lo = live_lo - self.ref_window_s
        for t, sig in lens.series_keys():
            live = lens.window_stats(t, sig, live_lo, now + 1.0)
            if live["count"] < self.min_live:
                continue  # not enough live traffic to judge — hold state
            ref = lens.window_stats(t, sig, ref_lo, live_lo)
            with self._lock:
                base = self._baselines.get((t, sig))
            causes = []
            if ref["count"] >= self.min_ref:
                if live["p50_ms"] > self.factor * ref["p50_ms"] > 0:
                    causes.append(
                        ("p50_vs_ref", live["p50_ms"], ref["p50_ms"]))
                if live["p99_ms"] > self.factor * ref["p99_ms"] > 0:
                    causes.append(
                        ("p99_vs_ref", live["p99_ms"], ref["p99_ms"]))
            if base is not None and live["p50_ms"] > self.factor * base > 0:
                causes.append(("p50_vs_baseline", live["p50_ms"], base))
            key = (t, sig)
            if not causes:
                with self._lock:
                    self._streaks.pop(key, None)
                    self._alarms.pop(key, None)
                continue
            with self._lock:
                streak = self._streaks.get(key, 0) + 1
                self._streaks[key] = streak
                already = key in self._alarms
                fire = streak >= self.sustain and not already
                if fire:
                    kind, live_v, ref_v = causes[0]
                    alarm = {
                        "type": t, "signature": sig, "cause": kind,
                        "live_ms": round(live_v, 3),
                        "ref_ms": round(ref_v, 3),
                        "factor": round(live_v / ref_v, 3) if ref_v else 0.0,
                        "live_count": live["count"], "ts": now,
                    }
                    self._alarms[key] = alarm
                    self.regressions_total += 1
            if fire:
                raised.append(alarm)
                self._raise_anomaly(alarm)
        with self._lock:
            self.eval_count += 1
        return raised

    def _raise_anomaly(self, alarm: dict) -> None:
        # the alert path: one A_REGRESSION flight record per episode (the
        # recorder's dump throttle bounds file output under a storm).
        # flight.record is the operator surface, not a feedback sink — an
        # alert raised from shadow is the whole point.
        from geomesa_tpu.obs import flight as _flight

        _flight.record(
            "lens.sentinel", alarm["type"], source="sentinel",
            plan=(f"{alarm['cause']}: live {alarm['live_ms']:.3g} ms vs "
                  f"ref {alarm['ref_ms']:.3g} ms "
                  f"({alarm['factor']:.2f}x, n={alarm['live_count']})"),
            latency_ms=alarm["live_ms"],
            plan_signature=alarm["signature"],
            anomalies=(_flight.A_REGRESSION,),
        )

    # -- worker ---------------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="geomesa-lens-sentinel", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception:  # pragma: no cover — the sentinel must not die
                pass

    # -- read surfaces --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "alarms": list(self._alarms.values()),
                "eval_count": self.eval_count,
                "regressions_total": self.regressions_total,
                "baselines": len(self._baselines),
                "factor": self.factor,
                "live_window_s": self.live_window_s,
                "ref_window_s": self.ref_window_s,
                "running": self._thread is not None,
            }

    def prometheus_lines(self, prefix: str = "geomesa") -> list[str]:
        with self._lock:
            alarms = list(self._alarms.values())
            total = self.regressions_total
        out = [f"# TYPE {prefix}_lens_regression gauge"]
        for a in alarms:
            out.append(
                f'{prefix}_lens_regression{{type="{_esc(a["type"])}",'
                f'signature="{_esc(a["signature"])}",'
                f'cause="{_esc(a["cause"])}"}} 1')
        out.append(f"# TYPE {prefix}_lens_regressions_total counter")
        out.append(f"{prefix}_lens_regressions_total {total}")
        return out

    def prometheus_text(self, prefix: str = "geomesa") -> str:
        return "\n".join(self.prometheus_lines(prefix)) + "\n"


# process-wide singletons (tests swap with install()/install_sentinel())
_lens = LatencyLens()
_sentinel = RegressionSentinel()


def get() -> LatencyLens:
    """The process-wide lens."""
    return _lens


def install(lens: LatencyLens) -> LatencyLens:
    """Swap the process lens (tests); returns the previous one."""
    global _lens
    prev, _lens = _lens, lens
    return prev


def sentinel() -> RegressionSentinel:
    """The process-wide regression sentinel (not started by default;
    servers opt in via ``start()``)."""
    return _sentinel


def install_sentinel(s: RegressionSentinel) -> RegressionSentinel:
    """Swap the process sentinel (tests); returns the previous one —
    callers own closing the outgoing worker."""
    global _sentinel
    prev, _sentinel = _sentinel, s
    return prev
