"""Hierarchical trace spans with ContextVar propagation (the obs core).

The per-query timeline the reference never had: every stage of
``QueryPlanner.runQuery`` (plan → range decomposition → device dispatch →
refine → reduce → serialize) opens a :class:`Span`; spans nest through a
``contextvars.ContextVar``, so propagation is correct across the threaded
web server's request threads and the watchdog's scan worker threads
(``utils.timeouts.run_with_timeout`` copies the context into its worker)
without any explicit plumbing.

Zero-overhead contract: with tracing disabled, :func:`span` returns a
shared no-op context manager after one module-global check and one
ContextVar read — no allocation, no clock read, and (critically) no jax
import anywhere in this module, so ``GEOMESA_TPU_NO_JAX=1`` keeps working.
The bound is asserted by ``tests/test_obs.py``.

Enable globally with :func:`enable` (or ``GEOMESA_TPU_TRACE=<path>`` in the
environment — bench.py's ``--trace`` sets it), or per-call-tree with
:func:`collect` (what ``DataStore.explain(..., analyze=True)`` uses).
Completed root spans land in a bounded in-memory buffer; exporters
(:mod:`geomesa_tpu.obs.export`) turn them into Chrome/Perfetto trace JSON.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar

__all__ = [
    "Span", "StageTimeline", "span", "collect", "current", "annotate",
    "enable", "disable", "enabled", "event", "recent", "drain", "NOOP",
]

_enabled = False  # module-global fast flag (the one check on the no-op path)
_forced: ContextVar[bool] = ContextVar("geomesa_obs_forced", default=False)
_current: ContextVar["Span | None"] = ContextVar("geomesa_obs_span", default=None)

_buffer_lock = threading.Lock()
_MAX_TRACES = 512  # completed root spans retained (ring buffer)
_traces: deque = deque(maxlen=_MAX_TRACES)

# span/trace ids: a per-process random salt + cheap counter — unique within
# and across processes without paying uuid4 per span
_salt = os.urandom(4).hex()
_ids = itertools.count(1)


class Span:
    """One timed stage. Context manager; children attach automatically via
    the ContextVar, so concurrent requests build disjoint trees."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attrs", "children",
        "events", "t0_ns", "t1_ns", "thread_id", "_token",
    )

    def __init__(self, name: str, attrs: dict, parent: "Span | None"):
        self.name = name
        self.attrs = attrs
        self.children: list[Span] = []
        # point-in-time markers inside this span's window — (name, t_ns,
        # attrs) — the federation layer's member-error/degradation record
        self.events: list[tuple] = []
        sid = next(_ids)
        self.span_id = f"{_salt}-{sid:x}"
        if parent is None:
            self.trace_id = f"{_salt}-t{sid:x}"
            self.parent_id = ""
        else:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        self.t0_ns = 0
        self.t1_ns = 0
        self.thread_id = threading.get_ident()
        self._token = None

    # -- timing ---------------------------------------------------------------
    @property
    def duration_ms(self) -> float:
        end = self.t1_ns if self.t1_ns else time.perf_counter_ns()
        return (end - self.t0_ns) / 1e6

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs) -> "Span":
        """Record a point-in-time marker on this span (list.append is
        atomic under the GIL; exporters snapshot via list())."""
        self.events.append((name, time.perf_counter_ns(), attrs))
        return self

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        self.t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.t1_ns = time.perf_counter_ns()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        parent = None
        if self._token is not None:
            prev = self._token.old_value  # Token.MISSING when var was unset
            _current.reset(self._token)
            self._token = None
            if isinstance(prev, Span):
                parent = prev
        if parent is not None:
            # list.append is atomic under the GIL; an abandoned (timed-out)
            # scan worker may attach late — exporters snapshot via list()
            parent.children.append(self)
        else:
            with _buffer_lock:
                _traces.append(self)

    # -- introspection --------------------------------------------------------
    def walk(self):
        """This span and every descendant, depth-first."""
        yield self
        for c in list(self.children):
            yield from c.walk()

    def find(self, name: str) -> "list[Span]":
        return [s for s in self.walk() if s.name == name]

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return (
            f"Span({self.name!r}, {self.duration_ms:.3f} ms, "
            f"children={len(self.children)})"
        )


class _NoopSpan:
    """The disabled path: one shared instance, every method a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None

    def set(self, **attrs):
        return self

    def event(self, name, **attrs):
        return self

    # mimic the Span read surface so call sites never branch on type
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = ""
    attrs: dict = {}
    children: list = []
    events: list = []
    duration_ms = 0.0

    def walk(self):
        return iter(())

    def find(self, name):
        return []


NOOP = _NoopSpan()


def active() -> bool:
    """True when spans are being recorded on THIS context (global enable or
    an enclosing :func:`collect`)."""
    return _enabled or _forced.get()


def enabled() -> bool:
    return _enabled


def enable(jax_telemetry: bool = True) -> None:
    """Turn tracing on process-wide. ``jax_telemetry`` also installs the
    jax.monitoring compile listeners — guarded so a ``GEOMESA_TPU_NO_JAX=1``
    process never imports jax from here."""
    global _enabled
    _enabled = True
    if jax_telemetry:
        from geomesa_tpu.obs import jaxmon

        jaxmon.install()


def disable() -> None:
    global _enabled
    _enabled = False


def span(name: str, **attrs) -> "Span | _NoopSpan":
    """Open a child span of the current context (a root when none).

    Usage: ``with obs.span("plan", index="z3"): ...`` — returns the shared
    no-op singleton when tracing is off.
    """
    if not _enabled and not _forced.get():
        return NOOP
    return Span(name, attrs, _current.get())


def current() -> "Span | None":
    """The innermost live span on this context, or None."""
    return _current.get()


def annotate(**attrs) -> None:
    """Attach attributes to the innermost live span (no-op when untraced)."""
    sp = _current.get()
    if sp is not None:
        sp.attrs.update(attrs)


def event(name: str, **attrs) -> None:
    """Record a point-in-time marker on the innermost live span (no-op
    when untraced) — e.g. a federation member error inside a query span."""
    sp = _current.get()
    if sp is not None:
        sp.event(name, **attrs)


@contextmanager
def collect(name: str = "trace", **attrs):
    """Force-trace one call tree regardless of the global flag and yield its
    root span (inspect ``root.children`` after the block). This is the
    ``EXPLAIN ANALYZE`` mechanism: per-query opt-in with zero ambient cost."""
    tok = _forced.set(True)
    root = Span(name, attrs, _current.get())
    try:
        with root:
            yield root
    finally:
        _forced.reset(tok)


def recent() -> list:
    """Completed root spans, oldest first (non-destructive)."""
    with _buffer_lock:
        return list(_traces)


def drain() -> list:
    """Completed root spans, clearing the buffer (exporter consumption)."""
    with _buffer_lock:
        out = list(_traces)
        _traces.clear()
    return out


class StageTimeline:
    """A root span flattened to the stage decomposition the acceptance
    contract names: direct children as (stage, ms) pairs plus an ``other``
    residual. Child durations are CLAMPED to the root's own window —
    a still-open child (an abandoned, timed-out scan worker whose span
    never closed) or one attached late cannot push coverage past wall —
    so for the sequential query pipeline stage durations sum to wall time
    by construction (``other`` absorbs untraced gaps)."""

    def __init__(self, root: Span):
        self.root = root
        self.wall_ms = root.duration_ms
        root_end = root.t1_ns if root.t1_ns else time.perf_counter_ns()
        stages = []
        for c in list(root.children):
            child_end = c.t1_ns if c.t1_ns else root_end  # still open
            lo = max(c.t0_ns, root.t0_ns)
            hi = min(child_end, root_end)
            stages.append((c.name, max(hi - lo, 0) / 1e6))
        covered = sum(ms for _, ms in stages)
        other = self.wall_ms - covered
        if other > 1e-6:
            stages.append(("other", other))
        self.stages = stages

    def stage_ms(self, name: str) -> float:
        return sum(ms for n, ms in self.stages if n == name)

    def render(self) -> str:
        lines = [
            f"Stage timeline ({self.wall_ms:.3f} ms wall, "
            f"trace {self.root.trace_id}):"
        ]
        for n, ms in self.stages:
            pct = 100.0 * ms / self.wall_ms if self.wall_ms else 0.0
            lines.append(f"  {n:<12s} {ms:10.3f} ms  {pct:5.1f}%")
        return "\n".join(lines)

    __str__ = render


# bench.py --trace / operator opt-in without code: enabling via environment
# here means child worker processes (bench driver mode) inherit tracing
if os.environ.get("GEOMESA_TPU_TRACE"):
    enable()
